"""Headline benchmarks — all three BASELINE.json metrics.

1. ``logistic_glm_rows_per_sec`` (primary): fused value+gradient throughput
   of the sparse logistic objective — the hot op behind BASELINE's "1B-row
   logistic GLM epoch time" (epoch seconds = 1e9 / rows_per_sec per
   objective evaluation; SURVEY.md §3.1 hot loop).
2. ``game_cd_iters_per_sec``: full GAME coordinate-descent iterations
   (fixed effect + long-tailed per-user random effect) per second on a
   MovieLens-shaped synthetic — 10⁵ entities, zipf-tailed row counts
   (BASELINE metric "GAME coord-descent iters/sec").
3. ``glm_driver_wall_seconds``: end-to-end legacy GLM driver wall-clock
   (read → index → summarize → train λ grid → validate → select → write) on
   an a1a-shaped dataset (BASELINE config 1).

MEASUREMENT METHODOLOGY: iterations are chained inside ONE jitted
``fori_loop`` and the clock stops only after a small slice of the result is
read back to host (``jax.block_until_ready`` returns before compute
finishes on this TPU transport — round 1's committed 29.45 M rows/s was a
dispatch-rate artifact of that; it lives on only in
bench_baseline.json["history"]).

CROSS-SESSION COMPARISON (round 3): the chip's effective stream rate
drifts 24-90 GB/s between sessions for identical code, so the PRIMARY
``vs_baseline`` is bandwidth-normalized — (rows/s ÷ this session's
``chip_stream_gbps``) over the same quotient recorded in
bench_baseline.json (round-2 measured numbers, honest methodology).  The
raw rows/s ratio is still reported as ``extra.vs_baseline_raw``.  GAME CD
is timed as the median over ``N_REPS`` runs of ≥3 iterations each with a
spread report; the driver metric reports COLD (fresh compilation cache)
and WARM (persistent-cache hit) wall seconds separately.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"} —
the primary metric in the required fields, the other metrics under "extra"
with their own vs_baseline ratios.

Env knobs: BENCH_SMALL=1 shrinks every workload (CI/smoke); BENCH_ONLY=
glm|game|driver|stream|serving|freshness|tuning|solvers|chaos|telemetry|
tracing|analysis|cluster runs a single section (tracing: trace-
propagation overhead A/B, gated <= 1% of the closed-loop serving
baseline; cluster: the 3-host control-plane drill as a gate plus the
checksum-verified snapshot-fetch MB/s).
"""

import json
import os
import sys
import tempfile
import time

import numpy as np


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()

SMALL = os.environ.get("BENCH_SMALL") == "1"
ONLY = os.environ.get("BENCH_ONLY", "")

N_ROWS = 1 << (16 if SMALL else 20)
N_FEATURES = 1 << 13
NNZ_PER_ROW = 32
N_CHAINED = 10  # objective evals chained inside one jit
N_REPS = 3  # timed repetitions (min taken)

GAME_ENTITIES = 2_000 if SMALL else 100_000
GAME_FIXED_FEATURES = 512
GAME_FIXED_NNZ = 8
GAME_RE_DIM = 8
GAME_TIMED_ITERS = 3   # iterations per timed run (VERDICT r2: >=3)
GAME_TIMED_RUNS = 5    # median over this many runs, spread reported
GAME_BUCKET_GROWTH = 4.0  # consolidate the zipf tail: ~5 compiled shapes
GAME_ROW_CAP = 128

STREAM_CHUNKS = 4  # streaming A/B: resident vs 4-chunk double-buffered
STREAM_OS_CHUNKS = 16  # oversubscription leg: store sized past HBM budget
STREAM_OS_HOT_FRAC = 0.7  # hot working-set budget as fraction of wire store

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def _read_sync(x) -> None:
    """Force true completion: read one element back to host."""
    np.asarray(x.ravel()[0:1])


def bench_chip_stream() -> float:
    """Chip calibration: GB/s of a plain XLA elementwise reduce over ~256 MB.

    The tunneled TPU's effective streaming rate varies ~2x between
    sessions (measured 47 vs ~90 GB/s on different days for the SAME
    committed code).  This number lets rows/s results be normalized
    across sessions; the sparse kernels are bandwidth-bound, so rows/s
    scales ~linearly with it.
    """
    import jax
    import jax.numpy as jnp

    x = jnp.ones((64 << 20,), jnp.float32)  # 256 MB

    @jax.jit
    def chain(x):
        def body(i, acc):
            return acc + jnp.sum(x * (1.0 + 1e-12 * acc))
        return jax.lax.fori_loop(0, 10, body, jnp.zeros((), jnp.float32))

    r = chain(x)
    _read_sync(r)
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        r = chain(x)
        _read_sync(r)
        best = min(best, (time.perf_counter() - t0) / 10)
    return x.nbytes / best / 1e9


def bench_glm_throughput() -> dict:
    """rows/s of the fused sparse logistic value+grad (primary metric),
    plus the achieved HBM bandwidth of one pass for roofline tracking."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.objective import GlmObjective

    rng = np.random.default_rng(0)
    nnz = N_ROWS * NNZ_PER_ROW
    rows = np.repeat(np.arange(N_ROWS, dtype=np.int64), NNZ_PER_ROW)
    cols = rng.integers(0, N_FEATURES, size=nnz).astype(np.int64)
    values = rng.normal(size=nnz).astype(np.float32)
    w_true = (rng.normal(size=N_FEATURES) *
              (rng.uniform(size=N_FEATURES) < 0.2)).astype(np.float32)
    margins_true = np.zeros(N_ROWS, np.float32)
    np.add.at(margins_true, rows, values * w_true[cols.astype(np.int64)])
    y = (rng.uniform(size=N_ROWS) < 1 / (1 + np.exp(-margins_true))).astype(
        np.float32)

    if jax.default_backend() == "tpu":
        from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix

        X = build_pallas_matrix(rows, cols, values, N_ROWS, N_FEATURES)
    else:
        from photon_ml_tpu.ops.sparse import from_coo

        X = from_coo(rows, cols, values, N_ROWS, N_FEATURES)

    data = jax.device_put(GlmData(
        features=X,
        labels=jnp.asarray(y),
        weights=jnp.ones(N_ROWS, jnp.float32),
        offsets=jnp.zeros(N_ROWS, jnp.float32),
    ))
    obj = GlmObjective(losses.logistic)

    # Data is an ARGUMENT, not a closure constant: closed-over arrays get
    # baked into the HLO as literals (overflows the remote-compile transport).
    @jax.jit
    def chain(w, data):
        def body(i, w):
            val, grad = obj.value_and_grad(w, data, l2_weight=1.0)
            return w - 1e-4 * grad
        return jax.lax.fori_loop(0, N_CHAINED, body, w)

    _log("glm: compiling throughput chain...")
    w = jnp.zeros(N_FEATURES, jnp.float32)
    out = chain(w, data)
    _read_sync(out)  # compile + prime true sync

    best = np.inf
    for i in range(N_REPS):
        wp = jnp.full((N_FEATURES,), np.float32(1e-3 * (i + 1)))
        _read_sync(wp)
        t0 = time.perf_counter()
        out = chain(wp, data)
        _read_sync(out)  # force real completion
        best = min(best, (time.perf_counter() - t0) / N_CHAINED)

    # Roofline accounting (VERDICT r4 #7): bytes one fused value+grad
    # pass must move through HBM — the layout leaves (which ALREADY hold
    # separate forward and backward orientations, each read once:
    # margins ride the f_* grids, the gradient scatter the b_* grids),
    # the three per-row columns, and the w/grad vectors (reads + the
    # fori body's update) — over the measured pass time.  Divided by the
    # same-session chip_stream_gbps calibration this tracks the kernels'
    # bandwidth-bound fraction per round (ops/README.md's ablation
    # measured ~84%).  Both sides are PROXIES (the calibration is a
    # plain elementwise reduce), so treat the ratio as a round-over-
    # round regression tracker, not an absolute roofline percentage —
    # values near/above 1 mean the packed kernels stream at least as
    # fast as plain XLA.
    x_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(X))
    bytes_per_pass = (
        x_bytes + 3 * (N_ROWS * 4) + 5 * (N_FEATURES * 4)
    )
    return {
        "rows_per_sec": N_ROWS / best,
        "achieved_gbps": bytes_per_pass / best / 1e9,
    }


def bench_game_cd() -> dict:
    """Full coordinate-descent iterations per second on a MovieLens-shaped
    synthetic: one fixed effect over sparse global features + one per-user
    random effect with a zipf long tail of rows per user."""
    import scipy.sparse as sp

    from photon_ml_tpu.game.coordinates import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.data import (
        FixedEffectDataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext

    rng = np.random.default_rng(1)
    # Long-tailed rows per entity (MovieLens-like): zipf, capped so bucket
    # count (= compile count) stays bounded.
    sizes = np.minimum(rng.zipf(1.8, GAME_ENTITIES), GAME_ROW_CAP)
    n = int(sizes.sum())
    users = np.repeat(
        np.array([f"u{i}" for i in range(GAME_ENTITIES)], dtype=object),
        sizes,
    )
    perm = rng.permutation(n)
    users = users[perm]

    nnzf = n * GAME_FIXED_NNZ
    Xg = sp.csr_matrix(
        (rng.normal(size=nnzf).astype(np.float32),
         (np.repeat(np.arange(n, dtype=np.int64), GAME_FIXED_NNZ),
          rng.integers(0, GAME_FIXED_FEATURES, size=nnzf))),
        shape=(n, GAME_FIXED_FEATURES),
    )
    Xu = sp.csr_matrix(rng.normal(size=(n, GAME_RE_DIM)).astype(np.float32))
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = np.ones(n, np.float32)

    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )
    fixed = FixedEffectCoordinate(
        "fixed",
        FixedEffectDataset(data=make_glm_data(Xg, y), n_global_rows=n),
        "logistic", opt, reg_weight=1.0,
    )
    _log(f"game: {n} rows, {GAME_ENTITIES} entities; grouping...")
    re_ds = build_random_effect_dataset(
        users, Xu, y, weights, bucket_growth=GAME_BUCKET_GROWTH
    )
    _log(f"game: {len(re_ds.blocks)} buckets "
         f"{[(b.n_entities, b.rows_per_entity) for b in re_ds.blocks]}")
    re = RandomEffectCoordinate(
        "per_user", re_ds,
        "logistic", opt, reg_weight=1.0, entity_key="userId",
    )
    cd = CoordinateDescent([fixed, re])

    import jax.numpy as jnp

    base = jnp.zeros(n, jnp.float32)
    _log("game: warmup iteration (compiles every bucket shape)...")
    warm = cd.run(base, n_iterations=1)  # warmup: compiles every bucket shape
    _read_sync(warm.scores["per_user"])
    # One untimed run at the TIMED shape: the first multi-iteration run
    # after compile pays allocator/pipeline warm-in (~2x a steady rep —
    # it alone put >100% spread on the 5-rep sample), steady state after.
    _read_sync(cd.run(base, n_iterations=GAME_TIMED_ITERS).scores["per_user"])
    _log("game: warmup done; timing...")

    # Median over GAME_TIMED_RUNS runs of GAME_TIMED_ITERS iterations each,
    # with the within-session spread reported (the chip stream rate drifts
    # even within a session; 1-iteration best-of-2 carried error bars
    # comparable to round-over-round gains — VERDICT r2).
    per_iter = []
    for r in range(GAME_TIMED_RUNS):
        t0 = time.perf_counter()
        result = cd.run(base, n_iterations=GAME_TIMED_ITERS)
        _read_sync(result.scores["per_user"])
        per_iter.append((time.perf_counter() - t0) / GAME_TIMED_ITERS)
    med = float(np.median(per_iter))
    spread_pct = 100.0 * (max(per_iter) - min(per_iter)) / med
    _log(f"game: median {med:.3f}s/iter over {GAME_TIMED_RUNS}x"
         f"{GAME_TIMED_ITERS} iters (spread {spread_pct:.1f}%)")

    # Per-coordinate breakdown: one manual pass per coordinate with a sync
    # after each update (the headline number above keeps the production
    # batched-readback path; this is diagnostic only).
    states = {c.name: warm.states[c.name] for c in cd.coordinates}
    scores = dict(warm.scores)
    total = base
    for s in scores.values():
        total = total + s
    breakdown = {}
    for coord in cd.coordinates:
        best_c = np.inf
        for _ in range(2):
            offsets = total - scores[coord.name]
            t0 = time.perf_counter()
            st = coord.train(offsets, warm_state=states[coord.name])
            sc = coord.score(st)
            _read_sync(sc)
            best_c = min(best_c, time.perf_counter() - t0)
        breakdown[coord.name] = round(best_c, 3)
    _log(f"game: per-coordinate seconds {breakdown}")
    return {
        "iters_per_sec": 1.0 / med,
        "spread_pct": round(spread_pct, 1),
        "coordinate_seconds": breakdown,
    }


def bench_game_multi_re() -> dict:
    """BASELINE config 5's shape at chip scale: coordinate descent over
    fixed + THREE random effects (user + item + context, MovieLens-like
    geometry — zipf-tailed users and items, few heavy contexts with the
    active-set cap exercising the active/passive split).  This is the
    flagship multi-random-effect number the north star cares about;
    until round 5 it only ran in CPU tests and the dryrun."""
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.game.coordinates import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.data import (
        FixedEffectDataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext

    rng = np.random.default_rng(3)
    sizes = np.minimum(rng.zipf(1.8, GAME_ENTITIES), GAME_ROW_CAP)
    n = int(sizes.sum())
    users = np.repeat(
        np.array([f"u{i}" for i in range(GAME_ENTITIES)], dtype=object),
        sizes,
    )[rng.permutation(n)]
    n_items = max(2, GAME_ENTITIES // 5)
    item_sizes = np.minimum(rng.zipf(1.5, n_items), 4 * GAME_ROW_CAP)
    # Each row draws its item from the zipf-weighted pool (with
    # replacement), giving items a matching long-tailed row distribution.
    item_pool = np.repeat(
        np.array([f"i{i}" for i in range(n_items)], dtype=object),
        item_sizes,
    )
    items = item_pool[rng.integers(0, len(item_pool), size=n)]
    n_ctx = 200
    contexts = np.array(
        [f"c{rng.integers(n_ctx)}" for _ in range(n)], dtype=object
    )

    nnzf = n * GAME_FIXED_NNZ
    Xg = sp.csr_matrix(
        (rng.normal(size=nnzf).astype(np.float32),
         (np.repeat(np.arange(n, dtype=np.int64), GAME_FIXED_NNZ),
          rng.integers(0, GAME_FIXED_FEATURES, size=nnzf))),
        shape=(n, GAME_FIXED_FEATURES),
    )
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = np.ones(n, np.float32)
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )

    fixed = FixedEffectCoordinate(
        "fixed",
        FixedEffectDataset(data=make_glm_data(Xg, y), n_global_rows=n),
        "logistic", opt, reg_weight=1.0,
    )
    coords = [fixed]
    _log(f"multire: {n} rows; grouping user/item/context...")
    for name, keys, cap in (
        ("per_user", users, None),
        ("per_item", items, None),
        # Few heavy contexts: the active-set cap bounds training rows,
        # the passive remainder still scores (the reference's split).
        ("per_context", contexts, 256),
    ):
        Xe = sp.csr_matrix(
            rng.normal(size=(n, GAME_RE_DIM)).astype(np.float32)
        )
        ds = build_random_effect_dataset(
            keys, Xe, y, weights,
            max_rows_per_entity=cap, bucket_growth=GAME_BUCKET_GROWTH,
        )
        _log(f"multire: {name}: {len(ds.blocks)} buckets "
             f"{[(b.n_entities, b.rows_per_entity) for b in ds.blocks]}")
        coords.append(RandomEffectCoordinate(
            name, ds, "logistic", opt, reg_weight=1.0, entity_key=name,
        ))
    cd = CoordinateDescent(coords)

    import jax.numpy as jnp

    base = jnp.zeros(n, jnp.float32)
    _log("multire: warmup iteration (compiles every bucket shape)...")
    warm = cd.run(base, n_iterations=1)
    _read_sync(warm.scores["per_context"])
    # Untimed run at the timed shape — same warm-in discipline as game_cd.
    _read_sync(
        cd.run(base, n_iterations=GAME_TIMED_ITERS).scores["per_context"]
    )
    _log("multire: warmup done; timing...")
    per_iter = []
    for _ in range(GAME_TIMED_RUNS):
        t0 = time.perf_counter()
        result = cd.run(base, n_iterations=GAME_TIMED_ITERS)
        _read_sync(result.scores["per_context"])
        per_iter.append((time.perf_counter() - t0) / GAME_TIMED_ITERS)
    med = float(np.median(per_iter))
    spread_pct = 100.0 * (max(per_iter) - min(per_iter)) / med
    _log(f"multire: median {med:.3f}s/iter over {GAME_TIMED_RUNS}x"
         f"{GAME_TIMED_ITERS} iters (spread {spread_pct:.1f}%)")

    states = {c.name: warm.states[c.name] for c in cd.coordinates}
    scores = dict(warm.scores)
    total = base
    for s in scores.values():
        total = total + s
    breakdown = {}
    for coord in cd.coordinates:
        best_c = np.inf
        for _ in range(2):
            offsets = total - scores[coord.name]
            t0 = time.perf_counter()
            st = coord.train(offsets, warm_state=states[coord.name])
            sc = coord.score(st)
            _read_sync(sc)
            best_c = min(best_c, time.perf_counter() - t0)
        breakdown[coord.name] = round(best_c, 3)
    _log(f"multire: per-coordinate seconds {breakdown}")
    return {
        "iters_per_sec": 1.0 / med,
        "spread_pct": round(spread_pct, 1),
        "coordinate_seconds": breakdown,
        "rows": n,
    }


def _game_scaling_problem(n_devices: int):
    """Deterministic multi-random-effect CD problem for the device-scaling
    leg — random effects ONLY, because the bitwise contract under test is
    the bucket-shard plan's (the distributed fixed effect is allclose,
    not bitwise, so it would mask the comparison)."""
    import scipy.sparse as sp

    import jax.numpy as jnp

    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.game.data import build_random_effect_dataset
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.game.hierarchical import (
        ShardedBucketRandomEffectCoordinate,
    )
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext
    from photon_ml_tpu.parallel.distributed import data_mesh

    rng = np.random.default_rng(7)
    n_ent = 600 if SMALL else 4_000
    sizes = np.minimum(rng.zipf(1.8, n_ent), 64)
    n = int(sizes.sum())
    users = np.repeat(
        np.array([f"u{i}" for i in range(n_ent)], dtype=object), sizes
    )[rng.permutation(n)]
    items = np.array(
        [f"i{rng.integers(max(2, n_ent // 5))}" for _ in range(n)],
        dtype=object,
    )
    contexts = np.array(
        [f"c{rng.integers(200)}" for _ in range(n)], dtype=object
    )
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = np.ones(n, np.float32)
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )
    mesh = data_mesh() if n_devices > 1 else None
    coords = []
    plans = {}
    for name, keys in (
        ("per_user", users), ("per_item", items), ("per_context", contexts)
    ):
        Xe = sp.csr_matrix(
            rng.normal(size=(n, GAME_RE_DIM)).astype(np.float32)
        )
        ds = build_random_effect_dataset(
            keys, Xe, y, weights,
            bucket_growth=GAME_BUCKET_GROWTH, device=mesh is None,
        )
        if mesh is not None:
            coord = ShardedBucketRandomEffectCoordinate(
                name, ds, mesh, "logistic", opt, reg_weight=1.0,
                entity_key=name,
            )
            plans[name] = [coord.plan.n_split, coord.plan.n_packed]
        else:
            coord = RandomEffectCoordinate(
                name, ds, "logistic", opt, reg_weight=1.0, entity_key=name
            )
        coords.append(coord)
    base = jnp.asarray(rng.normal(size=n).astype(np.float32))
    return CoordinateDescent(coords), base, plans


def _game_scaling_worker(n_devices: int) -> None:
    """Subprocess body for ``bench.py --game-scaling-worker N`` (the XLA
    host device count is fixed at backend init, so each scaling point
    needs its own process).  Prints ONE JSON line: iters/sec plus a
    sha256 over the final score vectors — the cross-device-count
    bitwise-parity witness."""
    import hashlib

    import jax

    assert jax.device_count() == n_devices, (
        f"expected {n_devices} devices, got {jax.device_count()} — was "
        "XLA_FLAGS=--xla_force_host_platform_device_count set?"
    )
    cd, base, plans = _game_scaling_problem(n_devices)
    _log(f"scaling worker ({n_devices} devices): warmup...")
    warm = cd.run(base, n_iterations=1)
    _read_sync(warm.scores["per_context"])
    _read_sync(cd.run(base, n_iterations=2).scores["per_context"])
    per_iter = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = cd.run(base, n_iterations=2)
        _read_sync(result.scores["per_context"])
        per_iter.append((time.perf_counter() - t0) / 2)
    digest = hashlib.sha256()
    for coord in cd.coordinates:
        digest.update(
            np.asarray(result.scores[coord.name], np.float32).tobytes()
        )
    print(json.dumps({
        "n_devices": n_devices,
        "iters_per_sec": 1.0 / float(np.median(per_iter)),
        "score_sha256": digest.hexdigest(),
        "plans": plans,
    }))


def bench_game_device_scaling() -> dict:
    """Hierarchical-execution scaling gate (ISSUE 20): multi-RE CD
    iterations/sec at 1 vs 4 forced CPU host devices, with the sharded
    run's final scores required BITWISE equal to the single-device
    geometric-ladder baseline.  The >=1.5x speedup gate only arms when
    >=4 CPU cores are actually visible — 4 forced host devices on fewer
    cores timeshare, so a speedup there is unmeasurable by construction."""
    import subprocess

    results = {}
    for nd in (1, 4):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={nd}",
        )
        _log(f"scaling: launching {nd}-device worker...")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--game-scaling-worker", str(nd)],
            env=env, capture_output=True, text=True, timeout=1800,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{nd}-device scaling worker failed: "
                f"{proc.stderr.strip().splitlines()[-5:]}"
            )
        results[nd] = json.loads(proc.stdout.strip().splitlines()[-1])
    scaling = results[4]["iters_per_sec"] / results[1]["iters_per_sec"]
    bitwise = results[1]["score_sha256"] == results[4]["score_sha256"]
    cores = len(os.sched_getaffinity(0))
    out = {
        "game_scaling_iters_per_sec_1dev": round(
            results[1]["iters_per_sec"], 3
        ),
        "game_scaling_iters_per_sec_4dev": round(
            results[4]["iters_per_sec"], 3
        ),
        "game_scaling_speedup_4dev": round(scaling, 3),
        "game_scaling_bitwise_ok": bitwise,
        "game_scaling_plans_4dev": results[4]["plans"],
    }
    if cores >= 4:
        out["game_scaling_gate_ok"] = bool(scaling >= 1.5 and bitwise)
    else:
        out["game_scaling_gate_ok"] = (
            f"waived: {cores} CPU core(s) visible — 4 forced host devices "
            "timeshare, parallel speedup unmeasurable (bitwise parity "
            f"still checked: {'PASS' if bitwise else 'FAIL'})"
        )
        if not bitwise:
            raise RuntimeError(
                "sharded scores diverged bitwise from the single-device "
                "ladder baseline"
            )
    _log(f"scaling: 1dev {results[1]['iters_per_sec']:.3f} it/s, "
         f"4dev {results[4]['iters_per_sec']:.3f} it/s "
         f"({scaling:.2f}x), bitwise {'PASS' if bitwise else 'FAIL'}, "
         f"gate {out['game_scaling_gate_ok']}")
    return out


def bench_game_repack_ab() -> dict:
    """Cost-model repacker A/B (ISSUE 20): realized padded FLOPs of the
    bench zipf entity distribution under the geometric ladder vs the
    repacker plan at the same program budget."""
    import scipy.sparse as sp

    from photon_ml_tpu.game.data import build_random_effect_dataset

    rng = np.random.default_rng(1)
    n_ent = min(GAME_ENTITIES, 20_000)
    sizes = np.minimum(rng.zipf(1.8, n_ent), GAME_ROW_CAP)
    n = int(sizes.sum())
    keys = np.repeat(
        np.array([f"u{i}" for i in range(n_ent)], dtype=object), sizes
    )
    Xe = sp.csr_matrix(rng.normal(size=(n, GAME_RE_DIM)).astype(np.float32))
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = np.ones(n, np.float32)
    flops, blocks = {}, {}
    for repack in ("geometric", "cost_model"):
        ds = build_random_effect_dataset(
            keys, Xe, y, weights, device=False,
            bucket_growth=GAME_BUCKET_GROWTH, repack=repack,
            program_budget=16,
        )
        flops[repack] = sum(
            b.n_entities * b.rows_per_entity * b.block_dim
            for b in ds.blocks
        )
        blocks[repack] = len(ds.blocks)
    reduction = 100.0 * (1.0 - flops["cost_model"] / flops["geometric"])
    _log(f"repack A/B: geometric {flops['geometric']:.3g} padded FLOPs "
         f"({blocks['geometric']} programs) vs cost_model "
         f"{flops['cost_model']:.3g} ({blocks['cost_model']} programs): "
         f"{reduction:.1f}% reduction")
    return {
        "game_repack_padded_flops_geometric": flops["geometric"],
        "game_repack_padded_flops_cost_model": flops["cost_model"],
        "game_repack_programs": blocks,
        "game_repack_flop_reduction_pct": round(reduction, 1),
    }


def bench_glm_driver() -> tuple[float, float]:
    """Wall-clock of the full legacy GLM driver on an a1a-shaped dataset
    (1605 train / 2000 validate rows, 123 binary features, 3-point λ grid)."""
    import scipy.sparse as sp

    from photon_ml_tpu.data import libsvm
    from photon_ml_tpu.drivers import glm_driver

    rng = np.random.default_rng(2)
    n_train, n_val, d = (400, 200, 123) if SMALL else (1605, 2000, 123)
    X = sp.random(
        n_train + n_val, d, density=0.11, random_state=4, format="csr"
    )
    X.data[:] = 1.0
    w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    logits = X @ w_true - 0.5
    y = np.where(
        rng.uniform(size=n_train + n_val) < 1 / (1 + np.exp(-logits)),
        1.0, -1.0,
    )
    with tempfile.TemporaryDirectory() as td:
        train = os.path.join(td, "a1a_shaped.libsvm")
        val = os.path.join(td, "a1a_shaped.t.libsvm")
        libsvm.write_libsvm(train, X[:n_train], y[:n_train])
        libsvm.write_libsvm(val, X[n_train:], y[n_train:])
        # COLD vs WARM are separate metrics (VERDICT r2: the single number
        # mostly measured compile-cache luck).  Cold runs in-process
        # against a FRESH persistent-cache dir inside this tempdir (so
        # neither a developer's ~/.cache nor a prior bench invocation can
        # pre-warm it).  Warm runs in a FRESH SUBPROCESS with that same
        # cache dir — a real repeat job: interpreter + import + re-trace
        # cost paid, only the XLA executables come from the cache.  (A
        # second in-process run would reuse live jit executables and
        # understate it.)
        cache = os.path.join(td, "jax_cache")
        argv = [
            "--train-data", train,
            "--validate-data", val,
            "--output-dir", os.path.join(td, "out"),
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0,10.0",
            "--n-features", str(d),
            "--compile-cache", cache,
        ]
        _log("driver: cold run (fresh compile cache)...")
        t0 = time.perf_counter()
        glm_driver.run(argv)
        cold = time.perf_counter() - t0
        _log(f"driver: cold {cold:.2f}s; warm run (fresh process, "
             "cache hit)...")
        import subprocess
        import sys as _sys

        import jax

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        # APPEND to PYTHONPATH: the TPU plugin loads from the existing
        # entries; replacing the var kills backend init on this host.
        env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
        # Pin the child to the parent's backend: without this, a child
        # that cannot init the TPU (exclusive access) would silently fall
        # back to CPU with returncode 0 and report a bogus warm number.
        # Pinned, the failure is hard and the in-process fallback below
        # takes over instead.
        env["JAX_PLATFORMS"] = jax.default_backend()
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [_sys.executable, "-m", "photon_ml_tpu.drivers.glm_driver",
                 *argv],
                env=env, capture_output=True, text=True,
                # libtpu in the child may BLOCK waiting for the chip the
                # parent holds instead of failing fast; bound it.
                timeout=max(600.0, 20.0 * cold),
            )
        except subprocess.TimeoutExpired as e:
            r = subprocess.CompletedProcess(
                e.cmd, returncode=-1,
                stdout="", stderr="timed out waiting for the chip",
            )
        warm = time.perf_counter() - t0
        if r.returncode != 0:
            # Standard libtpu grants EXCLUSIVE chip access per process, so
            # while this bench process holds the chip a second one cannot
            # init — fall back to an in-process repeat run there.  It
            # reuses live jit executables too (slightly flattering), so
            # the method is logged for the record.
            err_tail = (
                r.stderr.strip().splitlines()[-1][:200]
                if r.stderr.strip() else "(no stderr)"
            )
            _log("driver: fresh-process warm run failed (exclusive TPU "
                 f"access?) — falling back to in-process repeat: {err_tail}")
            t0 = time.perf_counter()
            glm_driver.run(argv)
            warm = time.perf_counter() - t0
        _log(f"driver: warm {warm:.2f}s")
        # The driver enabled the persistent compile cache at the tempdir
        # path process-wide; switch it off so later bench sections don't
        # serialize compilations into an orphaned /tmp path.
        from photon_ml_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache("off")
        return cold, warm


def bench_streaming() -> dict:
    """Out-of-core A/B: the streamed objective pass (host chunks,
    double-buffered device_put — data/streaming.py) vs the device-resident
    pass on the SAME data, timed identically (host loop per pass, readback
    sync).  The VERDICT r2 acceptance bar is streamed ≥ 0.75x resident."""
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.optim.objective import GlmObjective
    from photon_ml_tpu.optim.streaming import StreamingObjective
    from photon_ml_tpu.ops import losses

    # Calibrate host→device FIRST and size the workload from it: each
    # streamed pass re-transfers the whole chunk store, and on the
    # tunneled dev chip h2d runs at ~5-10 MB/s (vs ~25 GB/s PCIe on
    # production v5e hosts) — a fixed-size A/B would either starve real
    # hardware or spend 10+ bench minutes measuring the tunnel.  Budget:
    # ~15 s of transfer per streamed pass, reported so the ratio is
    # interpretable anywhere.
    blob = np.ones(32 << 20, np.uint8)
    dev = jax.device_put(blob)  # warmup: backend init / first-call cost
    np.asarray(dev[0:1])
    del dev
    t0 = time.perf_counter()
    dev = jax.device_put(blob)
    np.asarray(dev[0:1])
    h2d_gbps = blob.nbytes / (time.perf_counter() - t0) / 1e9
    del dev, blob
    bytes_per_row = NNZ_PER_ROW * 16  # measured ~500 B/row incl. layout pad
    n = int(min(N_ROWS, max(1 << 14, 15.0 * h2d_gbps * 1e9 / bytes_per_row)))
    _log(f"stream: h2d {h2d_gbps:.3f} GB/s -> {n} rows")

    rng = np.random.default_rng(5)
    nnz = n * NNZ_PER_ROW
    rows = np.repeat(np.arange(n, dtype=np.int64), NNZ_PER_ROW)
    cols = rng.integers(0, N_FEATURES, size=nnz).astype(np.int64)
    values = rng.normal(size=nnz).astype(np.float32)
    X = sp.coo_matrix((values, (rows, cols)), shape=(n, N_FEATURES)).tocsr()
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)

    _log(f"stream: building {STREAM_CHUNKS}-chunk store + resident copy...")
    use_pallas = jax.default_backend() == "tpu"
    stream = make_streaming_glm_data(
        X, y, chunk_rows=-(-n // STREAM_CHUNKS), use_pallas=use_pallas
    )
    if stream.staged is None:
        # The coalesced staging pipeline IS the thing being measured; a
        # silent fall-back to per-leaf device_put would report the slow
        # path's numbers as if they were the pipeline's (the failure mode
        # that would quietly re-open the 150x gap).
        raise RuntimeError(
            "bench_streaming: chunk store built UNSTAGED — the prefetch "
            "pipeline would fall back to per-leaf transfers; fix the "
            "store build (this is a measurement bug, not a workload "
            "property)"
        )
    sobj = StreamingObjective("logistic", stream)
    data = make_glm_data(X, y, use_pallas=use_pallas)
    obj = GlmObjective(losses.logistic)
    w = jnp.zeros(N_FEATURES, jnp.float32)

    # Fairness: the resident side is ONE jitted program (data as an
    # argument, never a closure constant), exactly like the streamed
    # side's jitted per-chunk program — otherwise eager dispatch overhead
    # inflates t_res and flatters the ratio.
    res_fn = jax.jit(
        lambda w, data: obj.value_and_grad(w, data, l2_weight=1.0)
    )

    # Warm both (compile) with a readback.
    _v, g = res_fn(w, data)
    _read_sync(g)
    _v, g = sobj.value_and_grad(w, 1.0)
    _read_sync(g)

    def timed(fn, reps=3):
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _val, grad = fn()
            _read_sync(grad)
            best = min(best, time.perf_counter() - t0)
        return best

    t_res = timed(lambda: res_fn(w, data))
    # Transfer observability over the TIMED streamed passes only (the
    # warmup pass above would pollute the per-chunk numbers with
    # compile-time noise).  ONE timed pass for the stage attribution so
    # stage seconds and wall seconds describe the same window (timed()
    # keeps the best-of-3 wall for the headline rate).
    t_str = timed(lambda: sobj.value_and_grad(w, 1.0))
    sobj.transfer_stats.reset()
    t0 = time.perf_counter()
    _val, grad = sobj.value_and_grad(w, 1.0)
    _read_sync(grad)
    wall_1pass = time.perf_counter() - t0
    st = sobj.transfer_stats
    # Stage-attribution overlap witness: with pack ∥ transfer ∥ compute
    # pipelined, the SUMMED per-stage seconds exceed the pass's wall
    # clock (ratio > 1); serialized stages sum to ≤ wall.  A regression
    # in any one stage now names itself instead of hiding in the total.
    overlap = st.stage_seconds / wall_1pass if wall_1pass > 0 else 0.0

    # ---- Oversubscription leg (ISSUE 14): a chunk store split well past
    # the per-pass HBM budget, streamed with lossless wire compression +
    # the importance-aware hot working-set cache (hot budget = 70% of the
    # WIRE store, so ~11 of 16 chunks go resident and skip pack+transfer
    # entirely).  The headline stream_vs_resident is THIS configuration;
    # the uncompressed, uncached 4-chunk ratio stays reported as
    # stream_vs_resident_raw.  Two guards make the number honest: the
    # codec must have actually compressed (ratio > 1.02 — COO int64
    # indices always delta/downcast on this workload, so ~raw means the
    # planner silently fell back), and the compressed+cached gradient
    # must be BITWISE the raw streamed gradient on the same store.
    from photon_ml_tpu.data.staging import plan_compression

    _log(f"stream: oversubscription leg ({STREAM_OS_CHUNKS} chunks, "
         f"lossless wire + hot cache)...")
    stream_os = make_streaming_glm_data(
        X, y, chunk_rows=-(-n // STREAM_OS_CHUNKS), use_pallas=use_pallas
    )
    plan = plan_compression(stream_os.staging, stream_os.staged, "lossless")
    wire_store = plan.wire_nbytes * stream_os.n_chunks
    sobj_os_raw = StreamingObjective("logistic", stream_os)
    sobj_os = StreamingObjective(
        "logistic", stream_os, compress="lossless",
        hot_budget_bytes=int(STREAM_OS_HOT_FRAC * wire_store),
    )
    codec = sobj_os._codec
    if codec.ratio <= 1.02:
        raise RuntimeError(
            f"bench_streaming: lossless compression ratio {codec.ratio:.3f}"
            " — the wire chunks are effectively RAW, so the oversubscribed"
            " leg would time the uncompressed path while reporting it as"
            " compressed; the codec planner fell back (measurement bug,"
            " not a workload property)"
        )
    _vr, g_raw = sobj_os_raw.value_and_grad(w, 1.0)
    _read_sync(g_raw)
    # Warm passes: pass 1 compiles + scores chunk importance, pass 2
    # admits the hot set; the timed passes then run at steady-state hit
    # rate.  Bitwise gate on the LAST timed pass below.
    for _ in range(2):
        _vc, g_comp = sobj_os.value_and_grad(w, 1.0)
        _read_sync(g_comp)
    cache = sobj_os._hot_cache
    hits0, misses0 = cache.hits, cache.misses
    t_comp = timed(lambda: sobj_os.value_and_grad(w, 1.0), reps=2)
    _vc, g_comp = sobj_os.value_and_grad(w, 1.0)
    _read_sync(g_comp)
    if np.asarray(g_comp).tobytes() != np.asarray(g_raw).tobytes():
        raise RuntimeError(
            "bench_streaming: compressed+cached streamed gradient is NOT"
            " bitwise identical to the raw streamed gradient on the same"
            " oversubscribed store — the transfer-avoidance path changed"
            " the numbers it was supposed to only move faster"
        )
    d_hits = cache.hits - hits0
    d_misses = cache.misses - misses0
    hot_hit_rate = d_hits / max(1, d_hits + d_misses)
    logical_pass = stream_os.staging.nbytes * stream_os.n_chunks
    effective_gbps = logical_pass / t_comp / 1e9
    _log(f"stream: oversubscribed compressed+cached "
         f"{n / t_comp / 1e6:.1f} M rows/s (ratio {t_res / t_comp:.3f} vs "
         f"resident), codec {codec.ratio:.2f}x, hot hit rate "
         f"{hot_hit_rate:.2f} ({len(cache)} chunks / "
         f"{cache.resident_bytes / 1e6:.1f} MB resident), effective "
         f"{effective_gbps:.3f} GB/s logical")

    _log(f"stream: resident {n / t_res / 1e6:.1f} M rows/s, "
         f"streamed {n / t_str / 1e6:.1f} M rows/s "
         f"(ratio {t_res / t_str:.3f}, h2d {h2d_gbps:.3f} GB/s)")
    _log(f"stream: per-chunk h2d {st.gbps:.3f} GB/s "
         f"({st.chunk_seconds * 1e3:.1f} ms/chunk, "
         f"{len(stream.staged[0])} coalesced buffers), "
         f"stalls: consumer {st.consumer_stalls} "
         f"({st.consumer_stall_seconds:.2f}s) / producer "
         f"{st.producer_stalls} ({st.producer_stall_seconds:.2f}s), "
         f"max {st.max_live} chunks live")
    _log(f"stream: stage attribution over one {wall_1pass:.3f}s pass — "
         f"pack {st.pack_seconds:.3f}s | dispatch "
         f"{st.dispatch_seconds:.3f}s | h2d {st.h2d_seconds:.3f}s | "
         f"compute {st.consume_seconds:.3f}s; summed stages "
         f"{st.stage_seconds:.3f}s = {overlap:.2f}x wall "
         f"({'overlapped' if overlap > 1.0 else 'serialized'})")
    return {
        "stream_rows_per_sec": round(n / t_str, 1),
        "stream_rows": n,
        "resident_rows_per_sec": round(n / t_res, 1),
        # Headline: the oversubscribed store streamed with lossless wire
        # compression + the hot working-set cache (the ISSUE 14
        # configuration); _raw is the uncompressed, uncached 4-chunk A/B
        # the r2/r05 bars were set against.
        "stream_vs_resident": round(t_res / t_comp, 4),
        "stream_vs_resident_raw": round(t_res / t_str, 4),
        "stream_os_rows_per_sec": round(n / t_comp, 1),
        "stream_os_chunks": stream_os.n_chunks,
        "stream_compression_ratio": round(codec.ratio, 3),
        "stream_hot_hit_rate": round(hot_hit_rate, 4),
        "stream_hot_resident_chunks": len(cache),
        "stream_hot_resident_mb": round(cache.resident_bytes / 1e6, 2),
        "stream_effective_gbps": round(effective_gbps, 3),
        "h2d_gbps": round(h2d_gbps, 3),
        # Per-chunk ingest pipeline metrics (ops/README.md "Reading the
        # streamed-ingest h2d metrics"): achieved staging-buffer rate,
        # mean per-chunk transfer time, and queue-stall counters over
        # the timed passes.
        "stream_h2d_gbps": round(st.gbps, 3),
        "stream_h2d_chunk_ms": round(st.chunk_seconds * 1e3, 2),
        "stream_consumer_stalls": st.consumer_stalls,
        "stream_producer_stalls": st.producer_stalls,
        "stream_consumer_stall_s": round(st.consumer_stall_seconds, 3),
        "stream_producer_stall_s": round(st.producer_stall_seconds, 3),
        "stream_prefetch_max_live": st.max_live,
        # Per-STAGE wall attribution over one measured pass (pack thread /
        # put() dispatch / transfer completion / consumer compute) and
        # the overlap witness: summed stage seconds vs the pass's wall
        # clock — > 1.0 means the pipeline stages genuinely overlapped.
        "stream_pack_s": round(st.pack_seconds, 3),
        "stream_dispatch_s": round(st.dispatch_seconds, 3),
        "stream_h2d_s": round(st.h2d_seconds, 3),
        "stream_compute_s": round(st.consume_seconds, 3),
        "stream_pass_wall_s": round(wall_1pass, 3),
        "stream_stage_overlap": round(overlap, 3),
    }


def bench_chaos() -> dict:
    """Chaos-harness cost + recovery latency (ISSUE 6 acceptance gates).

    1. **Disabled-path overhead gate**: with no FaultPlan installed every
       ``chaos.maybe_fail`` seam costs one global read + one branch.
       Measured directly (tight-loop ns/call), multiplied by the EXACT
       per-pass call count (an empty installed plan counts occurrences
       without injecting), and compared against a streamed objective
       pass's wall — the ``bench_streaming`` workload shape.  Gate:
       ≤ 1% of the streamed pass wall.
    2. **Recovery latency**: a scripted kill at a λ-grid boundary, then
       the watchdog resume — reported as the resumed attempt's wall
       (checkpoint reload + remaining solves) next to the uninterrupted
       grid's wall.
    3. **Serving degrade/re-promote**: wall of the first degraded
       (host cold path) batch and of the re-promotion probe batch.
    """
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu import chaos
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.io.checkpoint import GridCheckpointer
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        GlmOptimizationProblem,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import (
        StreamingObjective,
        streaming_run_grid,
    )
    from photon_ml_tpu.utils.watchdog import RetryPolicy, run_with_retries

    assert chaos.current_plan() is None, "bench needs the disabled path"

    # -- 1a. per-call cost of the disabled hook ----------------------------
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        chaos.maybe_fail("grid.point")
    per_call_s = (time.perf_counter() - t0) / reps

    # -- 1b. streamed pass wall + exact per-pass seam-call count -----------
    rng = np.random.default_rng(17)
    n, d = (1 << 13), 256
    nnz = n * 16
    rows = np.repeat(np.arange(n, dtype=np.int64), 16)
    cols = rng.integers(0, d, size=nnz).astype(np.int64)
    X = sp.coo_matrix(
        (rng.normal(size=nnz).astype(np.float32), (rows, cols)),
        shape=(n, d),
    ).tocsr()
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    stream = make_streaming_glm_data(
        X, y, chunk_rows=-(-n // STREAM_CHUNKS), use_pallas=False
    )
    sobj = StreamingObjective("logistic", stream)
    w = jnp.zeros(d, jnp.float32)
    _v, g = sobj.value_and_grad(w, 1.0)  # warm (compile)
    _read_sync(g)
    wall = np.inf
    for _ in range(N_REPS):
        t0 = time.perf_counter()
        _v, g = sobj.value_and_grad(w, 1.0)
        _read_sync(g)
        wall = min(wall, time.perf_counter() - t0)
    # Exact call count: an EMPTY plan counts occurrences, injects nothing
    # (this pass runs the enabled-no-match path; only the count is used).
    counter_plan = chaos.FaultPlan([])
    with counter_plan:
        _v, g = sobj.value_and_grad(w, 1.0)
        _read_sync(g)
    calls = sum(
        counter_plan.occurrences(site) for site in chaos.KNOWN_SITES
    )
    overhead_frac = calls * per_call_s / wall if wall > 0 else 0.0
    gate_ok = overhead_frac <= 0.01
    _log(
        f"chaos: disabled maybe_fail {per_call_s * 1e9:.0f} ns/call x "
        f"{calls} calls/pass over a {wall * 1e3:.1f} ms streamed pass "
        f"-> {overhead_frac * 100:.4f}% overhead "
        f"({'PASS' if gate_ok else 'FAIL'} @ <=1%)"
    )

    # -- 2. kill/resume recovery latency -----------------------------------
    problem = GlmOptimizationProblem(
        "logistic",
        GlmOptimizationConfig(
            optimizer=OptimizerConfig(max_iters=25),
            regularization=RegularizationContext.l2(),
        ),
    )
    lams = [3.0, 1.0, 0.3]
    t0 = time.perf_counter()
    streaming_run_grid(problem, stream, lams)
    full_wall = time.perf_counter() - t0

    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench_chaos_") as td:
        ckpt = GridCheckpointer(td)
        plan = chaos.FaultPlan([chaos.FaultSpec(site="grid.point", at=1)])
        attempt_walls = []

        def train(attempt):
            t0 = time.perf_counter()
            solved = ckpt.load() if attempt else {}
            acc = dict(solved)

            def on_solved(lam, w_):
                acc[lam] = np.asarray(w_)
                ckpt.save(acc)

            try:
                return streaming_run_grid(
                    problem, stream, lams, solved=solved,
                    on_solved=on_solved,
                )
            finally:
                attempt_walls.append(time.perf_counter() - t0)

        with plan:
            run_with_retries(
                train, RetryPolicy(max_retries=1), sleep=lambda s: None
            )
    recovery_wall = attempt_walls[-1]
    _log(
        f"chaos: kill@λ-boundary recovery {recovery_wall:.3f}s resume vs "
        f"{full_wall:.3f}s uninterrupted grid"
    )

    # -- 3. serving degrade / re-promote latency ---------------------------
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    workload = SyntheticWorkload(n_entities=256, seed=21)
    runtime = ScoringRuntime(
        workload.model, workload.index_maps,
        RuntimeConfig(max_batch_size=8, hot_entities=32,
                      breaker_cooldown_s=0.0),
    )
    batch = [runtime.parse_request(workload.request(i)) for i in range(8)]
    runtime.score_rows(batch)  # healthy warm batch
    with chaos.FaultPlan([
        chaos.FaultSpec(site="serving.device", at=0,
                        exception="InjectedDeviceLost"),
    ]):
        t0 = time.perf_counter()
        runtime.score_rows(batch)  # fault -> degrade -> host path
        degrade_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        runtime.score_rows(batch)  # probe -> re-promotion
        repromote_wall = time.perf_counter() - t0
    assert runtime.degraded is False and runtime.repromotions == 1
    _log(
        f"chaos: serving degrade batch {degrade_wall * 1e3:.2f} ms, "
        f"re-promotion probe {repromote_wall * 1e3:.2f} ms"
    )

    return {
        "chaos_maybe_fail_ns": round(per_call_s * 1e9, 1),
        "chaos_calls_per_streamed_pass": calls,
        "chaos_streamed_pass_wall_s": round(wall, 4),
        "chaos_disabled_overhead_frac": round(overhead_frac, 6),
        "chaos_overhead_gate_ok": gate_ok,
        "chaos_grid_full_wall_s": round(full_wall, 3),
        "chaos_grid_recovery_wall_s": round(recovery_wall, 3),
        "chaos_serving_degrade_ms": round(degrade_wall * 1e3, 2),
        "chaos_serving_repromote_ms": round(repromote_wall * 1e3, 2),
    }


def bench_telemetry() -> dict:
    """Live ops-plane cost gate (ISSUE 7 acceptance): the ENABLED plane
    — time-series sampler + /metrics exporter + per-chunk HBM gauges —
    must add ≤ 1% to a streamed GLM pass.

    Gate methodology mirrors ``bench_chaos``: each component's unit cost
    is measured directly (tight loop), multiplied by its per-pass call
    count, and compared against the streamed pass wall — noise-free
    where a wall-clock A/B on a ~100 ms pass is not.  The measured A/B
    delta is reported alongside for the record.  Components:

    - sampler: one ``sample()`` per ``interval_s`` (1 s default) —
      cost/sample ÷ interval is the steady-state fraction;
    - HBM gauges: 2 locked ``gauge.set`` calls per chunk bump (2 bumps/
      chunk) + 2 per-pass gauges — counted exactly;
    - exporter: zero unless scraped; one /metrics render is timed and
      amortized over a 5 s scrape interval.
    """
    import tempfile

    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.optim.streaming import StreamingObjective
    from photon_ml_tpu.telemetry.exporter import prometheus_text
    from photon_ml_tpu.telemetry.timeseries import TimeSeriesSampler

    # -- workload: the bench_chaos streamed shape --------------------------
    rng = np.random.default_rng(23)
    n, d = (1 << 13), 256
    nnz = n * 16
    rows = np.repeat(np.arange(n, dtype=np.int64), 16)
    cols = rng.integers(0, d, size=nnz).astype(np.int64)
    X = sp.coo_matrix(
        (rng.normal(size=nnz).astype(np.float32), (rows, cols)),
        shape=(n, d),
    ).tocsr()
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    stream = make_streaming_glm_data(
        X, y, chunk_rows=-(-n // STREAM_CHUNKS), use_pallas=False
    )
    sobj = StreamingObjective("logistic", stream)
    w = jnp.zeros(d, jnp.float32)

    def one_pass():
        _v, g = sobj.value_and_grad(w, 1.0)
        _read_sync(g)

    prev = telemetry_mod.set_current(telemetry_mod.NULL)
    try:
        one_pass()  # warm (compile)
        wall_off = np.inf
        for _ in range(N_REPS):
            t0 = time.perf_counter()
            one_pass()
            wall_off = min(wall_off, time.perf_counter() - t0)

        with tempfile.TemporaryDirectory(prefix="bench_tel_") as td:
            with telemetry_mod.Telemetry(
                output_dir=td, run_name="bench-telemetry"
            ) as tel:
                plane = telemetry_mod.mount_ops_plane(
                    tel, port=0, interval_s=1.0
                )
                try:
                    one_pass()  # re-warm under the enabled hub
                    wall_on = np.inf
                    for _ in range(N_REPS):
                        t0 = time.perf_counter()
                        one_pass()
                        wall_on = min(
                            wall_on, time.perf_counter() - t0
                        )

                    # -- unit costs --------------------------------------
                    sampler: TimeSeriesSampler = plane.sampler
                    reps = 200
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        sampler.sample()
                    sample_s = (time.perf_counter() - t0) / reps

                    g = tel.gauge("hbm_live_bytes")
                    reps = 100_000
                    t0 = time.perf_counter()
                    for i in range(reps):
                        g.set(i)
                    gauge_s = (time.perf_counter() - t0) / reps

                    snap = tel.snapshot()
                    reps = 50
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        prometheus_text(snap)
                    render_s = (time.perf_counter() - t0) / reps
                finally:
                    plane.close()
    finally:
        telemetry_mod.set_current(prev)

    # -- per-pass accounting ----------------------------------------------
    chunks = stream.n_chunks
    # 2 gauge sets per _bump x 2 bumps per chunk, + 2 window gauges/pass.
    gauge_calls = 4 * chunks + 2
    frac_gauges = gauge_calls * gauge_s / wall_off
    frac_sampler = sample_s / 1.0  # one sample per interval_s=1.0
    frac_exporter = render_s / 5.0  # one scrape per 5 s, rendered live
    overhead_frac = frac_gauges + frac_sampler + frac_exporter
    gate_ok = overhead_frac <= 0.01
    measured_delta = (wall_on - wall_off) / wall_off
    _log(
        f"telemetry: ops plane — gauges {gauge_s * 1e9:.0f} ns/set x "
        f"{gauge_calls}/pass, sampler {sample_s * 1e3:.2f} ms/sample, "
        f"/metrics render {render_s * 1e3:.2f} ms -> "
        f"{overhead_frac * 100:.4f}% of a {wall_off * 1e3:.1f} ms "
        f"streamed pass ({'PASS' if gate_ok else 'FAIL'} @ <=1%); "
        f"measured A/B delta {measured_delta * 100:+.2f}%"
    )
    return {
        "telemetry_gauge_set_ns": round(gauge_s * 1e9, 1),
        "telemetry_sample_ms": round(sample_s * 1e3, 3),
        "telemetry_prom_render_ms": round(render_s * 1e3, 3),
        "telemetry_streamed_pass_wall_s": round(wall_off, 4),
        "telemetry_ops_plane_overhead_frac": round(overhead_frac, 6),
        "telemetry_overhead_gate_ok": gate_ok,
        "telemetry_measured_delta_frac": round(measured_delta, 4),
    }


def bench_analysis() -> dict:
    """Lock-order sanitizer cost gate (ISSUE 10 acceptance): the ENABLED
    sanitizer — every tracked-lock acquire/release feeding the witness
    graph — must add ≤ 1% to a streamed GLM pass.  The DISABLED path is
    free by construction (``sanitizers.tracked`` returns the raw lock
    when nothing is installed), asserted here rather than timed.

    Gate methodology mirrors ``bench_chaos``/``bench_telemetry``: the
    tracked acquire+release pair cost is measured in a tight loop and
    multiplied by the exact per-pass acquisition count (prefetch's
    ``_bump`` takes ``prefetch.live`` twice per chunk), then compared
    against the streamed pass wall; the measured A/B delta (sanitizer
    installed vs not — the prefetch pipeline creates its locks per pass,
    so installation flips the real hot path) is reported alongside.
    The static checker's own wall time over the full tree rides along
    as an informational number (it runs in check.sh, not per pass).
    """
    import threading

    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.analysis import check as analysis_check
    from photon_ml_tpu.analysis import sanitizers
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.optim.streaming import StreamingObjective

    # -- workload: the bench_chaos/bench_telemetry streamed shape ----------
    rng = np.random.default_rng(29)
    n, d = (1 << 13), 256
    nnz = n * 16
    rows = np.repeat(np.arange(n, dtype=np.int64), 16)
    cols = rng.integers(0, d, size=nnz).astype(np.int64)
    X = sp.coo_matrix(
        (rng.normal(size=nnz).astype(np.float32), (rows, cols)),
        shape=(n, d),
    ).tocsr()
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    stream = make_streaming_glm_data(
        X, y, chunk_rows=-(-n // STREAM_CHUNKS), use_pallas=False
    )
    sobj = StreamingObjective("logistic", stream)
    w = jnp.zeros(d, jnp.float32)

    def one_pass():
        _v, g = sobj.value_and_grad(w, 1.0)
        _read_sync(g)

    # Disabled path: tracked() must hand back the raw lock untouched.
    raw = threading.Lock()
    assert sanitizers.tracked(raw, "bench.check") is raw

    one_pass()  # warm (compile)
    wall_off = np.inf
    for _ in range(N_REPS):
        t0 = time.perf_counter()
        one_pass()
        wall_off = min(wall_off, time.perf_counter() - t0)

    reps = 100_000
    t0 = time.perf_counter()
    for _ in range(reps):
        raw.acquire()
        raw.release()
    raw_pair_s = (time.perf_counter() - t0) / reps

    with sanitizers.LockOrderSanitizer() as san:
        one_pass()  # re-warm: locks are now created tracked
        wall_on = np.inf
        for _ in range(N_REPS):
            t0 = time.perf_counter()
            one_pass()
            wall_on = min(wall_on, time.perf_counter() - t0)

        tl = sanitizers.tracked(threading.Lock(), "bench.unit")
        t0 = time.perf_counter()
        for _ in range(reps):
            tl.acquire()
            tl.release()
        tracked_pair_s = (time.perf_counter() - t0) / reps
        n_reports = len(san.reports)

    # -- per-pass accounting ----------------------------------------------
    chunks = stream.n_chunks
    # prefetch._bump takes prefetch.live once per +1 and once per -1.
    tracked_calls = 2 * chunks
    overhead_frac = (
        tracked_calls * max(tracked_pair_s - raw_pair_s, 0.0) / wall_off
    )
    gate_ok = overhead_frac <= 0.01
    measured_delta = (wall_on - wall_off) / wall_off

    t0 = time.perf_counter()
    report = analysis_check()
    check_wall_s = time.perf_counter() - t0

    _log(
        f"analysis: lock-order sanitizer — tracked pair "
        f"{tracked_pair_s * 1e9:.0f} ns vs raw {raw_pair_s * 1e9:.0f} ns "
        f"x {tracked_calls}/pass -> {overhead_frac * 100:.4f}% of a "
        f"{wall_off * 1e3:.1f} ms streamed pass "
        f"({'PASS' if gate_ok else 'FAIL'} @ <=1%); measured A/B delta "
        f"{measured_delta * 100:+.2f}%; {n_reports} inversion report(s); "
        f"static --check {'clean' if report.ok else 'FAILED'} in "
        f"{check_wall_s * 1e3:.0f} ms over {report.files} files"
    )
    return {
        "analysis_tracked_pair_ns": round(tracked_pair_s * 1e9, 1),
        "analysis_raw_pair_ns": round(raw_pair_s * 1e9, 1),
        "analysis_sanitizer_overhead_frac": round(overhead_frac, 6),
        "analysis_sanitizer_gate_ok": gate_ok,
        "analysis_measured_delta_frac": round(measured_delta, 4),
        "analysis_inversion_reports": n_reports,
        "analysis_check_wall_s": round(check_wall_s, 3),
        "analysis_check_ok": report.ok,
    }


def bench_avro_write() -> dict:
    """Scoring-result write rate (VERDICT r4 weak #5: the write path was
    the last pure-Python hot loop and had never been measured).  Times
    the columnar writer with the native encoder vs the Python fallback
    on 100k MovieLens-shaped scoring rows, deflate codec (the driver's
    default)."""
    from photon_ml_tpu import native as native_mod
    from photon_ml_tpu.io import avro

    rng = np.random.default_rng(7)
    n = 20_000 if SMALL else 100_000
    uids = [f"row{i}" for i in range(n)]
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    ids = {
        "movieId": [f"m{i % 3883}" for i in range(n)],
        "userId": [f"u{i % 6040}" for i in range(n)],
    }
    block = (uids, scores, labels, ids)
    out = {}
    saved_env = os.environ.get("PHOTON_NO_NATIVE")
    try:
        with tempfile.TemporaryDirectory() as td:
            for label_, env in (("native", None), ("python", "1")):
                if env is None:
                    os.environ.pop("PHOTON_NO_NATIVE", None)
                else:
                    os.environ["PHOTON_NO_NATIVE"] = env
                native_mod._CACHE.pop("encoder", None)
                if env is None and native_mod.load_score_encoder() is None:
                    # No toolchain: don't report the fallback's rate as
                    # the native number.
                    out["avro_write_native_recs_per_sec"] = (
                        "unavailable (encoder build failed)"
                    )
                    continue
                path = os.path.join(td, f"w_{label_}.avro")
                best = np.inf
                for _ in range(3):
                    t0 = time.perf_counter()
                    avro.write_scoring_container(path, [block])
                    best = min(best, time.perf_counter() - t0)
                out[f"avro_write_{label_}_recs_per_sec"] = round(n / best, 1)
    finally:
        if saved_env is None:
            os.environ.pop("PHOTON_NO_NATIVE", None)
        else:
            os.environ["PHOTON_NO_NATIVE"] = saved_env
        native_mod._CACHE.pop("encoder", None)
    _log(
        f"avro: write native={out.get('avro_write_native_recs_per_sec')} "
        f"python={out.get('avro_write_python_recs_per_sec')} rec/s"
    )
    return out


def bench_serving() -> dict:
    """Online serving (PR 3): closed-loop throughput + latency of the
    micro-batched scoring service on a synthetic GAME model with ≥10k
    random-effect entities (zipf-skewed request stream, so the LRU hot
    set sees realistic hits over a cold tail).  In-process submits — no
    HTTP framing — so the number is the batcher+kernel path itself."""
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    n_entities = 10_000 if SMALL else 50_000
    duration = 2.0 if SMALL else 6.0
    clients = 16
    _log(f"serving: building synthetic GAME model "
         f"({n_entities} entities)...")
    workload = SyntheticWorkload(
        n_entities=n_entities, fixed_dim=64, re_dim=8, seed=9
    )
    runtime = ScoringRuntime(
        workload.model, workload.index_maps,
        RuntimeConfig(max_batch_size=64, hot_entities=4096),
    )
    _log(f"serving: warmed {runtime.warmup_compiles} bucket kernels "
         f"{runtime.buckets}; loading...")
    service = ScoringService(runtime, BatcherConfig(
        max_batch_size=64, max_wait_us=1000, max_queue=1024,
    ))
    with service:
        # Short warm run: first-touch allocator/pipeline costs and the
        # initial hot-set fill stay out of the timed window.
        loadgen.closed_loop(
            service.submit, workload.request, clients=4, duration_s=0.5
        )
        report = loadgen.closed_loop(
            service.submit, workload.request,
            clients=clients, duration_s=duration,
        )
    snap = report.snapshot()
    stats = runtime.stats()
    hot = stats["hot_sets"]["per_entity"]
    mean_batch = (
        stats["rows_scored"] / stats["batches"] if stats["batches"] else None
    )
    _log(f"serving: {snap['throughput_rps']} rps over {clients} closed-"
         f"loop clients, p50 {snap['latency_p50_ms']} ms / p99 "
         f"{snap['latency_p99_ms']} ms / p99.9 {snap['latency_p999_ms']} "
         f"ms, mean batch {mean_batch and round(mean_batch, 1)} rows, "
         f"hot hit rate {hot['hit_rate'] and round(hot['hit_rate'], 3)}")
    out = {
        "serving_throughput_rps": snap["throughput_rps"],
        "serving_latency_p50_ms": snap["latency_p50_ms"],
        "serving_latency_p99_ms": snap["latency_p99_ms"],
        "serving_latency_p999_ms": snap["latency_p999_ms"],
        "serving_completed": report.completed,
        "serving_rejected": report.rejected,
        "serving_clients": clients,
        "serving_entities": n_entities,
        "serving_mean_batch_rows": (
            None if mean_batch is None else round(mean_batch, 2)
        ),
        "serving_hot_hit_rate": (
            None if hot["hit_rate"] is None else round(hot["hit_rate"], 4)
        ),
    }
    out.update(_bench_serving_wire(workload))
    out.update(_bench_serving_scenarios(workload))
    out.update(_bench_serving_process(workload))
    out.update(_bench_serving_tenancy(workload))
    out.update(_bench_serving_fleet(workload))
    return out


def _bench_serving_scenarios(workload) -> dict:
    """Scripted HA scenarios against a 2-replica supervisor: per-scenario
    p50/p99 + error counts.  The replica-kill and swap-under-load
    scenarios must complete with ZERO failed requests — that is the HA
    acceptance gate, reported (not asserted) here so a regression shows
    up in the bench diff."""
    import tempfile

    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    rate = 150.0 if SMALL else 400.0
    rt_cfg = RuntimeConfig(max_batch_size=32, hot_entities=1024)

    def factory() -> ScoringRuntime:
        return ScoringRuntime(
            workload.model, workload.index_maps, rt_cfg
        )

    def make_request(i: int, phase) -> dict:
        if phase.entity_pool is None:
            return workload.request(i)
        # Skew shift: draw the entity from the phase's fraction range of
        # the entity space (disjoint ranges churn the LRU hot set).
        lo, hi = phase.entity_pool
        req = workload.request(i)
        span = max(1, int((hi - lo) * workload.n_entities))
        req["ids"][workload.entity_key] = (
            f"u{int(lo * workload.n_entities) + i % span}"
        )
        return req

    out: dict = {}
    with tempfile.TemporaryDirectory(prefix="bench_serving_swap_") as td:
        v2 = SyntheticWorkload(
            n_entities=workload.n_entities, fixed_dim=workload.fixed_dim,
            re_dim=workload.re_dim, seed=10,
        )
        v2_dir = os.path.join(td, "v2")
        _log("serving: saving swap-target model...")
        save_game_model(v2.model, v2.index_maps, v2_dir)
        for name, scenario in loadgen.SCENARIOS.items():
            if name == "noisy_neighbor":
                # Tenant-aware: needs per-tenant outcome accounting, so
                # _bench_serving_tenancy replays it via
                # run_noisy_neighbor — the tenant-blind run_scenario
                # here would lump aggressor sheds in with victim counts.
                continue
            wired = {"swap", "kill_replica"}
            if any(
                p.action is not None and p.action not in wired
                for p in scenario.phases
            ):
                # Scenarios needing other substrates run elsewhere:
                # worker_kill in _bench_serving_process (worker pool),
                # host_kill / quota_partition in _bench_serving_fleet
                # (multi-host router + lease coordinator).
                # run_scenario refuses unwired actions by design.
                continue
            supervisor = ReplicaSupervisor(
                factory, n_replicas=2, probe_interval_s=0.1
            )
            service = ScoringService(supervisor, BatcherConfig(
                max_batch_size=32, max_wait_us=1000, max_queue=1024,
            ))
            with service:
                actions = {
                    "swap": lambda svc=service: svc.reload(
                        v2_dir
                    ).to_dict(),
                    "kill_replica": lambda sup=supervisor: {
                        "killed": sup.kill_replica(0).rid
                    },
                }
                report = loadgen.run_scenario(
                    service.submit, make_request, scenario,
                    base_rate_rps=rate, actions=actions,
                )
            snap = report.snapshot()
            _log(
                f"serving scenario {name}: {report.completed} ok / "
                f"{report.rejected} shed / {report.errors} errors, p50 "
                f"{snap['latency_p50_ms']} ms p99 {snap['latency_p99_ms']}"
                " ms"
            )
            out[f"serving_scenario_{name}_p50_ms"] = snap["latency_p50_ms"]
            out[f"serving_scenario_{name}_p99_ms"] = snap["latency_p99_ms"]
            out[f"serving_scenario_{name}_p999_ms"] = (
                snap["latency_p999_ms"]
            )
            out[f"serving_scenario_{name}_completed"] = report.completed
            out[f"serving_scenario_{name}_rejected"] = report.rejected
            out[f"serving_scenario_{name}_errors"] = report.errors
    return out


def _bench_serving_process(workload) -> dict:
    """Process-mode HA gate: the ``worker_kill`` scenario delivers a real
    SIGKILL to a worker process while ≥120 rps flows through a 2-worker
    pool-backed supervisor.  The acceptance gate is zero errors AND zero
    rejections across the whole scenario (the pipe-EOF resubmission path
    absorbing the crash), reported as an explicit boolean so a
    regression is unmissable in the bench diff, alongside the tail
    latency (p99.9) the kill window costs."""
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.procpool import WorkerPool
    from photon_ml_tpu.serving.runtime import RuntimeConfig
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

    rate = 120.0 if SMALL else 240.0
    _log("serving: publishing model to shared memory (process mode)...")
    pool = WorkerPool(
        workload.model, workload.index_maps,
        runtime_config=RuntimeConfig(
            max_batch_size=32, hot_entities=1024
        ),
    )
    supervisor = ReplicaSupervisor(
        pool=pool, n_replicas=2, probe_interval_s=0.1
    )
    service = ScoringService(supervisor, BatcherConfig(
        max_batch_size=32, max_wait_us=1000, max_queue=1024,
    ))
    scenario = loadgen.SCENARIOS["worker_kill"]
    with service:
        report = loadgen.run_scenario(
            service.submit,
            lambda i, phase: workload.request(i),
            scenario,
            base_rate_rps=rate,
            actions={
                "kill_worker": lambda: {
                    "killed": supervisor.kill_replica(0).rid
                },
            },
        )
    snap = report.snapshot()
    zero_failed = report.errors == 0 and report.rejected == 0
    _log(
        f"serving process-mode worker_kill @ {rate:g} rps: "
        f"{report.completed} ok / {report.rejected} shed / "
        f"{report.errors} errors, p99 {snap['latency_p99_ms']} ms "
        f"p99.9 {snap['latency_p999_ms']} ms, zero-failed gate "
        f"{'PASS' if zero_failed else 'FAIL'}"
    )
    return {
        "serving_proc_worker_kill_rate_rps": rate,
        "serving_proc_worker_kill_p50_ms": snap["latency_p50_ms"],
        "serving_proc_worker_kill_p99_ms": snap["latency_p99_ms"],
        "serving_proc_worker_kill_p999_ms": snap["latency_p999_ms"],
        "serving_proc_worker_kill_completed": report.completed,
        "serving_proc_worker_kill_rejected": report.rejected,
        "serving_proc_worker_kill_errors": report.errors,
        "serving_proc_worker_kill_zero_failed": zero_failed,
    }


def _bench_serving_tenancy(workload) -> dict:
    """Multi-tenant isolation gate: the ``noisy_neighbor`` scenario in
    BOTH thread and process mode.  An aggressor tenant bursts to 10x
    its token-bucket quota while a victim tenant holds 40 rps; the
    acceptance gate (``*_isolation_pass``) is victim ZERO failures AND
    victim p99 inside its configured SLO AND the aggressor actually
    shed — reported per mode so a containment regression is unmissable
    in the bench diff."""
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.procpool import WorkerPool
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.tenancy import TenancyConfig, TenantSpec

    victim_slo_ms = 500.0
    n_units = 2
    rt_cfg = RuntimeConfig(max_batch_size=32, hot_entities=1024)
    # Quotas are enforced per batcher (per replica/worker): size the
    # aggressor's so the 10x burst is 10x its AGGREGATE admitted rate.
    aggressor_quota = 40.0 / n_units
    tenancy = TenancyConfig(tenants=(
        TenantSpec(
            name="victim", max_queue=256, p99_slo_ms=victim_slo_ms,
        ),
        TenantSpec(
            name="aggressor", quota_rps=aggressor_quota,
            burst=max(aggressor_quota / 2.0, 1.0), max_queue=128,
        ),
    ))
    batcher_cfg = BatcherConfig(
        max_batch_size=32, max_wait_us=1000, max_queue=1024,
        tenancy=tenancy,
    )

    def make_request(i: int, phase, tenant: str) -> dict:
        req = dict(workload.request(i))
        req["tenant"] = tenant
        return req

    out: dict = {}
    for mode, prefix in (("thread", "serving_tenant"),
                         ("process", "serving_proc_tenant")):
        if mode == "thread":
            supervisor = ReplicaSupervisor(
                lambda: ScoringRuntime(
                    workload.model, workload.index_maps, rt_cfg
                ),
                n_replicas=n_units, probe_interval_s=0.1,
            )
        else:
            _log("serving: publishing model to shared memory "
                 "(tenancy, process mode)...")
            pool = WorkerPool(
                workload.model, workload.index_maps,
                runtime_config=rt_cfg,
            )
            supervisor = ReplicaSupervisor(
                pool=pool, n_replicas=n_units, probe_interval_s=0.1
            )
        service = ScoringService(supervisor, batcher_cfg)
        with service:
            report = loadgen.run_noisy_neighbor(
                service.submit, make_request,
                victim_rate_rps=40.0, aggressor_rate_rps=40.0,
            )
        gate = report.isolation(victim_slo_ms)
        _log(
            f"serving tenancy noisy_neighbor ({mode}): victim "
            f"{gate['victim_completed']} ok / {gate['victim_failed']} "
            f"failed, p99 {gate['victim_p99_ms']} ms (SLO "
            f"{victim_slo_ms:g} ms); aggressor "
            f"{gate['aggressor_completed']} ok / "
            f"{gate['aggressor_shed']} shed; isolation gate "
            f"{'PASS' if gate['pass'] else 'FAIL'}"
        )
        out.update({
            f"{prefix}_victim_completed": gate["victim_completed"],
            f"{prefix}_victim_failed": gate["victim_failed"],
            f"{prefix}_victim_p99_ms": gate["victim_p99_ms"],
            f"{prefix}_victim_slo_ms": victim_slo_ms,
            f"{prefix}_aggressor_completed": (
                gate["aggressor_completed"]
            ),
            f"{prefix}_aggressor_shed": gate["aggressor_shed"],
            f"{prefix}_isolation_pass": gate["pass"],
        })
    return out


def _bench_serving_wire(workload) -> dict:
    """Data-plane A/B (ISSUE 16): the same service, the same request
    stream, measured over HTTP with persistent connections under both
    wire formats, plus adaptive-vs-static micro-batching in process.

    - ``serving_wire_{json,binary}_*``: closed-loop throughput and
      open-loop p50/p99/p999 at a FIXED offered rate for the JSON
      compatibility path vs the binary frame path.  The speedup ratio
      is reported, not hard-gated (accelerator-dependent).
    - ``serving_adaptive_*`` / ``serving_static_*``: open-loop latency
      at the same offered rate with the coalescing wait sized by the
      arrival-rate EWMA vs the static ``max_wait_us`` knob.
    """
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService, start_http_server

    duration = 2.0 if SMALL else 5.0
    clients = 16
    rate = 300.0 if SMALL else 1000.0
    out: dict = {}

    def service():
        return ScoringService(
            ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=64, hot_entities=4096),
            ),
            BatcherConfig(
                max_batch_size=64, max_wait_us=1000, max_queue=1024,
            ),
        )

    # -- JSON vs binary over HTTP ------------------------------------------
    for fmt in ("json", "binary"):
        svc = service()
        with svc:
            server, _ = start_http_server(svc, port=0)
            base = f"http://127.0.0.1:{server.server_address[1]}"
            try:
                with loadgen.HttpSubmitter(
                    base, wire_format=fmt, workers=clients * 2
                ) as sub:
                    loadgen.closed_loop(  # warmup
                        sub.submit, workload.request,
                        clients=4, duration_s=0.5,
                    )
                    closed = loadgen.closed_loop(
                        sub.submit, workload.request,
                        clients=clients, duration_s=duration,
                    )
                    fixed = loadgen.open_loop(
                        sub.submit, workload.request,
                        rate_rps=rate, duration_s=duration,
                    )
            finally:
                server.shutdown()
                server.server_close()
        snap_c, snap_o = closed.snapshot(), fixed.snapshot()
        _log(
            f"serving wire[{fmt}]: {snap_c['throughput_rps']} rps closed"
            f"-loop; open-loop @{rate:g} rps p50 "
            f"{snap_o['latency_p50_ms']} / p99 {snap_o['latency_p99_ms']}"
            f" / p99.9 {snap_o['latency_p999_ms']} ms"
        )
        out.update({
            f"serving_wire_{fmt}_throughput_rps": snap_c["throughput_rps"],
            f"serving_wire_{fmt}_open_p50_ms": snap_o["latency_p50_ms"],
            f"serving_wire_{fmt}_open_p99_ms": snap_o["latency_p99_ms"],
            f"serving_wire_{fmt}_open_p999_ms": snap_o["latency_p999_ms"],
            f"serving_wire_{fmt}_errors": closed.errors + fixed.errors,
        })
    j = out["serving_wire_json_throughput_rps"]
    b = out["serving_wire_binary_throughput_rps"]
    out["serving_wire_speedup"] = round(b / j, 3) if j else None
    _log(f"serving wire: binary/json throughput ratio "
         f"{out['serving_wire_speedup']}")

    # -- codec microbench: framing cost without socket noise ----------------
    # The server-side work a request batch buys before scoring: encode
    # on the client, decode + validate into Rows on the server.  This
    # is where the binary format's zero-copy columns pay — JSON pays
    # json.loads + per-row parse allocations.
    import json as json_mod
    import time as time_mod

    from photon_ml_tpu.serving import wire as wire_mod

    runtime = ScoringRuntime(
        workload.model, workload.index_maps,
        RuntimeConfig(max_batch_size=64, hot_entities=4096),
    )
    parser = runtime._parser
    batch = [workload.request(i) for i in range(512)]
    reps = 5 if SMALL else 20

    def timed(fn) -> float:
        fn()  # warm
        t0 = time_mod.perf_counter()
        for _ in range(reps):
            fn()
        return (time_mod.perf_counter() - t0) / reps

    def json_path():
        raw = json_mod.dumps({"rows": batch}).encode()
        rows = json_mod.loads(raw)["rows"]
        return [parser.parse(r) for r in rows]

    def binary_path():
        raw = wire_mod.encode_request(batch)
        return wire_mod.decode_request(raw, parser)

    t_json = timed(json_path)
    t_bin = timed(binary_path)
    out["serving_wire_codec_json_ms"] = round(t_json * 1e3, 3)
    out["serving_wire_codec_binary_ms"] = round(t_bin * 1e3, 3)
    out["serving_wire_codec_speedup"] = round(t_json / t_bin, 2)
    _log(
        f"serving wire codec (512 rows): json {t_json * 1e3:.2f} ms, "
        f"binary {t_bin * 1e3:.2f} ms — {t_json / t_bin:.1f}x"
    )

    # -- adaptive vs static micro-batching ---------------------------------
    for label, adaptive in (("static", False), ("adaptive", True)):
        svc = ScoringService(
            ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=64, hot_entities=4096),
            ),
            BatcherConfig(
                max_batch_size=64, max_wait_us=1000, max_queue=1024,
                adaptive_wait=adaptive,
            ),
        )
        with svc:
            loadgen.open_loop(  # warmup
                svc.submit, workload.request,
                rate_rps=rate / 2, duration_s=0.5,
            )
            report = loadgen.open_loop(
                svc.submit, workload.request,
                rate_rps=rate, duration_s=duration,
            )
        snap = report.snapshot()
        _log(
            f"serving batching[{label}]: open-loop @{rate:g} rps p50 "
            f"{snap['latency_p50_ms']} / p99 {snap['latency_p99_ms']} / "
            f"p99.9 {snap['latency_p999_ms']} ms"
        )
        out.update({
            f"serving_{label}_open_p50_ms": snap["latency_p50_ms"],
            f"serving_{label}_open_p99_ms": snap["latency_p99_ms"],
            f"serving_{label}_open_p999_ms": snap["latency_p999_ms"],
        })
    return out


def _bench_serving_fleet(workload) -> dict:
    """Fleet tier gates (serving/fleet.py): whole HOSTS behind one
    ``FleetRouter`` with a ``QuotaCoordinator`` leasing each tenant's
    fleet budget across hosts.

    - ``serving_fleet_host_kill_pass``: the ``host_kill`` scenario at
      >= 120 rps — a host's listener dies mid-phase and returns — must
      cost ZERO failed requests and ZERO rejections for the in-quota
      tenant (the ReplicaSupervisor's gate, one tier up).
    - ``serving_fleet_quota_partition_pass``: the ``quota_partition``
      scenario — every host's LeaseClient loses the coordinator — must
      hold fleet-wide admission within ONE LEASE WINDOW of the budget
      (degrade-to-last-lease: never unlimited, never zero) and recover
      to exact enforcement after heal, with zero non-shed failures.
      ``serving_fleet_quota_error_rps`` is the measured partition-phase
      over-admission rate; its allowance is one lease window spread
      over the phase.
    """
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.fleet import (
        FleetBudget, FleetRouter, LocalHost, QuotaCoordinator,
    )
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.tenancy import TenancyConfig, TenantSpec

    n_hosts = 2 if SMALL else 3
    kill_rate = 120.0 if SMALL else 240.0
    acme_budget = 600.0 if SMALL else 1200.0
    budget_rps = 60.0
    burst_s = 0.25
    lease_ttl_s = 1.0
    rt_cfg = RuntimeConfig(max_batch_size=32, hot_entities=1024)
    tenancy = TenancyConfig(tenants=(
        TenantSpec(
            name="acme", quota_rps=acme_budget / n_hosts,
            burst=max(acme_budget * burst_s / n_hosts, 1.0),
            max_queue=512,
        ),
        TenantSpec(
            name="metered", quota_rps=budget_rps / n_hosts,
            burst=max(budget_rps * burst_s / n_hosts, 1.0),
            max_queue=512,
        ),
    ))
    batcher_cfg = BatcherConfig(
        max_batch_size=32, max_wait_us=1000, max_queue=1024,
        tenancy=tenancy,
    )

    def make_request(i: int, phase, tenant: str) -> dict:
        req = dict(workload.request(i))
        req["tenant"] = tenant
        return req

    _log(f"serving fleet: starting {n_hosts} HTTP hosts + router...")
    hosts = [
        LocalHost(
            f"host{i}",
            ScoringService(
                ScoringRuntime(workload.model, workload.index_maps, rt_cfg),
                batcher_cfg,
            ),
        ).start()
        for i in range(n_hosts)
    ]
    coordinator = QuotaCoordinator(
        [
            FleetBudget("acme", acme_budget, burst_s=burst_s),
            FleetBudget("metered", budget_rps, burst_s=burst_s),
        ],
        lease_ttl_s=lease_ttl_s,
    )
    clients = [h.attach_lease_client(coordinator).start() for h in hosts]
    router = FleetRouter(
        [h.base_url for h in hosts], probe_interval_s=0.1
    ).start()
    out: dict = {}
    try:
        for i in range(n_hosts * 4):  # warm ladders + settle leases
            router.score(make_request(i, None, "acme"))
        time.sleep(1.5 * lease_ttl_s)

        report = loadgen.run_fleet_scenario(
            router.submit, make_request,
            loadgen.SCENARIOS["host_kill"], tenant="acme",
            base_rate_rps=kill_rate,
            actions={
                "kill_host": hosts[0].kill,
                "restart_host": hosts[0].restart,
            },
        )
        kill_pass = (
            report.failed == 0 and report.shed == 0
            and report.completed >= kill_rate
        )
        snap = report.snapshot()
        _log(
            f"serving fleet host_kill: {report.completed} ok / "
            f"{report.shed} shed / {report.failed} failed at "
            f"{kill_rate:g} rps, p99 "
            f"{snap['phases']['kill']['latency_p99_ms']} ms in the kill "
            f"phase; gate {'PASS' if kill_pass else 'FAIL'}"
        )
        out.update({
            "serving_fleet_hosts": n_hosts,
            "serving_fleet_host_kill_rate_rps": kill_rate,
            "serving_fleet_host_kill_completed": report.completed,
            "serving_fleet_host_kill_rejected": report.shed,
            "serving_fleet_host_kill_failed": report.failed,
            "serving_fleet_host_kill_kill_p99_ms": (
                snap["phases"]["kill"]["latency_p99_ms"]
            ),
            "serving_fleet_host_kill_pass": kill_pass,
        })

        def partition() -> bool:
            for lc in clients:
                lc.partitioned = True
            return True

        def heal() -> bool:
            for lc in clients:
                lc.partitioned = False
            return True

        q_report = loadgen.run_fleet_scenario(
            router.submit, make_request,
            loadgen.SCENARIOS["quota_partition"], tenant="metered",
            base_rate_rps=2.5 * budget_rps,
            actions={"partition": partition, "heal": heal},
            seed=1,
        )
        burst_total = budget_rps * burst_s
        q_pass = q_report.failed == 0
        quota_error_rps = None
        for name, duration, _, pr in q_report.phases:
            window = lease_ttl_s if name == "partition" else 0.0
            bound = (
                budget_rps * (duration + window) * 1.15
                + burst_total + 10
            )
            if pr.completed > bound or (
                pr.completed < 0.4 * budget_rps * duration
            ):
                q_pass = False
            if name == "partition":
                quota_error_rps = round(
                    max(0.0, pr.completed / duration - budget_rps), 2
                )
        if any(lc.stale for lc in clients):
            q_pass = False  # renewal never recovered after heal
        _log(
            f"serving fleet quota_partition: {q_report.completed} "
            f"admitted / {q_report.shed} shed / {q_report.failed} "
            f"failed against budget {budget_rps:g} rps; partition "
            f"over-admission {quota_error_rps} rps (allowance: one "
            f"{lease_ttl_s:g}s lease window); gate "
            f"{'PASS' if q_pass else 'FAIL'}"
        )
        out.update({
            "serving_fleet_quota_budget_rps": budget_rps,
            "serving_fleet_quota_admitted": q_report.completed,
            "serving_fleet_quota_shed": q_report.shed,
            "serving_fleet_quota_failed": q_report.failed,
            "serving_fleet_quota_error_rps": quota_error_rps,
            "serving_fleet_lease_window_s": lease_ttl_s,
            "serving_fleet_quota_partition_pass": q_pass,
        })
    finally:
        router.stop()
        for h in hosts:
            h.stop()
    return out


def bench_tracing() -> dict:
    """Distributed-tracing propagation overhead (PR 17): the same
    closed-loop in-process serving workload as bench_serving, A/B'd with
    trace-context propagation OFF (sink-less hub — every adopt/span is
    the one-branch no-op) vs ON at the DEFAULT 1/256 head sampling
    against an active hub.  The ON leg pays, per request, exactly what
    the transport edges pay: mint the context, render the header string,
    re-parse it, adopt it, and open the hop span (emitted for the ~0.4%
    sampled traces, elided otherwise).  Gate: overhead <= 1% of
    baseline throughput.

    The GATED number is deterministic: per-request propagation cost
    (tight-loop median over the exact wrapper, sans the submit) divided
    by the baseline per-request service time (clients / closed-loop
    rps) — the throughput delta the A/B converges to in expectation.
    The raw alternating off/on closed-loop pairs are still run and
    reported, but this box's throughput drifts 10-30% between
    back-to-back IDENTICAL legs, so the raw delta measures machine
    weather, not the ~0.3% tracing cost."""
    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload
    from photon_ml_tpu.telemetry.recorder import FlightRecorder

    n_entities = 10_000
    duration = 1.5 if SMALL else 4.0
    clients = 16
    _log(f"tracing: building synthetic GAME model ({n_entities} "
         "entities)...")
    workload = SyntheticWorkload(
        n_entities=n_entities, fixed_dim=64, re_dim=8, seed=11
    )
    runtime = ScoringRuntime(
        workload.model, workload.index_maps,
        RuntimeConfig(max_batch_size=64, hot_entities=4096),
    )
    service = ScoringService(runtime, BatcherConfig(
        max_batch_size=64, max_wait_us=1000, max_queue=1024,
    ))

    # ON leg: an ACTIVE hub (in-memory ring sink — no disk I/O in the
    # timed window) at the default head-sampling rate, driven the way
    # the HTTP edge drives it.
    traced_hub = telemetry_mod.Telemetry(sinks=[FlightRecorder()])
    TraceContext = telemetry_mod.TraceContext

    def submit_traced(request):
        ctx = traced_hub.new_trace()
        wire = ctx.header_value()          # what the transport renders
        parsed = TraceContext.parse(wire)  # ...and the far edge parses
        with traced_hub.adopt(parsed), \
                traced_hub.span("serving.http_score"):
            return service.submit(request)

    pairs = 3
    off_rps: list = []
    on_rps: list = []
    with service:
        loadgen.closed_loop(
            service.submit, workload.request, clients=4, duration_s=0.5
        )
        for k in range(pairs):
            for leg, submit, sink in (
                ("off", service.submit, off_rps),
                ("on", submit_traced, on_rps),
            ):
                report = loadgen.closed_loop(
                    submit, workload.request,
                    clients=clients, duration_s=duration,
                )
                sink.append(report.snapshot()["throughput_rps"])
                _log(f"tracing: pair {k} leg {leg}: {sink[-1]} rps")
    # Deterministic per-request propagation cost: the SAME wrapper with
    # the submit replaced by a no-op, tight loop, median of 5 runs.
    def noop_submit(request):
        return request

    n_iter = 50_000
    costs = []
    for _ in range(5):
        t0 = time.perf_counter()
        for i in range(n_iter):
            ctx = traced_hub.new_trace()
            wire = ctx.header_value()
            parsed = TraceContext.parse(wire)
            with traced_hub.adopt(parsed), \
                    traced_hub.span("serving.http_score"):
                noop_submit(None)
        costs.append((time.perf_counter() - t0) / n_iter)
    cost_s = float(np.median(costs))
    traced_hub.close()

    base = float(np.median(off_rps))
    # Closed loop: rps = clients / t_req, so adding cost_s per request
    # costs cost_s / t_req = cost_s * rps / clients of throughput.
    t_req = clients / base if base > 0 else float("inf")
    overhead = cost_s / t_req
    raw_deltas = [
        round(1.0 - on / off, 4) if off > 0 else None
        for off, on in zip(off_rps, on_rps)
    ]
    _log(f"tracing: {cost_s * 1e6:.2f} us/request propagation cost over "
         f"{t_req * 1e3:.2f} ms/request baseline -> {overhead * 100:.3f}% "
         f"throughput overhead (gate: <= 1%); raw A/B deltas "
         f"{raw_deltas} (machine noise)")
    return {
        "tracing_baseline_rps": round(base, 1),
        "tracing_on_rps": round(float(np.median(on_rps)), 1),
        "tracing_off_rps": off_rps,
        "tracing_on_rps_legs": on_rps,
        "tracing_raw_ab_deltas": raw_deltas,
        "tracing_cost_us_per_request": round(cost_s * 1e6, 3),
        "tracing_sample_every": traced_hub.trace_sample_every,
        "tracing_overhead_frac": round(overhead, 5),
        "tracing_overhead_pass": overhead <= 0.01,
    }


def bench_freshness() -> dict:
    """Continuous train→serve loop (PR 12): the wall cost of staying
    fresh.  Two measurements:

    1. The ``freshness`` loadgen scenario against a 2-replica supervised
       service — an online-refined delta publishes and hot-applies
       MID-PHASE under open-loop traffic.  Reports p50/p99 and the
       zero-failed-requests gate (reported, not asserted, so a
       regression shows in the bench diff) plus the event→servable
       freshness SLO actually achieved.
    2. Delta apply vs full reload of the SAME refined model: the delta
       path's whole point is patching K changed rows instead of
       rebuilding n_entities tables from disk — both walls and the
       ratio, over several refine→publish→apply cycles.
    """
    import tempfile

    from photon_ml_tpu.freshness.applier import DeltaApplier
    from photon_ml_tpu.freshness.online import (
        LabeledEvent,
        OnlineRefiner,
        RefinerConfig,
    )
    from photon_ml_tpu.freshness.publisher import DeltaPublisher
    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    n_entities = 5_000 if SMALL else 20_000
    n_events = 200
    n_cycles = 2 if SMALL else 4  # quiet cycles after the scenario one
    rate = 150.0 if SMALL else 400.0
    workload = SyntheticWorkload(
        n_entities=n_entities, fixed_dim=32, re_dim=8, seed=21
    )
    rng = np.random.default_rng(22)
    rt_cfg = RuntimeConfig(max_batch_size=32, hot_entities=1024)

    def drift_events(now_wall: float) -> list:
        events = []
        for _ in range(n_events):
            events.append(LabeledEvent(
                features={
                    workload.fixed_shard: rng.normal(
                        size=workload.fixed_dim
                    ).astype(np.float32),
                    workload.re_shard: rng.normal(
                        size=workload.re_dim
                    ).astype(np.float32),
                },
                ids={
                    workload.entity_key: f"u{rng.integers(n_entities)}"
                },
                label=float(rng.integers(2)),
                wall_epoch=now_wall,
            ))
        return events

    def make_request(i: int, phase) -> dict:
        req = workload.request(i)
        if phase.entity_pool is not None:
            lo, hi = phase.entity_pool
            span = max(1, int((hi - lo) * n_entities))
            req["ids"][workload.entity_key] = (
                f"u{int(lo * n_entities) + i % span}"
            )
        return req

    out: dict = {}
    with tempfile.TemporaryDirectory(prefix="bench_freshness_") as td:
        v1_dir = os.path.join(td, "v1")
        _log(f"freshness: saving base model ({n_entities} entities)...")
        save_game_model(workload.model, workload.index_maps, v1_dir)

        def factory() -> ScoringRuntime:
            return ScoringRuntime.load(v1_dir, rt_cfg)

        supervisor = ReplicaSupervisor(
            factory, n_replicas=2, probe_interval_s=0.1
        )
        service = ScoringService(supervisor, BatcherConfig(
            max_batch_size=32, max_wait_us=1000, max_queue=1024,
        ))
        publisher = DeltaPublisher(os.path.join(td, "publications"))
        applier = DeltaApplier(service, publisher.root)
        base_model, _ = ScoringRuntime.load_model(v1_dir)
        event_to_servable: list[float] = []
        apply_walls: list[float] = []
        delta_rows: list[int] = []
        # Each cycle warm-starts a refiner from the model the replicas
        # currently serve (bitwise: the previous cycle's refined model),
        # so every delta's base checksum matches the live tables.
        state = {"base": base_model, "event_wall": 0.0, "refiner": None}

        def publish_delta() -> dict:
            event_wall = time.time()
            state["event_wall"] = event_wall
            refiner = OnlineRefiner(state["base"], RefinerConfig(seed=23))
            refiner.consume(drift_events(event_wall))
            state["refiner"] = refiner
            pub = refiner.publish(publisher)
            delta_rows.append(pub.n_changed_rows)
            return {"seq": pub.seq, "rows": pub.n_changed_rows}

        def apply_delta_action() -> dict:
            t0 = time.perf_counter()
            results = applier.poll_once()
            apply_walls.append(time.perf_counter() - t0)
            now_wall = time.time()
            event_to_servable.append(now_wall - state["event_wall"])
            state["base"] = state["refiner"].refined_model()
            return {
                "applied": [r.status for r in results],
                "version": service.swapper.version,
            }

        with service:
            report = loadgen.run_scenario(
                service.submit, make_request,
                loadgen.SCENARIOS["freshness"],
                base_rate_rps=rate,
                actions={
                    "publish_delta": publish_delta,
                    "apply_delta": apply_delta_action,
                },
            )
            # Quiet cycles: more apply-wall / event→servable samples
            # without traffic jitter.
            for _ in range(n_cycles):
                publish_delta()
                apply_delta_action()
            # The honest alternative to the delta path: a FULL disk
            # reload of the same refined model on the same service.
            refined_dir = os.path.join(td, "refined")
            save_game_model(
                state["base"], workload.index_maps, refined_dir
            )
            t0 = time.perf_counter()
            full = service.reload(refined_dir)
            full_reload_wall = time.perf_counter() - t0
        snap = report.snapshot()
        zero_failed = report.errors == 0 and report.rejected == 0
        apply_ms = round(float(np.median(apply_walls)) * 1e3, 2)
        e2s_p50 = round(float(np.percentile(event_to_servable, 50)), 3)
        e2s_p99 = round(float(np.percentile(event_to_servable, 99)), 3)
        _log(
            f"freshness scenario @ {rate:g} rps: {report.completed} ok / "
            f"{report.rejected} shed / {report.errors} errors, p99 "
            f"{snap['latency_p99_ms']} ms, zero-failed gate "
            f"{'PASS' if zero_failed else 'FAIL'}; event→servable p50 "
            f"{e2s_p50}s p99 {e2s_p99}s; delta apply {apply_ms} ms vs "
            f"full reload {round(full_reload_wall * 1e3, 1)} ms "
            f"({full.status})"
        )
        out.update({
            "freshness_scenario_p50_ms": snap["latency_p50_ms"],
            "freshness_scenario_p99_ms": snap["latency_p99_ms"],
            "freshness_scenario_completed": report.completed,
            "freshness_scenario_rejected": report.rejected,
            "freshness_scenario_errors": report.errors,
            "freshness_zero_failed": zero_failed,
            "freshness_event_to_servable_p50_s": e2s_p50,
            "freshness_event_to_servable_p99_s": e2s_p99,
            "freshness_delta_apply_ms": apply_ms,
            "freshness_full_reload_ms": round(full_reload_wall * 1e3, 1),
            "freshness_reload_speedup": round(
                full_reload_wall * 1e3 / max(apply_ms, 1e-3), 1
            ),
            "freshness_delta_rows_per_cycle": int(np.median(delta_rows)),
            "freshness_deltas_applied": applier.applied,
        })
    return out


def bench_tuning() -> dict:
    """Tuning orchestrator (PR 4): sequential vs parallel-4 wall clock of
    the SAME synthetic GLM λ sweep (GridProposer over a fixed λ path, so
    both runs fit the identical trial set), plus best-metric parity.
    λ-path warm starts stay ON — parity within 1e-6 is the acceptance
    bar: the L2 problem is strictly convex, so different warm-start
    availability under parallel scheduling must not move the selected
    optimum beyond solver tolerance."""
    import tempfile as _tf

    from photon_ml_tpu.drivers.glm_driver import make_fit_once
    from photon_ml_tpu.tuning.executor import (
        TuningConfig,
        TuningOrchestrator,
    )
    from photon_ml_tpu.tuning.scheduler import GridProposer, SearchSpace
    from photon_ml_tpu.tuning.state import TuningJournal

    n_rows = 20_000 if SMALL else 120_000
    d = 256
    rng = np.random.default_rng(17)
    X = rng.normal(size=(n_rows, d)).astype(np.float32)
    w_true = (
        rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    ).astype(np.float32)
    y = (
        rng.uniform(size=n_rows) < 1.0 / (1.0 + np.exp(-(X @ w_true)))
    ).astype(np.float32)
    split = int(n_rows * 0.8)
    lambdas = np.geomspace(1e-4, 1e2, 8)
    _log(f"tuning: {split} train rows x {d} features, "
         f"{len(lambdas)}-point λ sweep...")
    fit_once = make_fit_once(
        X[:split], y[:split], X[split:], y[split:],
        task="logistic", reg_type="l2", max_iters=60, tolerance=1e-8,
    )
    fit_once(np.array([1.0]), 0, None)  # compile outside the timing

    space = SearchSpace.create([(1e-5, 1e3)], log_scale=True,
                               names=["lambda"])

    def sweep(workers: int) -> tuple:
        with _tf.TemporaryDirectory(prefix="bench_tuning_") as td:
            journal = TuningJournal(td, fsync=False)
            cfg = TuningConfig(
                max_trials=len(lambdas), workers=workers,
                maximize=fit_once.larger_is_better,
            )
            t0 = time.perf_counter()
            result = TuningOrchestrator(
                space, fit_once,
                GridProposer(space, [[lam] for lam in lambdas]),
                cfg, journal,
            ).run()
            wall = time.perf_counter() - t0
            journal.close()
        return result, wall

    seq, seq_wall = sweep(1)
    par, par_wall = sweep(4)
    delta = abs(seq.best_metric - par.best_metric)
    _log(f"tuning: sequential {seq_wall:.2f}s vs parallel-4 "
         f"{par_wall:.2f}s ({seq_wall / par_wall:.2f}x), best metric "
         f"{seq.best_metric:.6f} vs {par.best_metric:.6f} "
         f"(delta {delta:.2e})")
    return {
        "tuning_seq_seconds": round(seq_wall, 3),
        "tuning_par4_seconds": round(par_wall, 3),
        "tuning_speedup": round(seq_wall / par_wall, 3),
        "tuning_best_lambda": seq.best_params[0],
        "tuning_best_metric_delta": delta,
        "tuning_parity_ok": bool(delta <= 1e-6),
        "tuning_trials": seq.n_trials,
    }


def bench_solvers() -> dict:
    """Distributed solver A/B (PR 18): consensus-ADMM over ≥2 shards vs
    streamed OWL-QN on the SAME elastic-net lasso λ grid.

    The claim under test is COMMUNICATION, not FLOPs: OWL-QN pays one
    logical all-reduce per objective evaluation (every streamed pass
    publishes ``solver_allreduce_count`` — optim/streaming.py), while
    ADMM folds each outer iteration into ONE fixed-size psum
    (solvers/admm.py), so both sides are read off the same counter.
    The OWL-QN leg runs ``batch_linesearch=False``: batching the
    line-search bracket into one pass is a single-device streaming
    trick — on a real mesh every candidate evaluation is its own psum,
    and the bench counts the communication a mesh would pay.  The
    design matrix is moderately ill-conditioned (geometric spectrum
    1 → 0.02) so first-order line searches pay their usual toll; the
    squared-loss task also exercises ADMM's cached-eigendecomposition
    ridge x-update (one Gram factorization for the whole grid AND
    every ρ).  Gates: ≥5x fewer reduces per solve AND ≤1e-5 relative
    objective gap (both solvers scored by one resident evaluator)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.data.streaming import make_streaming_glm_data
    from photon_ml_tpu.ops import losses as losses_lib
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        GlmOptimizationProblem,
        OptimizerConfig,
        OptimizerType,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext
    from photon_ml_tpu.optim.streaming import streaming_run_grid
    from photon_ml_tpu.parallel.distributed import shard_glm_data
    from photon_ml_tpu.solvers import sharded as solvers_sharded

    n, d = (2048, 48) if SMALL else (8192, 96)
    n_shards = 4
    rng = np.random.default_rng(7)
    Z = rng.normal(size=(n, d))
    Q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    spec = np.geomspace(1.0, 0.02, d)
    X = ((Z * spec) @ Q.T / np.sqrt(d)).astype(np.float32)
    w_true = (
        rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    ).astype(np.float32)
    y = (X @ w_true + 0.1 * rng.normal(size=n)).astype(np.float32)
    grid = [3e-1, 1e-1, 3e-2]
    reg = RegularizationContext.elastic_net(0.5)
    loss = losses_lib.get("squared")
    Xj, yj = jnp.asarray(X), jnp.asarray(y)

    @jax.jit
    def objective(w, l1, l2):
        m = Xj @ w
        return (jnp.sum(loss.value(m, yj)) + l1 * jnp.sum(jnp.abs(w))
                + 0.5 * l2 * jnp.vdot(w, w))

    def score(results):
        return {
            lam: float(objective(
                jnp.asarray(model.coefficients.means),
                reg.l1_weight(lam), reg.l2_weight(lam),
            ))
            for lam, model, _res in results
        }

    def make_problem(solver=None, options=()):
        return GlmOptimizationProblem("linear", GlmOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer=OptimizerType.LBFGS, max_iters=200,
                tolerance=1e-8, solver=solver, solver_options=options,
            ),
            regularization=reg,
        ))

    tel = telemetry_mod.current()

    def counted(run):
        c0 = tel.counter("solver_allreduce_count").value
        b0 = tel.counter("solver_allreduce_bytes_total").value
        t0 = time.perf_counter()
        results = run()
        wall = time.perf_counter() - t0
        return (results, wall,
                tel.counter("solver_allreduce_count").value - c0,
                tel.counter("solver_allreduce_bytes_total").value - b0)

    _log(f"solvers: {n} rows x {d} features, {len(grid)}-point L1 grid, "
         f"ADMM over {n_shards} shards vs streamed OWL-QN...")
    stream = make_streaming_glm_data(X, y, chunk_rows=max(256, n // 8))
    p_ref = make_problem()
    ref_run = lambda: streaming_run_grid(
        p_ref, stream, grid, batch_linesearch=False
    )
    ref_run()  # compile outside the timing
    ref_results, ref_wall, ref_reduces, ref_bytes = counted(ref_run)

    p_admm = make_problem("admm", (
        ("rho", "0.05"), ("reltol", "1e-4"), ("over_relaxation", "1.8"),
    ))
    dist = shard_glm_data(X, y, None, n_shards=n_shards)
    admm_run = lambda: solvers_sharded.run_grid_sharded(
        p_admm, dist, None, grid
    )
    admm_run()  # compile outside the timing
    admm_results, admm_wall, admm_reduces, admm_bytes = counted(admm_run)

    f_ref, f_admm = score(ref_results), score(admm_results)
    gap = max(
        abs(f_admm[lam] - f_ref[lam]) / max(1.0, abs(f_ref[lam]))
        for lam in f_ref
    )
    reduce_ratio = ref_reduces / max(1, admm_reduces)
    _log(f"solvers: reduces/solve owlqn {ref_reduces / len(grid):.0f} vs "
         f"admm {admm_reduces / len(grid):.0f} ({reduce_ratio:.1f}x), "
         f"bytes {ref_bytes / 1e6:.2f} vs {admm_bytes / 1e6:.2f} MB, "
         f"wall {ref_wall:.2f}s vs {admm_wall:.2f}s, "
         f"objective gap {gap:.2e}")
    return {
        "solvers_owlqn_reduces_per_solve": round(ref_reduces / len(grid), 1),
        "solvers_admm_reduces_per_solve": round(admm_reduces / len(grid), 1),
        "solvers_reduce_ratio": round(reduce_ratio, 2),
        "solvers_owlqn_bytes": ref_bytes,
        "solvers_admm_bytes": admm_bytes,
        "solvers_owlqn_wall_seconds": round(ref_wall, 3),
        "solvers_admm_wall_seconds": round(admm_wall, 3),
        "solvers_objective_gap": gap,
        "solvers_gap_ok": bool(gap <= 1e-5),
        "solvers_reduce_ratio_ok": bool(reduce_ratio >= 5.0),
    }


def bench_cluster() -> dict:
    """Cluster control plane (ISSUE 19): the 3-host drill as a gate,
    plus a distribution wire microbench.

    The drill (the same one ``python -m photon_ml_tpu.cluster
    --selfcheck`` runs) kills the leader quota-coordinator replica
    under >= 120 rps open-loop load — failover must land within one
    lease TTL with ZERO failed requests and journal-replay-bounded
    over-admission — then cold-starts a third host from the newest
    snapshot publication over HTTP (bit-identical scores) while
    another host drains.  The microbench times a fresh snapshot fetch
    through :class:`PublicationClient` — every byte sha256-verified
    end to end — so the reported MB/s is the VERIFIED ingest rate a
    joining host actually sees, not raw socket throughput."""
    import shutil
    import tempfile

    from photon_ml_tpu.cluster import PublicationClient, PublicationServer
    from photon_ml_tpu.cluster.__main__ import run_cluster_drill
    from photon_ml_tpu.freshness.publisher import DeltaPublisher

    out: dict = {}
    _log("cluster: 3-host drill (coordinator kill + join/drain + "
         "cold start)...")
    td = tempfile.mkdtemp(prefix="bench_cluster_")
    try:
        t0 = time.perf_counter()
        failures = run_cluster_drill(
            td, drill_rate=60.0 if SMALL else 150.0, lease_ttl_s=1.0
        )
        out["cluster_drill_wall_seconds"] = round(
            time.perf_counter() - t0, 2
        )
        out["cluster_drill_ok"] = not failures
        if failures:
            out["cluster_drill_failures"] = failures[:3]

        # Verified-ingest microbench: one snapshot, fetched cold.
        payload_mb = 2 if SMALL else 16
        root = os.path.join(td, "bench_pub_root")
        model = os.path.join(td, "bench_model")
        os.makedirs(model)
        rng = np.random.default_rng(5)
        for i in range(4):
            with open(os.path.join(model, f"block{i}.bin"), "wb") as f:
                f.write(rng.bytes(payload_mb * 1024 * 1024 // 4))
        pub = DeltaPublisher(root, fsync=False).publish_snapshot(model)
        server = PublicationServer(root).serve()
        try:
            client = PublicationClient(
                server.base_url, os.path.join(td, "bench_cache")
            )
            remote = [
                p for p in client.publications() if p.seq == pub.seq
            ][0]
            t0 = time.perf_counter()
            client.fetch(remote)
            fetch_wall = time.perf_counter() - t0
        finally:
            server.close()
        out["cluster_fetch_mb_per_sec"] = round(
            payload_mb / fetch_wall, 1
        )
        _log(f"cluster: drill "
             f"{'ok' if out['cluster_drill_ok'] else 'FAILED'} in "
             f"{out['cluster_drill_wall_seconds']}s, verified fetch "
             f"{out['cluster_fetch_mb_per_sec']} MB/s "
             f"({payload_mb} MB snapshot)")
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return out


def main() -> None:
    # Sink-less but ENABLED telemetry hub: the streamed/ooc sections'
    # prefetch pipelines feed their TransferStats into its registry
    # (h2d_gbps, stall counters — data/prefetch.py), events stay
    # one-branch no-ops.  The snapshot rides the bench JSON so BENCH
    # trajectory files carry stall/bandwidth/compile attribution.
    from photon_ml_tpu import telemetry as telemetry_mod

    bench_tel = telemetry_mod.Telemetry(enabled=True, sinks=[])
    prev_tel = telemetry_mod.set_current(bench_tel)

    baseline = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            baseline = json.load(f)

    def ratio(value, key, smaller_is_better=False):
        base = baseline.get(key)
        if not base:
            return 1.0
        return round(base / value if smaller_is_better else value / base, 4)

    extra = {}
    # The plain-XLA calibration rate swings ~2x WITHIN a session (42.6 vs
    # 25.7 GB/s measured 90 s apart, best-of-3 each, while the packed
    # kernels' achieved GB/s stayed put) — one sample is unreliable, and
    # it normalizes the headline.  Sample at several points through the
    # run and use the MEDIAN; every sample is reported.
    chip_samples: list[float] = []

    def sample_chip():
        try:  # calibration must never sink the bench
            chip_samples.append(bench_chip_stream())
        except Exception as e:
            extra.setdefault("chip_stream_error", str(e))

    def chip_median():
        return float(np.median(chip_samples)) if chip_samples else None

    sample_chip()
    game_iters = None
    if ONLY in ("", "game"):
        g = bench_game_cd()
        extra["game_cd_iters_per_sec"] = round(g["iters_per_sec"], 3)
        extra["game_cd_spread_pct"] = g["spread_pct"]
        extra["game_cd_coordinate_seconds"] = g["coordinate_seconds"]
        # PRIMARY ratio is RAW against the round-3 same-methodology
        # baseline: measured CD iters/s is bandwidth-INSENSITIVE
        # (1.52 it/s at 23.9 GB/s, 1.524 at 28.2 — identical raw while
        # the chip stream moved 18%), so a linear bandwidth
        # normalization, which VERDICT r3 suggested, would itself inject
        # ±25% cross-session noise.  The normalized quotient is still
        # reported for the record — bench_baseline.json game_cd_note.
        extra["game_cd_vs_baseline"] = ratio(
            g["iters_per_sec"], "game_cd_iters_per_sec"
        )
        game_iters = g["iters_per_sec"]  # per-gbps extras at END (final median)
        try:
            extra.update(bench_game_repack_ab())
        except Exception as e:  # new section: never sink the headline
            extra["game_repack_flop_reduction_pct"] = f"failed: {e}"
        try:
            extra.update(bench_game_device_scaling())
        except Exception as e:  # new section: never sink the headline
            extra["game_scaling_gate_ok"] = f"failed: {e}"
        sample_chip()
    if ONLY in ("", "game", "multire"):
        try:
            m = bench_game_multi_re()
            extra["game_multi_re_iters_per_sec"] = round(
                m["iters_per_sec"], 3
            )
            extra["game_multi_re_spread_pct"] = m["spread_pct"]
            extra["game_multi_re_coordinate_seconds"] = (
                m["coordinate_seconds"]
            )
            extra["game_multi_re_rows"] = m["rows"]
            extra["game_multi_re_vs_baseline"] = ratio(
                m["iters_per_sec"], "game_multi_re_iters_per_sec"
            )
        except Exception as e:  # new section: never sink the headline
            extra["game_multi_re_iters_per_sec"] = f"failed: {e}"
    if ONLY in ("", "driver"):
        cold, warm = bench_glm_driver()
        extra["glm_driver_wall_seconds_cold"] = round(cold, 2)
        extra["glm_driver_wall_seconds_warm"] = round(warm, 2)
        extra["glm_driver_cold_vs_baseline"] = ratio(
            cold, "glm_driver_wall_seconds_cold", smaller_is_better=True
        )
        extra["glm_driver_warm_vs_baseline"] = ratio(
            warm, "glm_driver_wall_seconds_warm", smaller_is_better=True
        )
        sample_chip()
    if ONLY in ("", "stream"):
        try:
            extra.update(bench_streaming())
        except Exception as e:  # new section: never sink the headline
            extra["stream_rows_per_sec"] = f"failed: {e}"
    if ONLY in ("", "avro"):
        try:
            extra.update(bench_avro_write())
        except Exception as e:  # new section: never sink the headline
            extra["avro_write_native_recs_per_sec"] = f"failed: {e}"
    if ONLY in ("", "serving"):
        try:
            extra.update(bench_serving())
        except Exception as e:  # new section: never sink the headline
            extra["serving_throughput_rps"] = f"failed: {e}"
    if ONLY in ("", "freshness"):
        try:
            extra.update(bench_freshness())
        except Exception as e:  # new section: never sink the headline
            extra["freshness_delta_apply_ms"] = f"failed: {e}"
    if ONLY in ("", "tuning"):
        try:
            extra.update(bench_tuning())
        except Exception as e:  # new section: never sink the headline
            extra["tuning_seq_seconds"] = f"failed: {e}"
    if ONLY in ("", "solvers"):
        try:
            extra.update(bench_solvers())
        except Exception as e:  # new section: never sink the headline
            extra["solvers_reduce_ratio"] = f"failed: {e}"
    if ONLY in ("", "chaos"):
        try:
            extra.update(bench_chaos())
        except Exception as e:  # new section: never sink the headline
            extra["chaos_disabled_overhead_frac"] = f"failed: {e}"
    if ONLY in ("", "telemetry"):
        try:
            extra.update(bench_telemetry())
        except Exception as e:  # new section: never sink the headline
            extra["telemetry_ops_plane_overhead_frac"] = f"failed: {e}"
    if ONLY in ("", "tracing"):
        try:
            extra.update(bench_tracing())
        except Exception as e:  # new section: never sink the headline
            extra["tracing_overhead_frac"] = f"failed: {e}"
    if ONLY in ("", "analysis"):
        try:
            extra.update(bench_analysis())
        except Exception as e:  # new section: never sink the headline
            extra["analysis_sanitizer_overhead_frac"] = f"failed: {e}"
    if ONLY in ("", "cluster"):
        try:
            extra.update(bench_cluster())
        except Exception as e:  # new section: never sink the headline
            extra["cluster_drill_ok"] = f"failed: {e}"
    out = {
        "metric": "logistic_glm_rows_per_sec",
        "unit": "rows/s",
        "extra": extra,
    }
    if ONLY in ("", "glm"):
        sample_chip()  # one sample adjacent to the kernel timing
        chip_gbps = chip_median()
        glm = bench_glm_throughput()
        rows_per_sec = glm["rows_per_sec"]
        out["value"] = round(rows_per_sec, 1)
        if chip_gbps:
            # Roofline fraction: achieved HBM GB/s of one fused
            # value+grad pass over the same-session stream calibration
            # (the bandwidth-bound ceiling for these sparse kernels).
            extra["kernel_achieved_gbps"] = round(glm["achieved_gbps"], 1)
            extra["kernel_bandwidth_frac"] = round(
                glm["achieved_gbps"] / chip_gbps, 3
            )
        # PRIMARY comparison: bandwidth-normalized (rows/s per GB/s of the
        # same-session stream calibration) vs the round-2 recorded quotient
        # — the chip drifts 24-90 GB/s between sessions (bench_baseline
        # "normalization_note").  Raw ratio kept as extra.vs_baseline_raw.
        base_per_gbps = baseline.get("logistic_glm_rows_per_sec_per_gbps")
        if chip_gbps and base_per_gbps:
            out["vs_baseline"] = round(
                (rows_per_sec / chip_gbps) / base_per_gbps, 4
            )
            extra["rows_per_sec_per_gbps"] = round(
                rows_per_sec / chip_gbps, 1
            )
            extra["vs_baseline_raw"] = ratio(
                rows_per_sec, "logistic_glm_rows_per_sec"
            )
        else:
            out["vs_baseline"] = ratio(
                rows_per_sec, "logistic_glm_rows_per_sec"
            )
            out["note"] = "chip calibration unavailable; raw rows/s ratio"
    else:
        # Debug-only partial run: never report a fake 0.0 regression.
        out["value"] = None
        out["vs_baseline"] = None
        out["note"] = f"primary metric skipped (BENCH_ONLY={ONLY})"
    # Final calibration record + chip-normalized game quotients, all
    # against the same end-of-run MEDIAN so every normalized number in
    # one bench line shares one calibration.
    chip_gbps = chip_median()
    if chip_samples:
        extra["chip_stream_gbps"] = round(chip_gbps, 1)
        extra["chip_stream_samples"] = [round(s, 1) for s in chip_samples]
    base_cd_per_gbps = baseline.get("game_cd_iters_per_sec_per_gbps")
    if game_iters is not None and chip_gbps and base_cd_per_gbps:
        extra["game_cd_iters_per_sec_per_gbps"] = round(
            game_iters / chip_gbps, 4
        )
        extra["game_cd_vs_baseline_normalized"] = round(
            (game_iters / chip_gbps) / base_cd_per_gbps, 4
        )
    # Telemetry metrics snapshot: embedded in the bench line (so BENCH
    # trajectory files carry it) AND written next to bench_baseline.json
    # for direct inspection.  The driver section installs its own hub
    # in-process, so its counters land in its output dir, not here.
    telemetry_mod.set_current(prev_tel)
    snap = bench_tel.snapshot()
    extra["telemetry_metrics"] = {
        "counters": snap["counters"],
        "gauges": snap["gauges"],
    }
    try:
        bench_tel.write_snapshot(
            os.path.join(os.path.dirname(BASELINE_FILE),
                         "bench_metrics.json")
        )
    except OSError:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--game-scaling-worker":
        _game_scaling_worker(int(sys.argv[2]))
    else:
        main()
