"""Headline benchmarks — all three BASELINE.json metrics.

1. ``logistic_glm_rows_per_sec`` (primary): fused value+gradient throughput
   of the sparse logistic objective — the hot op behind BASELINE's "1B-row
   logistic GLM epoch time" (epoch seconds = 1e9 / rows_per_sec per
   objective evaluation; SURVEY.md §3.1 hot loop).
2. ``game_cd_iters_per_sec``: full GAME coordinate-descent iterations
   (fixed effect + long-tailed per-user random effect) per second on a
   MovieLens-shaped synthetic — 10⁵ entities, zipf-tailed row counts
   (BASELINE metric "GAME coord-descent iters/sec").
3. ``glm_driver_wall_seconds``: end-to-end legacy GLM driver wall-clock
   (read → index → summarize → train λ grid → validate → select → write) on
   an a1a-shaped dataset (BASELINE config 1).

MEASUREMENT METHODOLOGY (fixed in round 2): iterations are chained inside
ONE jitted ``fori_loop`` and the clock stops only after a small slice of the
result is read back to host.  Round 1 timed a Python loop closed by
``jax.block_until_ready``, which on this TPU transport returns before the
computation finishes unless a host readback has primed the sync path — so
round 1's number (27-29 M rows/s) measured DISPATCH rate, not compute.  The
honest round-1 COO throughput, re-measured with this methodology, is
~0.95 M rows/s; that is the ``real_round1_rows_per_sec`` recorded in
bench_baseline.json.  ``vs_baseline`` continues to be reported against the
COMMITTED round-1 number for round-over-round continuity, and is therefore
a massive *understatement* of the real kernel speedup (~70x).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"} —
the primary metric in the required fields, the other two under "extra" with
their own vs_baseline ratios.

Env knobs: BENCH_SMALL=1 shrinks every workload (CI/smoke); BENCH_ONLY=
glm|game|driver runs a single section.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()

SMALL = os.environ.get("BENCH_SMALL") == "1"
ONLY = os.environ.get("BENCH_ONLY", "")

N_ROWS = 1 << (16 if SMALL else 20)
N_FEATURES = 1 << 13
NNZ_PER_ROW = 32
N_CHAINED = 10  # objective evals chained inside one jit
N_REPS = 3  # timed repetitions (min taken)

GAME_ENTITIES = 2_000 if SMALL else 100_000
GAME_FIXED_FEATURES = 512
GAME_FIXED_NNZ = 8
GAME_RE_DIM = 8
GAME_TIMED_ITERS = 1
GAME_BUCKET_GROWTH = 4.0  # consolidate the zipf tail: ~5 compiled shapes
GAME_ROW_CAP = 128

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def _read_sync(x) -> None:
    """Force true completion: read one element back to host."""
    np.asarray(x.ravel()[0:1])


def bench_chip_stream() -> float:
    """Chip calibration: GB/s of a plain XLA elementwise reduce over ~256 MB.

    The tunneled TPU's effective streaming rate varies ~2x between
    sessions (measured 47 vs ~90 GB/s on different days for the SAME
    committed code).  This number lets rows/s results be normalized
    across sessions; the sparse kernels are bandwidth-bound, so rows/s
    scales ~linearly with it.
    """
    import jax
    import jax.numpy as jnp

    x = jnp.ones((64 << 20,), jnp.float32)  # 256 MB

    @jax.jit
    def chain(x):
        def body(i, acc):
            return acc + jnp.sum(x * (1.0 + 1e-12 * acc))
        return jax.lax.fori_loop(0, 10, body, jnp.zeros((), jnp.float32))

    r = chain(x)
    _read_sync(r)
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        r = chain(x)
        _read_sync(r)
        best = min(best, (time.perf_counter() - t0) / 10)
    return x.nbytes / best / 1e9


def bench_glm_throughput() -> float:
    """rows/s of the fused sparse logistic value+grad (primary metric)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.objective import GlmObjective

    rng = np.random.default_rng(0)
    nnz = N_ROWS * NNZ_PER_ROW
    rows = np.repeat(np.arange(N_ROWS, dtype=np.int64), NNZ_PER_ROW)
    cols = rng.integers(0, N_FEATURES, size=nnz).astype(np.int64)
    values = rng.normal(size=nnz).astype(np.float32)
    w_true = (rng.normal(size=N_FEATURES) *
              (rng.uniform(size=N_FEATURES) < 0.2)).astype(np.float32)
    margins_true = np.zeros(N_ROWS, np.float32)
    np.add.at(margins_true, rows, values * w_true[cols.astype(np.int64)])
    y = (rng.uniform(size=N_ROWS) < 1 / (1 + np.exp(-margins_true))).astype(
        np.float32)

    if jax.default_backend() == "tpu":
        from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix

        X = build_pallas_matrix(rows, cols, values, N_ROWS, N_FEATURES)
    else:
        from photon_ml_tpu.ops.sparse import from_coo

        X = from_coo(rows, cols, values, N_ROWS, N_FEATURES)

    data = jax.device_put(GlmData(
        features=X,
        labels=jnp.asarray(y),
        weights=jnp.ones(N_ROWS, jnp.float32),
        offsets=jnp.zeros(N_ROWS, jnp.float32),
    ))
    obj = GlmObjective(losses.logistic)

    # Data is an ARGUMENT, not a closure constant: closed-over arrays get
    # baked into the HLO as literals (overflows the remote-compile transport).
    @jax.jit
    def chain(w, data):
        def body(i, w):
            val, grad = obj.value_and_grad(w, data, l2_weight=1.0)
            return w - 1e-4 * grad
        return jax.lax.fori_loop(0, N_CHAINED, body, w)

    _log("glm: compiling throughput chain...")
    w = jnp.zeros(N_FEATURES, jnp.float32)
    out = chain(w, data)
    _read_sync(out)  # compile + prime true sync

    best = np.inf
    for i in range(N_REPS):
        wp = jnp.full((N_FEATURES,), np.float32(1e-3 * (i + 1)))
        _read_sync(wp)
        t0 = time.perf_counter()
        out = chain(wp, data)
        _read_sync(out)  # force real completion
        best = min(best, (time.perf_counter() - t0) / N_CHAINED)

    return N_ROWS / best


def bench_game_cd() -> float:
    """Full coordinate-descent iterations per second on a MovieLens-shaped
    synthetic: one fixed effect over sparse global features + one per-user
    random effect with a zipf long tail of rows per user."""
    import scipy.sparse as sp

    from photon_ml_tpu.game.coordinates import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.data import (
        FixedEffectDataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext

    rng = np.random.default_rng(1)
    # Long-tailed rows per entity (MovieLens-like): zipf, capped so bucket
    # count (= compile count) stays bounded.
    sizes = np.minimum(rng.zipf(1.8, GAME_ENTITIES), GAME_ROW_CAP)
    n = int(sizes.sum())
    users = np.repeat(
        np.array([f"u{i}" for i in range(GAME_ENTITIES)], dtype=object),
        sizes,
    )
    perm = rng.permutation(n)
    users = users[perm]

    nnzf = n * GAME_FIXED_NNZ
    Xg = sp.csr_matrix(
        (rng.normal(size=nnzf).astype(np.float32),
         (np.repeat(np.arange(n, dtype=np.int64), GAME_FIXED_NNZ),
          rng.integers(0, GAME_FIXED_FEATURES, size=nnzf))),
        shape=(n, GAME_FIXED_FEATURES),
    )
    Xu = sp.csr_matrix(rng.normal(size=(n, GAME_RE_DIM)).astype(np.float32))
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    weights = np.ones(n, np.float32)

    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )
    fixed = FixedEffectCoordinate(
        "fixed",
        FixedEffectDataset(data=make_glm_data(Xg, y), n_global_rows=n),
        "logistic", opt, reg_weight=1.0,
    )
    _log(f"game: {n} rows, {GAME_ENTITIES} entities; grouping...")
    re_ds = build_random_effect_dataset(
        users, Xu, y, weights, bucket_growth=GAME_BUCKET_GROWTH
    )
    _log(f"game: {len(re_ds.blocks)} buckets "
         f"{[(b.n_entities, b.rows_per_entity) for b in re_ds.blocks]}")
    re = RandomEffectCoordinate(
        "per_user", re_ds,
        "logistic", opt, reg_weight=1.0, entity_key="userId",
    )
    cd = CoordinateDescent([fixed, re])

    import jax.numpy as jnp

    base = jnp.zeros(n, jnp.float32)
    _log("game: warmup iteration (compiles every bucket shape)...")
    warm = cd.run(base, n_iterations=1)  # warmup: compiles every bucket shape
    # The CD loop's per-update float(score_norm) already forces readbacks,
    # but sync explicitly anyway — same discipline as the GLM bench.
    _read_sync(warm.scores["per_user"])
    _log("game: warmup done; timing...")

    best = np.inf
    for _ in range(2):  # best-of-2 post-warmup: damp chip/run variance
        t0 = time.perf_counter()
        result = cd.run(base, n_iterations=GAME_TIMED_ITERS)
        _read_sync(result.scores["per_user"])
        best = min(best, time.perf_counter() - t0)
    _log(f"game: {GAME_TIMED_ITERS} iters in {best:.2f}s (best of 2)")
    return GAME_TIMED_ITERS / best


def bench_glm_driver() -> float:
    """Wall-clock of the full legacy GLM driver on an a1a-shaped dataset
    (1605 train / 2000 validate rows, 123 binary features, 3-point λ grid)."""
    import scipy.sparse as sp

    from photon_ml_tpu.data import libsvm
    from photon_ml_tpu.drivers import glm_driver

    rng = np.random.default_rng(2)
    n_train, n_val, d = (400, 200, 123) if SMALL else (1605, 2000, 123)
    X = sp.random(
        n_train + n_val, d, density=0.11, random_state=4, format="csr"
    )
    X.data[:] = 1.0
    w_true = rng.normal(size=d) * (rng.uniform(size=d) < 0.3)
    logits = X @ w_true - 0.5
    y = np.where(
        rng.uniform(size=n_train + n_val) < 1 / (1 + np.exp(-logits)),
        1.0, -1.0,
    )
    with tempfile.TemporaryDirectory() as td:
        train = os.path.join(td, "a1a_shaped.libsvm")
        val = os.path.join(td, "a1a_shaped.t.libsvm")
        libsvm.write_libsvm(train, X[:n_train], y[:n_train])
        libsvm.write_libsvm(val, X[n_train:], y[n_train:])
        _log("driver: running glm_driver end to end...")
        t0 = time.perf_counter()
        glm_driver.run([
            "--train-data", train,
            "--validate-data", val,
            "--output-dir", os.path.join(td, "out"),
            "--task", "logistic",
            "--reg-type", "l2",
            "--reg-weights", "0.1,1.0,10.0",
            "--n-features", str(d),
            # Measure a COLD run: the persistent compilation cache (driver
            # default 'auto') would make repeat bench runs on one machine
            # incomparable with earlier rounds' cold numbers.  (Cache
            # impact, measured on v5e: 149 s cold -> 9.1 s warm.)
            "--compile-cache", "off",
        ])
        return time.perf_counter() - t0


def main() -> None:
    baseline = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            baseline = json.load(f)

    def ratio(value, key, smaller_is_better=False):
        base = baseline.get(key)
        if not base:
            return 1.0
        return round(base / value if smaller_is_better else value / base, 4)

    extra = {}
    try:
        extra["chip_stream_gbps"] = round(bench_chip_stream(), 1)
    except Exception as e:  # calibration must never sink the bench
        extra["chip_stream_gbps"] = f"failed: {e}"
    if ONLY in ("", "game"):
        v = bench_game_cd()
        extra["game_cd_iters_per_sec"] = round(v, 3)
        extra["game_cd_vs_baseline"] = ratio(v, "game_cd_iters_per_sec")
    if ONLY in ("", "driver"):
        v = bench_glm_driver()
        extra["glm_driver_wall_seconds"] = round(v, 2)
        extra["glm_driver_vs_baseline"] = ratio(
            v, "glm_driver_wall_seconds", smaller_is_better=True
        )
    out = {
        "metric": "logistic_glm_rows_per_sec",
        "unit": "rows/s",
        "extra": extra,
    }
    if ONLY in ("", "glm"):
        rows_per_sec = bench_glm_throughput()
        out["value"] = round(rows_per_sec, 1)
        out["vs_baseline"] = ratio(rows_per_sec, "logistic_glm_rows_per_sec")
    else:
        # Debug-only partial run: never report a fake 0.0 regression.
        out["value"] = None
        out["vs_baseline"] = None
        out["note"] = f"primary metric skipped (BENCH_ONLY={ONLY})"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
