"""Headline benchmark: logistic-GLM epoch throughput on one chip.

Measures the hot loop of BASELINE.json's headline metric ("1B-row logistic
GLM epoch time"): fused value+gradient evaluations of a sparse logistic
objective — the exact op Spark's ``treeAggregate`` performs per L-BFGS
iteration in the reference (SURVEY.md §3.1) — and reports rows/second.
Epoch time for any row count divides out: 1B rows / (rows/sec) = epoch
seconds per objective evaluation.

MEASUREMENT METHODOLOGY (fixed in round 2): iterations are chained inside
ONE jitted ``fori_loop`` and the clock stops only after a small slice of the
result is read back to host.  Round 1 timed a Python loop closed by
``jax.block_until_ready``, which on this TPU transport returns before the
computation finishes unless a host readback has primed the sync path — so
round 1's number (27-29 M rows/s) measured DISPATCH rate, not compute.  The
honest round-1 COO throughput, re-measured with this methodology, is
~0.95 M rows/s; that is the ``real_round1_rows_per_sec`` recorded in
bench_baseline.json.  ``vs_baseline`` continues to be reported against the
COMMITTED round-1 number for round-over-round continuity, and is therefore
a massive *understatement* of the real kernel speedup (~70x).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np

N_ROWS = 1 << 20  # 1,048,576
N_FEATURES = 1 << 13  # 8,192
NNZ_PER_ROW = 32
N_CHAINED = 10  # objective evals chained inside one jit
N_REPS = 3  # timed repetitions (min taken)
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def main() -> None:
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.objective import GlmObjective

    rng = np.random.default_rng(0)
    nnz = N_ROWS * NNZ_PER_ROW
    rows = np.repeat(np.arange(N_ROWS, dtype=np.int64), NNZ_PER_ROW)
    cols = rng.integers(0, N_FEATURES, size=nnz).astype(np.int64)
    values = rng.normal(size=nnz).astype(np.float32)
    w_true = (rng.normal(size=N_FEATURES) *
              (rng.uniform(size=N_FEATURES) < 0.2)).astype(np.float32)
    margins_true = np.zeros(N_ROWS, np.float32)
    np.add.at(margins_true, rows, values * w_true[cols.astype(np.int64)])
    y = (rng.uniform(size=N_ROWS) < 1 / (1 + np.exp(-margins_true))).astype(
        np.float32)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix

        X = build_pallas_matrix(rows, cols, values, N_ROWS, N_FEATURES)
    else:
        from photon_ml_tpu.ops.sparse import from_coo

        X = from_coo(rows, cols, values, N_ROWS, N_FEATURES)

    data = jax.device_put(GlmData(
        features=X,
        labels=jnp.asarray(y),
        weights=jnp.ones(N_ROWS, jnp.float32),
        offsets=jnp.zeros(N_ROWS, jnp.float32),
    ))
    obj = GlmObjective(losses.logistic)

    # Data is an ARGUMENT, not a closure constant: closed-over arrays get
    # baked into the HLO as literals (overflows the remote-compile transport).
    @jax.jit
    def chain(w, data):
        def body(i, w):
            val, grad = obj.value_and_grad(w, data, l2_weight=1.0)
            return w - 1e-4 * grad
        return jax.lax.fori_loop(0, N_CHAINED, body, w)

    w = jnp.zeros(N_FEATURES, jnp.float32)
    out = chain(w, data)
    _ = np.asarray(out.ravel()[0:1])  # compile + prime true sync

    best = np.inf
    for i in range(N_REPS):
        wp = jnp.full((N_FEATURES,), np.float32(1e-3 * (i + 1)))
        _ = np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out = chain(wp, data)
        _ = np.asarray(out.ravel()[0:1])  # force real completion
        best = min(best, (time.perf_counter() - t0) / N_CHAINED)

    rows_per_sec = N_ROWS / best

    vs_baseline = 1.0
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            base = json.load(f).get("logistic_glm_rows_per_sec")
        if base:
            vs_baseline = rows_per_sec / base

    print(json.dumps({
        "metric": "logistic_glm_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
