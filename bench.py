"""Headline benchmark: logistic-GLM epoch throughput on one chip.

Measures the hot loop of BASELINE.json's headline metric ("1B-row logistic
GLM epoch time"): fused value+gradient evaluations of a sparse logistic
objective — the exact op Spark's ``treeAggregate`` performs per L-BFGS
iteration in the reference (SURVEY.md §3.1) — and reports rows/second.
Epoch time for any row count divides out: 1B rows / (rows/sec) = epoch
seconds per objective evaluation.

No reference number is recorded in BASELINE.json (``published`` is {}), so
``vs_baseline`` is the ratio against the committed ``bench_baseline.json``
(first measured value on this hardware, round 1); it tracks round-over-round
progress until a real reference number exists.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np

N_ROWS = 1 << 20  # 1,048,576
N_FEATURES = 1 << 13  # 8,192
NNZ_PER_ROW = 32
N_TIMED = 30
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def main() -> None:
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import GlmData
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.sparse import SparseMatrix
    from photon_ml_tpu.optim.objective import GlmObjective

    rng = np.random.default_rng(0)
    nnz = N_ROWS * NNZ_PER_ROW
    # Row-sorted COO by construction: each row holds NNZ_PER_ROW entries.
    row_ids = np.repeat(np.arange(N_ROWS, dtype=np.int32), NNZ_PER_ROW)
    col_ids = rng.integers(0, N_FEATURES, size=nnz, dtype=np.int32)
    values = rng.normal(size=nnz).astype(np.float32)
    w_true = (rng.normal(size=N_FEATURES) *
              (rng.uniform(size=N_FEATURES) < 0.2)).astype(np.float32)

    X = SparseMatrix(
        row_ids=jnp.asarray(row_ids),
        col_ids=jnp.asarray(col_ids),
        values=jnp.asarray(values),
        n_rows=N_ROWS,
        n_cols=N_FEATURES,
    )
    margins_true = np.zeros(N_ROWS, np.float32)
    np.add.at(margins_true, row_ids, values * w_true[col_ids])
    y = (rng.uniform(size=N_ROWS) < 1 / (1 + np.exp(-margins_true))).astype(
        np.float32
    )
    data = GlmData(
        features=X,
        labels=jnp.asarray(y),
        weights=jnp.ones(N_ROWS, jnp.float32),
        offsets=jnp.zeros(N_ROWS, jnp.float32),
    )
    obj = GlmObjective(losses.logistic)

    # Data is an ARGUMENT, not a closure constant: closed-over arrays get
    # baked into the HLO as literals, which bloats the program (and overflows
    # the axon remote-compile transport).
    @jax.jit
    def value_and_grad(w, data):
        return obj.value_and_grad(w, data, l2_weight=1.0)

    data = jax.device_put(data)
    w = jnp.zeros(N_FEATURES, jnp.float32)
    # Warmup: compile + first execution.
    val, grad = value_and_grad(w, data)
    jax.block_until_ready(grad)

    start = time.perf_counter()
    for _ in range(N_TIMED):
        val, grad = value_and_grad(w, data)
        # New iterate each call so XLA can't fold the loop away.
        w = w - 1e-4 * grad
    jax.block_until_ready(w)
    elapsed = time.perf_counter() - start

    rows_per_sec = N_ROWS * N_TIMED / elapsed

    vs_baseline = 1.0
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            base = json.load(f).get("logistic_glm_rows_per_sec")
        if base:
            vs_baseline = rows_per_sec / base

    print(json.dumps({
        "metric": "logistic_glm_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
