"""photon_ml_tpu — a TPU-native framework with the capabilities of Photon ML.

Photon ML (reference: kaituozhe528/photon-ml, a Scala/Spark library) trains
large-scale Generalized Linear Models (GLMs) and GAME (Generalized Additive
Mixed Effects) models.  This package rebuilds those capabilities TPU-first:

- ``ops``        — pointwise GLM losses and sparse linear algebra (XLA/Pallas),
                   the analogue of the reference's Breeze/BLAS layer.
- ``optim``      — fully on-device convex optimizers (L-BFGS, OWL-QN, TRON)
                   as ``lax.while_loop`` programs; the analogue of
                   photon-lib's ``com.linkedin.photon.ml.optimization``.
- ``models``     — GLM model classes and GAME model containers; the analogue
                   of ``...ml.model`` / ``...ml.supervised``.
- ``parallel``   — device meshes, row/feature/entity shardings, and the
                   ``psum``-based distributed objective that replaces Spark's
                   ``RDD.treeAggregate`` gradient reduction.
- ``data``       — datasets (dense + CSR shards), LIBSVM ingest, feature index
                   maps, normalization, summary stats, down-sampling, and the
                   random-effect grouping/bucketing layer.
- ``game``       — coordinates, block coordinate descent, estimator and
                   transformer; the analogue of ``...ml.algorithm`` /
                   ``...ml.estimators``.
- ``evaluation`` — AUC / RMSE / log-loss / Poisson-loss / precision@k and
                   grouped (per-query) evaluators.
- ``hyperparameter`` — random search and Gaussian-process (Matérn + EI)
                   Bayesian search over regularization weights.
- ``tuning``     — trial orchestration over ``hyperparameter``: parallel
                   trials on a worker pool, constant-liar batched GP asks,
                   ASHA successive halving, warm starts, and a journaled
                   crash-safe ``--resume`` (``python -m photon_ml_tpu.tuning``).
- ``drivers``    — end-to-end CLI drivers mirroring the reference's
                   ``Driver`` (legacy GLM), ``GameTrainingDriver``,
                   ``GameScoringDriver``, ``FeatureIndexingDriver``.
- ``io``         — model/data serialization incl. a dependency-free Avro
                   container codec (the reference stores everything as Avro).
- ``utils``      — logging, timing, optimization-state tracking.

Design stance (see SURVEY.md §7): Spark is *replaced*, not translated.  Rows
are sharded over a ``jax.sharding.Mesh`` and gradients reduced with ``psum``
over ICI; per-entity random-effect solves are ``vmap``-batched over
size-bucketed entity blocks instead of per-partition Spark tasks.

NOTE: this is the target layout; subpackages land incrementally (check
``photon_ml_tpu/<name>/__init__.py`` existence, or the git log, for what has
shipped so far).
"""

__version__ = "0.1.0"

from photon_ml_tpu.ops import losses  # noqa: F401
