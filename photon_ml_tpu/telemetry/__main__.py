"""Telemetry smoke entry point.

``python -m photon_ml_tpu.telemetry --selfcheck`` emits a synthetic span
tree (including a cross-thread producer span, instant events, and every
metric kind) through the full sink set into a scratch directory, then
validates the outputs:

- every ``events.jsonl`` line parses as JSON and carries type/name/ts;
- ``trace.json`` parses as a Chrome trace-event ARRAY whose span events
  have the required ph/ts/dur/pid/tid fields and whose parent links
  resolve;
- ``metrics.json`` round-trips the registry snapshot;
- **ops plane**: the time-series sampler wrote ≥ 2 monotone-timestamped
  snapshots to ``metrics_ts.jsonl`` carrying a live HBM-bytes gauge,
  the embedded exporter's ``/metrics`` output PARSES as Prometheus text
  exposition (and ``/snapshot`` as JSON), and the exporter thread joins
  cleanly on close;
- **flight recorder**: an injected chaos fault (``serving.batch`` via a
  scripted FaultPlan) dumps ``flightrecorder.json`` whose last-N events
  END at the fault site's ``chaos.fault`` record;
- **fleet pass** (PR 17): a synthetic 2-host x 2-worker fleet — one
  traced request crosses router -> host -> worker hubs via
  ``TraceContext`` header propagation and the merged Chrome traces
  stitch into ONE trace (shared trace id, ``rparent`` links resolving
  across files); a :class:`FleetAggregator` scrapes both hosts' live
  ``/snapshot`` endpoints and its aggregated ``/metrics`` exposition
  PARSES with per-``host`` labels; injected slow latency trips the
  multi-window SLO burn alert (``slo.burn`` event + flight-recorder
  dump), and a scripted ``telemetry.scrape`` fault degrades to
  last-seen snapshots (failures counted, recovery observed) without
  wedging the poll loop.

``--lint-metrics`` runs the metric-name lint (telemetry/lint.py) over
the package source instead: duplicate-kind registrations and
non-conforming ``<subsystem>_<name>_<unit>`` names fail the check.

Exit status 0 on success; nonzero with a diagnostic on any failure —
CI-greppable, device-free (never imports jax).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _build_synthetic_run(out_dir: str) -> dict:
    from photon_ml_tpu.telemetry import Telemetry, mount_ops_plane

    info: dict = {}
    with Telemetry(output_dir=out_dir, run_name="selfcheck") as tel:
        plane = mount_ops_plane(tel, port=0, interval_s=0.02)
        with tel.span("run", driver="selfcheck"):
            for it in range(2):
                with tel.span("cd_iteration", iteration=it):
                    for coord in ("fixed", "per_user"):
                        with tel.span(
                            "coordinate", coordinate=coord, iteration=it
                        ):
                            with tel.span(
                                "solver", coordinate=coord,
                                optimizer="lbfgs",
                            ) as sp:
                                time.sleep(0.001)
                                sp.set(iterations=7, converged=True)
                            tel.counter("solver_iterations").inc(7)
                tel.event(
                    "checkpoint.save", iteration=it, path="<synthetic>"
                )

            ctx = tel.current_context()

            def producer():
                # Cross-thread spans ATTACH the spawning span's context
                # (the h2d prefetch producer's shape) so the Perfetto
                # view nests the producer track under the run.
                with tel.attach(ctx):
                    for k in range(3):
                        with tel.span("chunk", index=k):
                            time.sleep(0.0005)
                        tel.histogram("stream_chunk_seconds").observe(
                            0.0005
                        )
                        tel.gauge("hbm_live_bytes").set((k + 1) * 1024)
                    tel.gauge("h2d_gbps").set(1.25)
                    tel.counter("h2d_bytes_total").inc(3 * 1024)

            t = threading.Thread(
                target=producer, name="h2d-prefetch", daemon=True
            )
            t.start()
            t.join()
            tel.event(
                "watchdog.attempt", attempt=0, outcome="ok",
                exception=None,
            )

            # Injected chaos fault → flight-recorder dump ending at the
            # fault site (chaos/core.py imports no jax; this stays a
            # device-free check).
            from photon_ml_tpu import chaos

            with chaos.FaultPlan([chaos.FaultSpec(site="serving.batch")]):
                try:
                    chaos.maybe_fail("serving.batch", rows=4)
                    info["fault_raised"] = False
                except chaos.InjectedFault:
                    info["fault_raised"] = True

            # Let the interval sampler take >= 2 samples past the start
            # sample, then scrape the live endpoints.
            time.sleep(0.08)
            import urllib.request

            port = plane.port
            for route, key in (
                ("/metrics", "prom_text"),
                ("/snapshot", "snapshot_body"),
                ("/healthz", "healthz_body"),
            ):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=10
                ) as resp:
                    info[key] = resp.read().decode()
                    info[key + "_status"] = resp.status
        snap = tel.snapshot()
        exporter = plane.exporter
        plane.close()
        info["exporter_alive_after_close"] = exporter.alive
        info["sampler_alive_after_close"] = (
            plane.sampler is not None and plane.sampler.alive
        )
    info["snapshot"] = snap
    return info


def validate_outputs(out_dir: str, snapshot: dict) -> list[str]:
    """Returns a list of failure strings (empty = pass)."""
    failures: list[str] = []

    events_path = os.path.join(out_dir, "events.jsonl")
    trace_path = os.path.join(out_dir, "trace.json")
    metrics_path = os.path.join(out_dir, "metrics.json")
    for p in (events_path, trace_path, metrics_path):
        if not os.path.exists(p):
            failures.append(f"missing output: {p}")
    if failures:
        return failures

    span_ids = set()
    parents = []
    n_lines = 0
    with open(events_path) as f:
        for lineno, line in enumerate(f, 1):
            n_lines += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                failures.append(f"events.jsonl:{lineno} unparseable: {e}")
                continue
            if rec.get("type") == "metrics":
                # Trailing registry snapshot record — no name/ts.
                continue
            if "type" not in rec or "name" not in rec or "ts" not in rec:
                failures.append(
                    f"events.jsonl:{lineno} missing type/name/ts: {rec}"
                )
            if rec.get("type") == "span":
                span_ids.add(rec["id"])
                if rec.get("parent") is not None:
                    parents.append((lineno, rec["parent"]))
                if rec.get("dur", -1.0) < 0.0:
                    failures.append(
                        f"events.jsonl:{lineno} negative span duration"
                    )
    if n_lines == 0:
        failures.append("events.jsonl is empty")
    for lineno, parent in parents:
        if parent not in span_ids:
            failures.append(
                f"events.jsonl:{lineno} dangling parent span {parent}"
            )

    with open(trace_path) as f:
        try:
            trace = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"trace.json unparseable: {e}")
            trace = None
    if trace is not None:
        if not isinstance(trace, list):
            failures.append(
                f"trace.json is {type(trace).__name__}, not an array"
            )
        else:
            n_spans = 0
            for i, ev in enumerate(trace):
                if not isinstance(ev, dict):
                    failures.append(f"trace.json[{i}] not an object")
                    continue
                missing = [k for k in ("name", "ph", "ts", "pid", "tid")
                           if k not in ev]
                if missing:
                    failures.append(
                        f"trace.json[{i}] missing {missing}"
                    )
                if ev.get("ph") == "X":
                    n_spans += 1
                    if "dur" not in ev:
                        failures.append(
                            f"trace.json[{i}] X event without dur"
                        )
            if n_spans == 0:
                failures.append("trace.json holds no span (X) events")

    with open(metrics_path) as f:
        try:
            metrics = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"metrics.json unparseable: {e}")
            metrics = {}
    for kind in ("counters", "gauges", "histograms"):
        if kind not in metrics:
            failures.append(f"metrics.json missing {kind!r}")
        elif snapshot.get(kind) and metrics[kind] != json.loads(
            json.dumps(snapshot[kind])
        ):
            failures.append(
                f"metrics.json {kind} diverge from the live snapshot"
            )
    return failures


def validate_ops_plane(out_dir: str, info: dict) -> list[str]:
    """Validate the live ops plane's outputs: the time-series file, the
    Prometheus exposition scraped while the run was live, the exporter's
    thread lifecycle, and the chaos-fault flight-recorder dump."""
    from photon_ml_tpu.telemetry.exporter import parse_prometheus_text
    from photon_ml_tpu.telemetry.timeseries import read_series

    failures: list[str] = []

    # -- metrics_ts.jsonl: >= 2 monotone snapshots w/ live HBM gauge -------
    ts_path = os.path.join(out_dir, "metrics_ts.jsonl")
    if not os.path.exists(ts_path):
        failures.append(f"missing time series: {ts_path}")
    else:
        series = read_series(ts_path)
        if len(series) < 2:
            failures.append(
                f"metrics_ts.jsonl has {len(series)} snapshots, need >= 2"
            )
        for key in ("seq", "t_mono"):
            vals = [rec.get(key) for rec in series]
            if any(b <= a for a, b in zip(vals, vals[1:])):
                failures.append(
                    f"metrics_ts.jsonl {key} not strictly increasing: "
                    f"{vals}"
                )
        if series and "hbm_live_bytes" not in (
            series[-1].get("gauges") or {}
        ):
            failures.append(
                "metrics_ts.jsonl final snapshot lacks the live "
                "hbm_live_bytes gauge"
            )

    # -- /metrics parses as Prometheus exposition --------------------------
    prom = info.get("prom_text")
    if not prom:
        failures.append("/metrics returned no body")
    else:
        try:
            parsed = parse_prometheus_text(prom)
        except ValueError as e:
            failures.append(f"/metrics exposition unparseable: {e}")
            parsed = {}
        for family in ("hbm_live_bytes", "solver_iterations"):
            if (family, "") not in parsed:
                failures.append(
                    f"/metrics lacks the {family} family"
                )
        if not any(
            name == "stream_chunk_seconds" and 'quantile="0.5"' in labels
            for name, labels in parsed
        ):
            failures.append(
                "/metrics lacks histogram quantile samples "
                "(stream_chunk_seconds{quantile=...})"
            )

    # -- /snapshot + /healthz are JSON -------------------------------------
    for key in ("snapshot_body", "healthz_body"):
        body = info.get(key)
        if not body:
            failures.append(f"{key.split('_')[0]} endpoint returned nothing")
            continue
        try:
            json.loads(body)
        except json.JSONDecodeError as e:
            failures.append(f"{key} is not JSON: {e}")

    # -- exporter/sampler thread lifecycle ---------------------------------
    if info.get("exporter_alive_after_close"):
        failures.append("exporter thread still alive after close()")
    if info.get("sampler_alive_after_close"):
        failures.append("sampler thread still alive after stop()")

    # -- flight recorder: dump ends at the injected fault site -------------
    if not info.get("fault_raised"):
        failures.append("chaos fault did not raise (plan mis-armed?)")
    fr_path = os.path.join(out_dir, "flightrecorder.json")
    if not os.path.exists(fr_path):
        failures.append(f"missing flight-recorder dump: {fr_path}")
    else:
        with open(fr_path) as f:
            try:
                dump = json.load(f)
            except json.JSONDecodeError as e:
                failures.append(f"flightrecorder.json unparseable: {e}")
                dump = {}
        events = dump.get("events") or []
        if not events:
            failures.append("flightrecorder.json holds no events")
        else:
            last = events[-1]
            if last.get("name") != "chaos.fault" or (
                (last.get("attrs") or {}).get("site") != "serving.batch"
            ):
                failures.append(
                    "flightrecorder.json does not END at the fault "
                    f"site: last event {last.get('name')!r} "
                    f"attrs={last.get('attrs')}"
                )
        if dump.get("n_events", 0) > dump.get("capacity", 0):
            failures.append(
                "flight recorder dumped more events than its capacity"
            )
        if not str(dump.get("reason") or "").startswith("chaos"):
            failures.append(
                f"flight-recorder dump reason {dump.get('reason')!r} "
                "does not name the chaos fault"
            )
    return failures


def _build_fleet_run(out_dir: str) -> dict:
    """Synthetic 2-host x 2-worker fleet, all hubs in-process: a traced
    request hops router -> host -> worker through real ``TraceContext``
    header strings, the aggregator scrapes both hosts' live exporters
    over HTTP, injected slow latency trips the burn alert, and a chaos
    fault exercises scrape degradation.  Device-free and fast: no jax,
    no subprocesses — the hop boundaries are exactly the header-encoded
    contexts the real transports carry."""
    import urllib.request

    from photon_ml_tpu import chaos
    from photon_ml_tpu.telemetry import (
        ChromeTraceSink,
        FleetAggregator,
        JsonlSink,
        MetricsExporter,
        SloPolicy,
        Telemetry,
        TraceContext,
    )

    info: dict = {}
    fleet_dir = os.path.join(out_dir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)

    def _leaf_hub(name: str) -> tuple:
        path = os.path.join(fleet_dir, name + ".trace.json")
        hub = Telemetry(
            sinks=[
                ChromeTraceSink(path),
                JsonlSink(os.path.join(fleet_dir, name + ".jsonl")),
            ],
            run_name=name,
        )
        return hub, path

    # The router hub doubles as the aggregator-side current hub: the
    # slo.burn event and its flight-recorder dump land in fleet_dir.
    with Telemetry(output_dir=fleet_dir, run_name="fleet-router") as router:
        router.configure_tracing(sample_every=1)
        hosts: dict = {}
        trace_files = [os.path.join(fleet_dir, "trace.json")]
        try:
            for hid in ("host-0", "host-1"):
                hub, path = _leaf_hub(hid)
                trace_files.append(path)
                workers = []
                for wk in range(2):
                    whub, wpath = _leaf_hub(f"{hid}-worker-{wk}")
                    trace_files.append(wpath)
                    workers.append(whub)
                exporter = MetricsExporter(hub, port=0, host_id=hid)
                exporter.start()
                hosts[hid] = {
                    "hub": hub, "workers": workers, "exporter": exporter,
                }

            # -- one traced request fanning out across the fleet -------
            ctx = router.new_trace()
            info["trace_id"] = ctx.trace_id
            info["trace_sampled"] = ctx.sampled
            with router.adopt(ctx), router.span("serving.fleet_route"):
                header = router.propagation_context().header_value()
            for hid, entry in hosts.items():
                hub = entry["hub"]
                # Each hop re-parses the wire string — the same
                # round-trip the HTTP header / wire frame / shm slot
                # transports perform.
                with hub.adopt(TraceContext.parse(header)), \
                        hub.span("serving.http_score", host=hid):
                    inner = hub.propagation_context().header_value()
                    for wk, whub in enumerate(entry["workers"]):
                        with whub.adopt(TraceContext.parse(inner)), \
                                whub.span("serving.batch", worker=wk):
                            pass

            # -- metrics: a healthy baseline, then injected latency ----
            for entry in hosts.values():
                hub = entry["hub"]
                lat = hub.histogram("serving_request_latency_seconds")
                for _ in range(50):
                    lat.observe(0.002)
                for stage in ("admission", "queue", "batch", "device",
                              "encode"):
                    hub.histogram(
                        f"serving_stage_{stage}_seconds"
                    ).observe(0.001)

            agg = FleetAggregator(
                {
                    hid: f"http://127.0.0.1:{entry['exporter'].port}"
                    for hid, entry in hosts.items()
                },
                policies=[SloPolicy(
                    name="latency-p99", p99_s=0.05, error_budget=0.01,
                )],
            )
            try:
                agg.poll_once(now=1000.0)  # baseline: all fast

                # -- scrape chaos: both hosts drop off for one round ---
                # (before the burn injection, so the burn's forensics
                # dump is the LAST flightrecorder.json write)
                with chaos.FaultPlan([chaos.FaultSpec(
                    site="telemetry.scrape", at=0, count=2,
                )]):
                    info["faulted_report"] = agg.poll_once(now=1030.0)
                info["recovered_report"] = agg.poll_once(now=1060.0)

                for entry in hosts.values():
                    lat = entry["hub"].histogram(
                        "serving_request_latency_seconds"
                    )
                    for _ in range(20):
                        lat.observe(1.0)  # way past the 50ms target
                info["burn_report"] = agg.poll_once(now=1120.0)

                port = agg.serve()
                for route, key in (
                    ("/metrics", "fleet_prom_text"),
                    ("/slo", "fleet_slo_body"),
                    ("/healthz", "fleet_healthz_body"),
                ):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{route}", timeout=10
                    ) as resp:
                        info[key] = resp.read().decode()
            finally:
                agg.stop()
        finally:
            for entry in hosts.values():
                entry["exporter"].close()
                for whub in entry["workers"]:
                    whub.close()
                entry["hub"].close()
    info["trace_files"] = trace_files

    # Merge the per-hub Chrome traces the way ops would before loading
    # Perfetto: concatenate the event arrays.
    merged: list = []
    for path in trace_files:
        if os.path.exists(path):
            with open(path) as f:
                try:
                    merged.extend(json.load(f))
                except json.JSONDecodeError:
                    pass  # validated (and failed) per-file below
    merged_path = os.path.join(fleet_dir, "merged.trace.json")
    with open(merged_path, "w") as f:
        json.dump(merged, f)
    info["merged_path"] = merged_path
    return info


def validate_fleet(out_dir: str, info: dict) -> list[str]:
    """Validate the fleet pass: one stitched trace across 7 hubs, a
    parseable host-labeled aggregated exposition, a fired burn alert
    with its forensics dump, and non-wedging scrape degradation."""
    from photon_ml_tpu.telemetry.exporter import parse_prometheus_text

    failures: list[str] = []
    fleet_dir = os.path.join(out_dir, "fleet")
    trace_id = info.get("trace_id")

    # -- stitched trace ----------------------------------------------------
    if not info.get("trace_sampled"):
        failures.append("fleet: sample_every=1 trace not head-sampled")
    gids: set = set()
    links: list = []  # (file, rparent)
    files_in_trace = 0
    for path in info.get("trace_files") or []:
        if not os.path.exists(path):
            failures.append(f"fleet: missing trace file {path}")
            continue
        with open(path) as f:
            try:
                events = json.load(f)
            except json.JSONDecodeError as e:
                failures.append(f"fleet: {path} unparseable: {e}")
                continue
        in_trace = False
        for ev in events:
            args = ev.get("args") or {}
            if ev.get("ph") == "X" and args.get("trace") == trace_id:
                in_trace = True
                if args.get("gid"):
                    gids.add(args["gid"])
                if args.get("rparent"):
                    links.append((path, args["rparent"]))
        if in_trace:
            files_in_trace += 1
    if files_in_trace != 7:
        failures.append(
            f"fleet: trace {trace_id} spans {files_in_trace} hub files, "
            "expected 7 (router + 2 hosts + 4 workers)"
        )
    if len(links) != 6:
        failures.append(
            f"fleet: {len(links)} cross-hub parent links, expected 6"
        )
    for path, rparent in links:
        if rparent not in gids:
            failures.append(
                f"fleet: {os.path.basename(path)} rparent {rparent} "
                "resolves to no span gid in the merged trace"
            )
    merged_path = info.get("merged_path") or ""
    if not os.path.exists(merged_path):
        failures.append(f"fleet: missing merged trace {merged_path}")
    else:
        with open(merged_path) as f:
            try:
                merged = json.load(f)
            except json.JSONDecodeError as e:
                failures.append(f"fleet: merged trace unparseable: {e}")
                merged = None
        if merged is not None:
            if not isinstance(merged, list) or not merged:
                failures.append("fleet: merged trace not a non-empty array")
            else:
                for i, ev in enumerate(merged):
                    missing = [
                        k for k in ("name", "ph", "ts", "pid", "tid")
                        if not isinstance(ev, dict) or k not in ev
                    ]
                    if missing:
                        failures.append(
                            f"fleet: merged[{i}] missing {missing} — "
                            "not Perfetto-loadable"
                        )
                        break

    # -- aggregated exposition ---------------------------------------------
    prom = info.get("fleet_prom_text")
    if not prom:
        failures.append("fleet: /metrics returned no body")
    else:
        try:
            parsed = parse_prometheus_text(prom)
        except ValueError as e:
            failures.append(f"fleet: /metrics exposition unparseable: {e}")
            parsed = {}
        if parsed.get(("fleet_hosts_count", "")) != 2.0:
            failures.append("fleet: /metrics fleet_hosts_count != 2")
        for hid in ("host-0", "host-1"):
            key = ("serving_request_latency_seconds_count",
                   f'{{host="{hid}"}}')
            if key not in parsed:
                failures.append(
                    "fleet: /metrics lacks host-labeled latency count "
                    f"for {hid}"
                )
        if ("serving_request_latency_seconds_count", "") not in parsed:
            failures.append(
                "fleet: /metrics lacks the fleet-wide latency fold"
            )
        if not any(
            name.startswith("serving_stage_") and name.endswith("_count")
            for name, _ in parsed
        ):
            failures.append(
                "fleet: /metrics lacks serving_stage_* decomposition "
                "families"
            )
        if parsed.get(("fleet_scrape_failures_total", ""), 0.0) < 2.0:
            failures.append(
                "fleet: fleet_scrape_failures_total < 2 after the "
                "scripted 2-host scrape fault"
            )
        if parsed.get(("slo_burn_alerts_total", ""), 0.0) < 1.0:
            failures.append("fleet: slo_burn_alerts_total never fired")

    # -- burn alert --------------------------------------------------------
    report = info.get("burn_report") or {}
    policies = report.get("policies") or []
    if not policies:
        failures.append("fleet: burn report carries no policies")
    else:
        pol = policies[0]
        if not pol.get("alerting"):
            failures.append(
                "fleet: burn alert did not fire under injected latency: "
                f"{pol}"
            )
        if pol.get("fast", {}).get("burn", 0.0) < 1.0:
            failures.append(
                f"fleet: fast-window burn below threshold: {pol.get('fast')}"
            )
    slo_body = info.get("fleet_slo_body")
    if not slo_body:
        failures.append("fleet: /slo returned no body")
    else:
        try:
            slo = json.loads(slo_body)
        except json.JSONDecodeError as e:
            failures.append(f"fleet: /slo not JSON: {e}")
            slo = {}
        for hid, entry in (slo.get("hosts") or {}).items():
            identity = entry.get("identity") or {}
            if identity.get("host_id") != hid:
                failures.append(
                    f"fleet: /slo host {hid} identity block says "
                    f"{identity.get('host_id')!r}"
                )
    fr_path = os.path.join(fleet_dir, "flightrecorder.json")
    if not os.path.exists(fr_path):
        failures.append(
            f"fleet: burn alert left no flight-recorder dump at {fr_path}"
        )
    else:
        with open(fr_path) as f:
            try:
                dump = json.load(f)
            except json.JSONDecodeError as e:
                failures.append(f"fleet: flight dump unparseable: {e}")
                dump = {}
        if not str(dump.get("reason") or "").startswith("slo.burn"):
            failures.append(
                f"fleet: flight dump reason {dump.get('reason')!r} does "
                "not name the burn"
            )

    # -- scrape degradation: fail soft, recover --------------------------
    faulted = (info.get("faulted_report") or {}).get("hosts") or {}
    for hid, entry in faulted.items():
        if entry.get("failures", 0) < 1:
            failures.append(
                f"fleet: host {hid} shows no scrape failure under the "
                "chaos plan"
            )
    recovered = (info.get("recovered_report") or {}).get("hosts") or {}
    if not recovered:
        failures.append("fleet: poll loop wedged after the scrape fault")
    for hid, entry in recovered.items():
        if entry.get("stale"):
            failures.append(
                f"fleet: host {hid} still stale after the fault cleared"
            )
    events_path = os.path.join(fleet_dir, "events.jsonl")
    names = set()
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                try:
                    names.add(json.loads(line).get("name"))
                except json.JSONDecodeError:
                    pass
    for needed in ("slo.burn", "fleet.scrape_stale",
                   "fleet.scrape_recovered"):
        if needed not in names:
            failures.append(
                f"fleet: router events.jsonl lacks the {needed} event"
            )
    return failures


def _run_and_validate(out_dir: str) -> list[str]:
    info = _build_synthetic_run(out_dir)
    failures = validate_outputs(out_dir, info["snapshot"])
    failures.extend(validate_ops_plane(out_dir, info))
    fleet_info = _build_fleet_run(out_dir)
    failures.extend(validate_fleet(out_dir, fleet_info))
    return failures


def selfcheck(keep_dir: str | None = None) -> int:
    if keep_dir is not None:
        os.makedirs(keep_dir, exist_ok=True)
        out_dir = keep_dir
        failures = _run_and_validate(out_dir)
    else:
        with tempfile.TemporaryDirectory() as td:
            out_dir = td
            failures = _run_and_validate(out_dir)
    if failures:
        for f in failures:
            print(f"telemetry selfcheck FAIL: {f}", file=sys.stderr)
        return 1
    print(
        "telemetry selfcheck OK: events.jsonl + trace.json + metrics.json "
        "+ metrics_ts.jsonl + /metrics exposition + flightrecorder.json "
        "+ fleet pass (stitched 2-host trace, aggregated /metrics, SLO "
        f"burn alert, scrape degradation) valid ({out_dir})"
    )
    return 0


def lint_metrics() -> int:
    from photon_ml_tpu.telemetry.lint import lint_source

    n_names, problems = lint_source()
    if problems:
        for p_ in problems:
            print(f"metric lint FAIL: {p_}", file=sys.stderr)
        return 1
    print(
        f"metric lint OK: {n_names} metric names conform "
        "(<subsystem>_<name>_<unit>, one kind per name)"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m photon_ml_tpu.telemetry")
    p.add_argument(
        "--selfcheck", action="store_true",
        help="emit a synthetic span tree through every sink + the live "
        "ops plane (time-series sampler, /metrics exporter, chaos-fault "
        "flight recorder) and validate every output",
    )
    p.add_argument(
        "--lint-metrics", action="store_true",
        help="scan the package source for metric registrations and "
        "enforce the naming convention + one-kind-per-name",
    )
    p.add_argument(
        "--keep-dir",
        help="with --selfcheck: write the outputs here (inspectable) "
        "instead of a throwaway tempdir",
    )
    args = p.parse_args(argv)
    if args.lint_metrics:
        return lint_metrics()
    if args.selfcheck:
        return selfcheck(args.keep_dir)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
