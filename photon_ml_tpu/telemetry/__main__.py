"""Telemetry smoke entry point.

``python -m photon_ml_tpu.telemetry --selfcheck`` emits a synthetic span
tree (including a cross-thread producer span, instant events, and every
metric kind) through the full sink set into a scratch directory, then
validates the outputs:

- every ``events.jsonl`` line parses as JSON and carries type/name/ts;
- ``trace.json`` parses as a Chrome trace-event ARRAY whose span events
  have the required ph/ts/dur/pid/tid fields and whose parent links
  resolve;
- ``metrics.json`` round-trips the registry snapshot.

Exit status 0 on success; nonzero with a diagnostic on any failure —
CI-greppable, device-free (never imports jax).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _build_synthetic_run(out_dir: str) -> dict:
    from photon_ml_tpu.telemetry import Telemetry

    with Telemetry(output_dir=out_dir, run_name="selfcheck") as tel:
        with tel.span("run", driver="selfcheck"):
            for it in range(2):
                with tel.span("cd_iteration", iteration=it):
                    for coord in ("fixed", "per_user"):
                        with tel.span(
                            "coordinate", coordinate=coord, iteration=it
                        ):
                            with tel.span(
                                "solver", coordinate=coord,
                                optimizer="lbfgs",
                            ) as sp:
                                time.sleep(0.001)
                                sp.set(iterations=7, converged=True)
                            tel.counter("solver_iterations").inc(7)
                tel.event(
                    "checkpoint.save", iteration=it, path="<synthetic>"
                )

            def producer():
                # Cross-thread spans root their own stacks (the h2d
                # prefetch producer's shape).
                for k in range(3):
                    with tel.span("chunk", index=k):
                        time.sleep(0.0005)
                    tel.histogram("h2d_chunk_seconds").observe(0.0005)
                tel.gauge("h2d_gbps").set(1.25)
                tel.counter("h2d_bytes_total").inc(3 * 1024)

            t = threading.Thread(target=producer, name="h2d-prefetch")
            t.start()
            t.join()
            tel.event(
                "watchdog.attempt", attempt=0, outcome="ok",
                exception=None,
            )
        snap = tel.snapshot()
    return snap


def validate_outputs(out_dir: str, snapshot: dict) -> list[str]:
    """Returns a list of failure strings (empty = pass)."""
    failures: list[str] = []

    events_path = os.path.join(out_dir, "events.jsonl")
    trace_path = os.path.join(out_dir, "trace.json")
    metrics_path = os.path.join(out_dir, "metrics.json")
    for p in (events_path, trace_path, metrics_path):
        if not os.path.exists(p):
            failures.append(f"missing output: {p}")
    if failures:
        return failures

    span_ids = set()
    parents = []
    n_lines = 0
    with open(events_path) as f:
        for lineno, line in enumerate(f, 1):
            n_lines += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                failures.append(f"events.jsonl:{lineno} unparseable: {e}")
                continue
            if rec.get("type") == "metrics":
                # Trailing registry snapshot record — no name/ts.
                continue
            if "type" not in rec or "name" not in rec or "ts" not in rec:
                failures.append(
                    f"events.jsonl:{lineno} missing type/name/ts: {rec}"
                )
            if rec.get("type") == "span":
                span_ids.add(rec["id"])
                if rec.get("parent") is not None:
                    parents.append((lineno, rec["parent"]))
                if rec.get("dur", -1.0) < 0.0:
                    failures.append(
                        f"events.jsonl:{lineno} negative span duration"
                    )
    if n_lines == 0:
        failures.append("events.jsonl is empty")
    for lineno, parent in parents:
        if parent not in span_ids:
            failures.append(
                f"events.jsonl:{lineno} dangling parent span {parent}"
            )

    with open(trace_path) as f:
        try:
            trace = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"trace.json unparseable: {e}")
            trace = None
    if trace is not None:
        if not isinstance(trace, list):
            failures.append(
                f"trace.json is {type(trace).__name__}, not an array"
            )
        else:
            n_spans = 0
            for i, ev in enumerate(trace):
                if not isinstance(ev, dict):
                    failures.append(f"trace.json[{i}] not an object")
                    continue
                missing = [k for k in ("name", "ph", "ts", "pid", "tid")
                           if k not in ev]
                if missing:
                    failures.append(
                        f"trace.json[{i}] missing {missing}"
                    )
                if ev.get("ph") == "X":
                    n_spans += 1
                    if "dur" not in ev:
                        failures.append(
                            f"trace.json[{i}] X event without dur"
                        )
            if n_spans == 0:
                failures.append("trace.json holds no span (X) events")

    with open(metrics_path) as f:
        try:
            metrics = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"metrics.json unparseable: {e}")
            metrics = {}
    for kind in ("counters", "gauges", "histograms"):
        if kind not in metrics:
            failures.append(f"metrics.json missing {kind!r}")
        elif snapshot.get(kind) and metrics[kind] != json.loads(
            json.dumps(snapshot[kind])
        ):
            failures.append(
                f"metrics.json {kind} diverge from the live snapshot"
            )
    return failures


def selfcheck(keep_dir: str | None = None) -> int:
    if keep_dir is not None:
        os.makedirs(keep_dir, exist_ok=True)
        out_dir = keep_dir
        snap = _build_synthetic_run(out_dir)
        failures = validate_outputs(out_dir, snap)
    else:
        with tempfile.TemporaryDirectory() as td:
            out_dir = td
            snap = _build_synthetic_run(out_dir)
            failures = validate_outputs(out_dir, snap)
    if failures:
        for f in failures:
            print(f"telemetry selfcheck FAIL: {f}", file=sys.stderr)
        return 1
    print(
        "telemetry selfcheck OK: events.jsonl + trace.json + metrics.json "
        f"valid ({out_dir})"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m photon_ml_tpu.telemetry")
    p.add_argument(
        "--selfcheck", action="store_true",
        help="emit a synthetic span tree through every sink and validate "
        "the outputs",
    )
    p.add_argument(
        "--keep-dir",
        help="with --selfcheck: write the outputs here (inspectable) "
        "instead of a throwaway tempdir",
    )
    args = p.parse_args(argv)
    if args.selfcheck:
        return selfcheck(args.keep_dir)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
