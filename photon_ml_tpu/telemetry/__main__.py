"""Telemetry smoke entry point.

``python -m photon_ml_tpu.telemetry --selfcheck`` emits a synthetic span
tree (including a cross-thread producer span, instant events, and every
metric kind) through the full sink set into a scratch directory, then
validates the outputs:

- every ``events.jsonl`` line parses as JSON and carries type/name/ts;
- ``trace.json`` parses as a Chrome trace-event ARRAY whose span events
  have the required ph/ts/dur/pid/tid fields and whose parent links
  resolve;
- ``metrics.json`` round-trips the registry snapshot;
- **ops plane**: the time-series sampler wrote ≥ 2 monotone-timestamped
  snapshots to ``metrics_ts.jsonl`` carrying a live HBM-bytes gauge,
  the embedded exporter's ``/metrics`` output PARSES as Prometheus text
  exposition (and ``/snapshot`` as JSON), and the exporter thread joins
  cleanly on close;
- **flight recorder**: an injected chaos fault (``serving.batch`` via a
  scripted FaultPlan) dumps ``flightrecorder.json`` whose last-N events
  END at the fault site's ``chaos.fault`` record.

``--lint-metrics`` runs the metric-name lint (telemetry/lint.py) over
the package source instead: duplicate-kind registrations and
non-conforming ``<subsystem>_<name>_<unit>`` names fail the check.

Exit status 0 on success; nonzero with a diagnostic on any failure —
CI-greppable, device-free (never imports jax).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _build_synthetic_run(out_dir: str) -> dict:
    from photon_ml_tpu.telemetry import Telemetry, mount_ops_plane

    info: dict = {}
    with Telemetry(output_dir=out_dir, run_name="selfcheck") as tel:
        plane = mount_ops_plane(tel, port=0, interval_s=0.02)
        with tel.span("run", driver="selfcheck"):
            for it in range(2):
                with tel.span("cd_iteration", iteration=it):
                    for coord in ("fixed", "per_user"):
                        with tel.span(
                            "coordinate", coordinate=coord, iteration=it
                        ):
                            with tel.span(
                                "solver", coordinate=coord,
                                optimizer="lbfgs",
                            ) as sp:
                                time.sleep(0.001)
                                sp.set(iterations=7, converged=True)
                            tel.counter("solver_iterations").inc(7)
                tel.event(
                    "checkpoint.save", iteration=it, path="<synthetic>"
                )

            ctx = tel.current_context()

            def producer():
                # Cross-thread spans ATTACH the spawning span's context
                # (the h2d prefetch producer's shape) so the Perfetto
                # view nests the producer track under the run.
                with tel.attach(ctx):
                    for k in range(3):
                        with tel.span("chunk", index=k):
                            time.sleep(0.0005)
                        tel.histogram("stream_chunk_seconds").observe(
                            0.0005
                        )
                        tel.gauge("hbm_live_bytes").set((k + 1) * 1024)
                    tel.gauge("h2d_gbps").set(1.25)
                    tel.counter("h2d_bytes_total").inc(3 * 1024)

            t = threading.Thread(
                target=producer, name="h2d-prefetch", daemon=True
            )
            t.start()
            t.join()
            tel.event(
                "watchdog.attempt", attempt=0, outcome="ok",
                exception=None,
            )

            # Injected chaos fault → flight-recorder dump ending at the
            # fault site (chaos/core.py imports no jax; this stays a
            # device-free check).
            from photon_ml_tpu import chaos

            with chaos.FaultPlan([chaos.FaultSpec(site="serving.batch")]):
                try:
                    chaos.maybe_fail("serving.batch", rows=4)
                    info["fault_raised"] = False
                except chaos.InjectedFault:
                    info["fault_raised"] = True

            # Let the interval sampler take >= 2 samples past the start
            # sample, then scrape the live endpoints.
            time.sleep(0.08)
            import urllib.request

            port = plane.port
            for route, key in (
                ("/metrics", "prom_text"),
                ("/snapshot", "snapshot_body"),
                ("/healthz", "healthz_body"),
            ):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=10
                ) as resp:
                    info[key] = resp.read().decode()
                    info[key + "_status"] = resp.status
        snap = tel.snapshot()
        exporter = plane.exporter
        plane.close()
        info["exporter_alive_after_close"] = exporter.alive
        info["sampler_alive_after_close"] = (
            plane.sampler is not None and plane.sampler.alive
        )
    info["snapshot"] = snap
    return info


def validate_outputs(out_dir: str, snapshot: dict) -> list[str]:
    """Returns a list of failure strings (empty = pass)."""
    failures: list[str] = []

    events_path = os.path.join(out_dir, "events.jsonl")
    trace_path = os.path.join(out_dir, "trace.json")
    metrics_path = os.path.join(out_dir, "metrics.json")
    for p in (events_path, trace_path, metrics_path):
        if not os.path.exists(p):
            failures.append(f"missing output: {p}")
    if failures:
        return failures

    span_ids = set()
    parents = []
    n_lines = 0
    with open(events_path) as f:
        for lineno, line in enumerate(f, 1):
            n_lines += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                failures.append(f"events.jsonl:{lineno} unparseable: {e}")
                continue
            if rec.get("type") == "metrics":
                # Trailing registry snapshot record — no name/ts.
                continue
            if "type" not in rec or "name" not in rec or "ts" not in rec:
                failures.append(
                    f"events.jsonl:{lineno} missing type/name/ts: {rec}"
                )
            if rec.get("type") == "span":
                span_ids.add(rec["id"])
                if rec.get("parent") is not None:
                    parents.append((lineno, rec["parent"]))
                if rec.get("dur", -1.0) < 0.0:
                    failures.append(
                        f"events.jsonl:{lineno} negative span duration"
                    )
    if n_lines == 0:
        failures.append("events.jsonl is empty")
    for lineno, parent in parents:
        if parent not in span_ids:
            failures.append(
                f"events.jsonl:{lineno} dangling parent span {parent}"
            )

    with open(trace_path) as f:
        try:
            trace = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"trace.json unparseable: {e}")
            trace = None
    if trace is not None:
        if not isinstance(trace, list):
            failures.append(
                f"trace.json is {type(trace).__name__}, not an array"
            )
        else:
            n_spans = 0
            for i, ev in enumerate(trace):
                if not isinstance(ev, dict):
                    failures.append(f"trace.json[{i}] not an object")
                    continue
                missing = [k for k in ("name", "ph", "ts", "pid", "tid")
                           if k not in ev]
                if missing:
                    failures.append(
                        f"trace.json[{i}] missing {missing}"
                    )
                if ev.get("ph") == "X":
                    n_spans += 1
                    if "dur" not in ev:
                        failures.append(
                            f"trace.json[{i}] X event without dur"
                        )
            if n_spans == 0:
                failures.append("trace.json holds no span (X) events")

    with open(metrics_path) as f:
        try:
            metrics = json.load(f)
        except json.JSONDecodeError as e:
            failures.append(f"metrics.json unparseable: {e}")
            metrics = {}
    for kind in ("counters", "gauges", "histograms"):
        if kind not in metrics:
            failures.append(f"metrics.json missing {kind!r}")
        elif snapshot.get(kind) and metrics[kind] != json.loads(
            json.dumps(snapshot[kind])
        ):
            failures.append(
                f"metrics.json {kind} diverge from the live snapshot"
            )
    return failures


def validate_ops_plane(out_dir: str, info: dict) -> list[str]:
    """Validate the live ops plane's outputs: the time-series file, the
    Prometheus exposition scraped while the run was live, the exporter's
    thread lifecycle, and the chaos-fault flight-recorder dump."""
    from photon_ml_tpu.telemetry.exporter import parse_prometheus_text
    from photon_ml_tpu.telemetry.timeseries import read_series

    failures: list[str] = []

    # -- metrics_ts.jsonl: >= 2 monotone snapshots w/ live HBM gauge -------
    ts_path = os.path.join(out_dir, "metrics_ts.jsonl")
    if not os.path.exists(ts_path):
        failures.append(f"missing time series: {ts_path}")
    else:
        series = read_series(ts_path)
        if len(series) < 2:
            failures.append(
                f"metrics_ts.jsonl has {len(series)} snapshots, need >= 2"
            )
        for key in ("seq", "t_mono"):
            vals = [rec.get(key) for rec in series]
            if any(b <= a for a, b in zip(vals, vals[1:])):
                failures.append(
                    f"metrics_ts.jsonl {key} not strictly increasing: "
                    f"{vals}"
                )
        if series and "hbm_live_bytes" not in (
            series[-1].get("gauges") or {}
        ):
            failures.append(
                "metrics_ts.jsonl final snapshot lacks the live "
                "hbm_live_bytes gauge"
            )

    # -- /metrics parses as Prometheus exposition --------------------------
    prom = info.get("prom_text")
    if not prom:
        failures.append("/metrics returned no body")
    else:
        try:
            parsed = parse_prometheus_text(prom)
        except ValueError as e:
            failures.append(f"/metrics exposition unparseable: {e}")
            parsed = {}
        for family in ("hbm_live_bytes", "solver_iterations"):
            if (family, "") not in parsed:
                failures.append(
                    f"/metrics lacks the {family} family"
                )
        if not any(
            name == "stream_chunk_seconds" and 'quantile="0.5"' in labels
            for name, labels in parsed
        ):
            failures.append(
                "/metrics lacks histogram quantile samples "
                "(stream_chunk_seconds{quantile=...})"
            )

    # -- /snapshot + /healthz are JSON -------------------------------------
    for key in ("snapshot_body", "healthz_body"):
        body = info.get(key)
        if not body:
            failures.append(f"{key.split('_')[0]} endpoint returned nothing")
            continue
        try:
            json.loads(body)
        except json.JSONDecodeError as e:
            failures.append(f"{key} is not JSON: {e}")

    # -- exporter/sampler thread lifecycle ---------------------------------
    if info.get("exporter_alive_after_close"):
        failures.append("exporter thread still alive after close()")
    if info.get("sampler_alive_after_close"):
        failures.append("sampler thread still alive after stop()")

    # -- flight recorder: dump ends at the injected fault site -------------
    if not info.get("fault_raised"):
        failures.append("chaos fault did not raise (plan mis-armed?)")
    fr_path = os.path.join(out_dir, "flightrecorder.json")
    if not os.path.exists(fr_path):
        failures.append(f"missing flight-recorder dump: {fr_path}")
    else:
        with open(fr_path) as f:
            try:
                dump = json.load(f)
            except json.JSONDecodeError as e:
                failures.append(f"flightrecorder.json unparseable: {e}")
                dump = {}
        events = dump.get("events") or []
        if not events:
            failures.append("flightrecorder.json holds no events")
        else:
            last = events[-1]
            if last.get("name") != "chaos.fault" or (
                (last.get("attrs") or {}).get("site") != "serving.batch"
            ):
                failures.append(
                    "flightrecorder.json does not END at the fault "
                    f"site: last event {last.get('name')!r} "
                    f"attrs={last.get('attrs')}"
                )
        if dump.get("n_events", 0) > dump.get("capacity", 0):
            failures.append(
                "flight recorder dumped more events than its capacity"
            )
        if not str(dump.get("reason") or "").startswith("chaos"):
            failures.append(
                f"flight-recorder dump reason {dump.get('reason')!r} "
                "does not name the chaos fault"
            )
    return failures


def _run_and_validate(out_dir: str) -> list[str]:
    info = _build_synthetic_run(out_dir)
    failures = validate_outputs(out_dir, info["snapshot"])
    failures.extend(validate_ops_plane(out_dir, info))
    return failures


def selfcheck(keep_dir: str | None = None) -> int:
    if keep_dir is not None:
        os.makedirs(keep_dir, exist_ok=True)
        out_dir = keep_dir
        failures = _run_and_validate(out_dir)
    else:
        with tempfile.TemporaryDirectory() as td:
            out_dir = td
            failures = _run_and_validate(out_dir)
    if failures:
        for f in failures:
            print(f"telemetry selfcheck FAIL: {f}", file=sys.stderr)
        return 1
    print(
        "telemetry selfcheck OK: events.jsonl + trace.json + metrics.json "
        "+ metrics_ts.jsonl + /metrics exposition + flightrecorder.json "
        f"valid ({out_dir})"
    )
    return 0


def lint_metrics() -> int:
    from photon_ml_tpu.telemetry.lint import lint_source

    n_names, problems = lint_source()
    if problems:
        for p_ in problems:
            print(f"metric lint FAIL: {p_}", file=sys.stderr)
        return 1
    print(
        f"metric lint OK: {n_names} metric names conform "
        "(<subsystem>_<name>_<unit>, one kind per name)"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m photon_ml_tpu.telemetry")
    p.add_argument(
        "--selfcheck", action="store_true",
        help="emit a synthetic span tree through every sink + the live "
        "ops plane (time-series sampler, /metrics exporter, chaos-fault "
        "flight recorder) and validate every output",
    )
    p.add_argument(
        "--lint-metrics", action="store_true",
        help="scan the package source for metric registrations and "
        "enforce the naming convention + one-kind-per-name",
    )
    p.add_argument(
        "--keep-dir",
        help="with --selfcheck: write the outputs here (inspectable) "
        "instead of a throwaway tempdir",
    )
    args = p.parse_args(argv)
    if args.lint_metrics:
        return lint_metrics()
    if args.selfcheck:
        return selfcheck(args.keep_dir)
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
