"""Unified telemetry: spans, metrics, and pluggable sinks.

Public surface::

    from photon_ml_tpu import telemetry

    with telemetry.Telemetry(output_dir=out, logger=logger) as tel:
        with tel.span("run", driver="glm"):
            tel.event("checkpoint.save", path=p)
            tel.counter("solver_iterations").inc(12)

Library code that cannot be handed a hub uses :func:`current` — a
disabled no-op by default, the driver-installed hub inside a driver run.
``python -m photon_ml_tpu.telemetry --selfcheck`` exercises every sink
and validates the outputs (see __main__.py).

The LIVE ops plane (docs/telemetry.md "Live ops plane") composes on
top: :class:`TimeSeriesSampler` streams registry snapshots into
``metrics_ts.jsonl``, :class:`MetricsExporter` serves Prometheus text
exposition at ``/metrics`` (mount both with :func:`mount_ops_plane`
behind a ``--metrics-port`` flag), and the :class:`FlightRecorder`
ring dumps the last-N events on crash / watchdog-fatal / injected
chaos fault (:func:`dump_flight_recorder`).
"""

from photon_ml_tpu.telemetry.core import (  # noqa: F401
    NULL,
    TRACE_HEADER,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Span,
    Telemetry,
    TraceContext,
    current,
    dump_flight_recorder,
    json_safe,
    set_current,
)
from photon_ml_tpu.telemetry.exporter import (  # noqa: F401
    MetricsExporter,
    OpsPlane,
    host_identity,
    mount_ops_plane,
    parse_prometheus_text,
    prometheus_text,
)
from photon_ml_tpu.telemetry.recorder import FlightRecorder  # noqa: F401
from photon_ml_tpu.telemetry.sinks import (  # noqa: F401
    ChromeTraceSink,
    JsonlSink,
    LoggerSummarySink,
    Sink,
)
from photon_ml_tpu.telemetry.timeseries import (  # noqa: F401
    TimeSeriesSampler,
    read_series,
)
from photon_ml_tpu.telemetry.fleet import (  # noqa: F401
    FleetAggregator,
    SloPolicy,
)
