"""Unified telemetry: spans, metrics, and pluggable sinks.

Public surface::

    from photon_ml_tpu import telemetry

    with telemetry.Telemetry(output_dir=out, logger=logger) as tel:
        with tel.span("run", driver="glm"):
            tel.event("checkpoint.save", path=p)
            tel.counter("solver_iterations").inc(12)

Library code that cannot be handed a hub uses :func:`current` — a
disabled no-op by default, the driver-installed hub inside a driver run.
``python -m photon_ml_tpu.telemetry --selfcheck`` exercises every sink
and validates the outputs (see __main__.py).
"""

from photon_ml_tpu.telemetry.core import (  # noqa: F401
    NULL,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Span,
    Telemetry,
    current,
    json_safe,
    set_current,
)
from photon_ml_tpu.telemetry.sinks import (  # noqa: F401
    ChromeTraceSink,
    JsonlSink,
    LoggerSummarySink,
    Sink,
)
