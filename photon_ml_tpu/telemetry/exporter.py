"""Embedded metrics endpoint: Prometheus text exposition + JSON snapshot.

A stdlib ``ThreadingHTTPServer`` (no dependencies, same shape as the
serving endpoint) that any long-running process mounts behind a
``--metrics-port`` flag:

- ``GET /metrics``  — Prometheus text exposition (format 0.0.4) of the
  hub's registry: counters and numeric gauges as-is, histograms as
  summaries (``{quantile="0.5|0.9|0.99"}`` + ``_sum`` + ``_count``).
  Scrape it with any Prometheus/VictoriaMetrics/agent setup.
- ``GET /snapshot`` — the full registry snapshot as JSON (includes the
  non-numeric gauges Prometheus cannot carry) plus run identity
  (``trace``, ``wall_epoch``, ``pid``).
- ``GET /healthz``  — liveness: ``{"status": "ok", ...}``.  Always
  "ok" while the process answers — the exporter is alive iff it serves.
- ``GET /livez``    — alias of the same liveness verdict.
- ``GET /readyz``   — readiness, DISTINCT from liveness: when the
  mounting process supplied a ``readiness`` callable (the serving CLI
  passes ``ScoringService.readiness``), 503 ``"not_ready"`` during
  startup warmup / mid-swap / zero healthy replicas; without one, ready
  iff serving (matching /healthz).  Load balancers route on THIS.

``mount_ops_plane`` is the one-call composition the drivers, the tuning
orchestrator, and the serving CLI use: time-series sampler
(telemetry/timeseries.py) + exporter, both torn down by ``close()`` with
no thread leak (the lifecycle the ops-plane tests pin).
"""

from __future__ import annotations

import json
import math
import os
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from photon_ml_tpu.telemetry.timeseries import TimeSeriesSampler


def host_identity(host_id: Optional[str] = None) -> dict:
    """Stable provenance block every snapshot/heartbeat carries so the
    fleet aggregator and trace stitching never GUESS which host a
    number came from: explicit ``host_id`` > ``$PHOTON_HOST_ID`` >
    hostname, plus the emitting pid."""
    return {
        "host_id": str(
            host_id
            or os.environ.get("PHOTON_HOST_ID")
            or socket.gethostname()
        ),
        "pid": os.getpid(),
    }

#: summary quantiles /metrics exposes per histogram.
QUANTILES = (0.5, 0.9, 0.99)

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+"
    r"([-+]?(?:[0-9.]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$"
)


def _sanitize(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _fmt(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def prometheus_text(snapshot: dict) -> str:
    """Render a registry snapshot as Prometheus text exposition.

    Pure function of the snapshot (unit-testable without HTTP).
    Non-numeric gauges are skipped — they remain visible on /snapshot.
    """
    lines: list[str] = []
    for name in sorted(snapshot.get("counters") or {}):
        value = snapshot["counters"][name]
        if not isinstance(value, (int, float)):
            continue
        safe = _sanitize(name)
        lines.append(f"# TYPE {safe} counter")
        lines.append(f"{safe} {_fmt(value)}")
    for name in sorted(snapshot.get("gauges") or {}):
        value = snapshot["gauges"][name]
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        safe = _sanitize(name)
        lines.append(f"# TYPE {safe} gauge")
        lines.append(f"{safe} {_fmt(value)}")
    for name in sorted(snapshot.get("histograms") or {}):
        h = snapshot["histograms"][name]
        if not h.get("count"):
            continue
        safe = _sanitize(name)
        lines.append(f"# TYPE {safe} summary")
        for q, key in zip(QUANTILES, ("p50", "p90", "p99")):
            v = h.get(key)
            if v is not None:
                lines.append(f'{safe}{{quantile="{q}"}} {_fmt(v)}')
        lines.append(f"{safe}_sum {_fmt(h['sum'])}")
        lines.append(f"{safe}_count {h['count']}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Minimal exposition-format parser: ``{(name, labels): value}``.

    Raises ``ValueError`` on any malformed line — the selfcheck uses
    this to prove /metrics output actually parses, not merely that it
    was served.
    """
    out: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            raise ValueError(
                f"unparseable exposition line {lineno}: {line!r}"
            )
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        out[(name, labels)] = float(value)
    return out


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass

    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        hub = self.server.exporter.hub
        hub.counter("telemetry_scrapes_total").inc()
        if self.path == "/metrics":
            body = prometheus_text(hub.snapshot()).encode()
            self._send(
                200, body, "text/plain; version=0.0.4; charset=utf-8"
            )
        elif self.path == "/snapshot":
            snap = hub.snapshot()
            snap["wall_epoch"] = hub._epoch_wall
            snap["trace"] = hub.trace_id
            snap["pid"] = os.getpid()
            snap["host"] = host_identity(self.server.exporter.host_id)
            # Mergeable histogram state rides alongside the summaries:
            # the fleet aggregator folds /snapshot via absorb_delta,
            # which needs raw bucket vectors, not quantiles.
            snap["transport"] = hub.metrics.transport_snapshot()
            self._send(
                200, json.dumps(snap).encode(), "application/json"
            )
        elif self.path in ("/healthz", "/livez"):
            self._send(200, json.dumps({
                "status": "ok",
                "pid": os.getpid(),
                "trace": hub.trace_id,
                "uptime_s": round(
                    time.perf_counter() - hub._epoch_perf, 3
                ),
            }).encode(), "application/json")
        elif self.path == "/readyz":
            ready, reason = True, "ok"
            readiness = self.server.exporter.readiness
            if readiness is not None:
                try:
                    verdict = readiness()
                    # accept a bare bool or a (bool, reason) tuple
                    if isinstance(verdict, tuple):
                        ready, reason = verdict
                    else:
                        ready, reason = bool(verdict), ""
                except Exception as exc:  # noqa: BLE001 — fail not-ready
                    ready, reason = False, f"readiness check failed: {exc}"
            self._send(200 if ready else 503, json.dumps({
                "status": "ready" if ready else "not_ready",
                "reason": reason,
            }).encode(), "application/json")
        else:
            self._send(
                404,
                json.dumps({"error": f"no route {self.path}"}).encode(),
                "application/json",
            )


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    exporter: "MetricsExporter"


class MetricsExporter:
    """HTTP exposition of one hub's registry; start/close lifecycle."""

    def __init__(
        self, hub, host: str = "127.0.0.1", port: int = 0, readiness=None,
        host_id: Optional[str] = None,
    ):
        self.hub = hub
        self.host = host
        #: optional ``() -> bool | (bool, reason)`` behind /readyz; None
        #: keeps the pre-split behavior (ready iff serving).
        self.readiness = readiness
        #: stable identity /snapshot publishes (see :func:`host_identity`).
        self.host_id = host_id
        self._requested_port = port
        self._server: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        self._server = _Server((self.host, self._requested_port), _Handler)
        self._server.exporter = self
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="telemetry-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return None if self._server is None else (
            self._server.server_address[1]
        )

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def close(self, timeout: float = 5.0) -> None:
        """Shut the server down and JOIN its thread (no leaked daemon —
        the lifecycle tests assert this survives chaos teardown paths).
        Idempotent."""
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=timeout)


class OpsPlane:
    """Handle over the mounted live-ops pieces; ``close()`` is the one
    teardown call (sampler final-sample + exporter join), idempotent and
    exception-safe."""

    def __init__(
        self,
        sampler: Optional[TimeSeriesSampler],
        exporter: Optional[MetricsExporter],
        logger=None,
    ):
        self.sampler = sampler
        self.exporter = exporter
        if logger is not None and exporter is not None:
            logger.info(
                "metrics exporter on http://%s:%d (/metrics /snapshot "
                "/healthz /livez /readyz)", exporter.host, exporter.port,
            )

    @property
    def port(self) -> Optional[int]:
        return None if self.exporter is None else self.exporter.port

    def close(self) -> None:
        try:
            if self.sampler is not None:
                self.sampler.stop()
        finally:
            if self.exporter is not None:
                self.exporter.close()

    def __enter__(self) -> "OpsPlane":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def mount_ops_plane(
    hub,
    port: Optional[int] = None,
    interval_s: float = 1.0,
    host: str = "127.0.0.1",
    ts_path: Optional[str] = None,
    logger=None,
    readiness=None,
) -> OpsPlane:
    """Mount the live ops plane on ``hub``: a metrics_ts.jsonl sampler
    (when the hub has an output dir and ``interval_s > 0``) and the HTTP
    exporter (when ``port`` is not None; 0 binds an ephemeral port).
    Disabled hubs get an inert plane — callers mount unconditionally.
    """
    sampler = None
    exporter = None
    if hub.enabled:
        sampler = TimeSeriesSampler(
            hub, path=ts_path, interval_s=interval_s
        )
        sampler.start()
        if not sampler.enabled:
            sampler = None
        if port is not None and port >= 0:
            exporter = MetricsExporter(
                hub, host=host, port=port, readiness=readiness
            ).start()
    return OpsPlane(sampler, exporter, logger=logger)
