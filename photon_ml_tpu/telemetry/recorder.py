"""Flight recorder: a bounded ring of recent telemetry records.

The end-of-run sinks (events.jsonl, trace.json) answer "what happened"
only after a run ends cleanly; a crashed long run leaves a partial event
log whose interesting part — the seconds before the failure — is buried
at the tail of a file that may be gigabytes deep.  The flight recorder
is the aviation-style answer: a fixed-capacity ring that every record
passes through at append cost, dumped to ``flightrecorder.json`` only
when something goes wrong:

- a driver crash (``Telemetry.__exit__`` with an exception),
- a watchdog-fatal failure (``utils/watchdog.run_with_retries`` giving
  up or classifying non-transient),
- an injected chaos fault (``chaos/core.FaultPlan`` firing a "raise"
  action) — so every fault-injection test doubles as a forensics test.

The ring is a ``collections.deque(maxlen=capacity)``: appends are
atomic under CPython's GIL (no lock on the hot path) and the oldest
record falls off for free, so a runaway emitter costs bounded memory
and zero coordination.  Records arrive already JSON-sanitized (the hub
sanitizes attrs before fan-out), so a dump is a straight ``json.dump``.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Optional

from photon_ml_tpu.telemetry.sinks import Sink


class FlightRecorder(Sink):
    """Bounded ring of the most recent span/event/meta records.

    Installed automatically in the standard sink set of every hub built
    with an ``output_dir``; dump via
    :meth:`photon_ml_tpu.telemetry.Telemetry.dump_flight_recorder`.
    """

    DEFAULT_CAPACITY = 512

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        #: best-effort total records seen (unlocked increment; the exact
        #: value is forensic context, not an invariant).
        self.records_seen = 0

    # -- sink contract -------------------------------------------------------
    def emit(self, record: dict) -> None:
        self._ring.append(record)
        self.records_seen += 1

    def close(self, snapshot: dict) -> None:
        # Keep the ring: Telemetry.__exit__ dumps AFTER restoring the
        # previous hub, and tests inspect post-close contents.
        pass

    # -- forensics -----------------------------------------------------------
    def snapshot(self) -> list[dict]:
        """Copy of the ring, oldest first."""
        return list(self._ring)

    def dump(
        self,
        path: str,
        reason: Optional[str] = None,
        wall_epoch: Optional[float] = None,
        trace: Optional[str] = None,
    ) -> str:
        """Write the ring (oldest → newest) plus dump metadata to
        ``path`` atomically; returns ``path``.  The newest record is the
        last element of ``events`` — for a fault-triggered dump that is
        the fault site's own record."""
        events = list(self._ring)
        payload = {
            "reason": reason,
            "dumped_at_wall": time.time(),
            "wall_epoch": wall_epoch,
            "trace": trace,
            "capacity": self.capacity,
            "records_seen": self.records_seen,
            "n_events": len(events),
            "events": events,
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path
