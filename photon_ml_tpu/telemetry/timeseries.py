"""Time-series metrics: periodic registry snapshots to metrics_ts.jsonl.

``metrics.json`` is one end-of-run dump — useless for a run that takes
hours (or never ends, like the serving service).  The sampler turns the
same registry into a STREAM: a background thread snapshots every
``interval_s`` seconds and appends one JSON line to
``metrics_ts.jsonl`` in the hub's output dir, so a long run's observable
state is live (tail the file, or scrape /metrics — telemetry/exporter.py
serves the same registry over HTTP).

Records are append-only and self-describing::

    {"seq": 3, "t_wall": 1754..., "t_mono": 12.04,
     "host": {"host_id": "...", "pid": 1234},
     "counters": {...}, "gauges": {...}, "histograms": {...}}

``t_mono`` is monotonic seconds since the hub's epoch (immune to
wall-clock steps — consecutive records always have increasing ``t_mono``
and ``seq``); ``t_wall`` correlates across processes.  The file is
bounded: past ``max_bytes`` it rotates (``metrics_ts.jsonl`` →
``.1`` → ... → ``.keep``, oldest dropped), so an unattended month-long
run costs at most ``(keep + 1) * max_bytes`` of disk.

One sample is written at start and one at stop, so even a short run's
series brackets the run (≥ 2 records) and the final record equals the
end-of-run ``metrics.json`` state.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class TimeSeriesSampler:
    """Background interval snapshots of a hub's metrics registry.

    Use as a context manager (the ops-plane mount does)::

        with TimeSeriesSampler(tel, interval_s=1.0):
            ... long run ...

    A disabled hub, a missing destination, or ``interval_s <= 0`` makes
    the sampler a no-op — callers mount unconditionally.
    """

    def __init__(
        self,
        hub,
        path: Optional[str] = None,
        interval_s: float = 1.0,
        max_bytes: int = 4 << 20,
        keep: int = 2,
    ):
        if path is None and hub.output_dir is not None:
            path = os.path.join(hub.output_dir, "metrics_ts.jsonl")
        self.hub = hub
        self.path = path
        self.interval_s = float(interval_s)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self.enabled = bool(
            hub.enabled and path is not None and self.interval_s > 0
        )
        self.samples = 0
        self._seq = 0
        self._file = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TimeSeriesSampler":
        if not self.enabled or self._thread is not None:
            return self
        self._file = open(self.path, "w")
        self.sample()  # the series always brackets the run
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-ts", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Final sample + clean thread shutdown.  Idempotent."""
        if not self.enabled:
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._file is not None:
            self.sample()
            with self._lock:
                self._file.close()
                self._file = None

    def __enter__(self) -> "TimeSeriesSampler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- sampling ------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:
                # Observability must never sink the job it observes; a
                # failed write (disk full) drops this sample only.
                pass

    def sample(self) -> Optional[dict]:
        """Take one snapshot and append it; safe from any thread.
        Returns the record (None when disabled/closed)."""
        if not self.enabled:
            return None
        snap = self.hub.metrics.snapshot()
        # Local import: exporter imports this module at its top level.
        from photon_ml_tpu.telemetry.exporter import host_identity

        with self._lock:
            if self._file is None:
                return None
            record = {
                "seq": self._seq,
                "t_wall": time.time(),
                "t_mono": time.perf_counter() - self.hub._epoch_perf,
                "host": host_identity(),
                "counters": snap["counters"],
                "gauges": snap["gauges"],
                "histograms": snap["histograms"],
            }
            self._seq += 1
            self.samples += 1
            # Rotate BEFORE writing: the live file always ends with the
            # newest record (a reader tailing metrics_ts.jsonl never
            # finds it freshly-empty after a rotation).
            if self._file.tell() > self.max_bytes:
                self._rotate_locked()
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        return record

    def _rotate_locked(self) -> None:
        """path → path.1 → ... → path.keep (oldest generation dropped)."""
        self._file.close()
        for i in range(self.keep, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")
        self._file = open(self.path, "w")


def read_series(path: str) -> list[dict]:
    """Parse a metrics_ts.jsonl file (tolerating a torn final line — the
    sampler can die mid-write on a crash, exactly when the series is
    being read forensically)."""
    records = []
    with open(path) as f:
        for line in f:
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                break
    return records
