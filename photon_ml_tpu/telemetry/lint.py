"""Metric-name lint — thin compatibility shim.

The implementation moved to :mod:`photon_ml_tpu.analysis.rules_registry`
as the ``metric-naming`` rule of the project-wide invariant checker
(``python -m photon_ml_tpu.analysis --check``); this module re-exports
the old surface so ``python -m photon_ml_tpu.telemetry --lint-metrics``
and existing imports keep working unchanged.
"""

from __future__ import annotations

from photon_ml_tpu.analysis.engine import SourceTree
from photon_ml_tpu.analysis.rules_registry import (  # noqa: F401
    LEGACY_NAMES,
    SUBSYSTEMS,
    UNITS,
    lint_name,
    lint_source,
    scan_tree,
)


def scan_source(roots=None) -> list[tuple[str, str, str, int]]:
    """Old entry point: ``(name, kind, relpath, lineno)`` hits over the
    default roots (package + bench.py) or explicit ``roots``."""
    return scan_tree(SourceTree(roots=roots))


__all__ = [
    "LEGACY_NAMES",
    "SUBSYSTEMS",
    "UNITS",
    "lint_name",
    "lint_source",
    "scan_source",
]
