"""Metric-name lint: one name = one kind, `<subsystem>_<name>_<unit>`.

Two enforcement layers:

- **Runtime** (telemetry/core.py ``MetricsRegistry``): registering one
  name as two different kinds raises immediately — a counter and a
  gauge sharing a name cannot both exist in a Prometheus exposition,
  and the bug would otherwise surface as silently-wrong scraped data.
- **Source lint** (this module, ``python -m photon_ml_tpu.telemetry
  --lint-metrics``, wired into scripts/check.sh): scans the package for
  string-literal metric registrations and checks every name against the
  convention — lowercase snake_case, a known subsystem prefix, a known
  unit suffix — plus cross-file kind consistency.  Names that predate
  the convention are grandfathered in :data:`LEGACY_NAMES` (burn the
  list down, never grow it: new metrics must conform).
"""

from __future__ import annotations

import os
import re
from typing import Optional

#: First name token: which subsystem emits the metric.
SUBSYSTEMS = frozenset({
    "h2d", "hbm", "prefetch", "stream", "streaming", "staging",
    "solver", "cd", "grid", "game", "glm", "watchdog", "checkpoint",
    "chaos", "serving", "tuning", "compile", "run", "telemetry",
    "evaluation", "model",
})

#: Last name token: what the value measures.
UNITS = frozenset({
    "total", "seconds", "bytes", "ratio", "gbps", "rows", "ms",
    "count", "entries", "iterations", "retries", "depth", "version",
    "tier",
})

#: Pre-convention names (PRs 1-6), grandfathered verbatim.  Do NOT add
#: to this list — rename or conform instead; each entry is a pending
#: rename chore.
LEGACY_NAMES = frozenset({
    "chaos_faults_injected",
    "checkpoint_corruptions",
    "checkpoint_fallbacks",
    "checkpoint_restores",
    "checkpoint_saves",
    "compile_cache_warmup_compiles",
    "consumer_stall_seconds",
    "consumer_stalls",
    "producer_stall_seconds",
    "producer_stalls",
    "prefetch_max_live",
    "prefetch_passes",
    "prefetch_thread_leak",
    "scored_rows",
    "serving_batch_occupancy",
    "serving_degraded",
    "tuning_best_metric",
    "tuning_trials_completed",
    "tuning_trials_failed",
    "tuning_trials_pruned",
    "tuning_trials_started",
})

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")
_CALL_RE = re.compile(r"\.(counter|gauge|histogram)\(\s*\"([a-z0-9_]+)\"")


def lint_name(name: str, kind: Optional[str] = None) -> list[str]:
    """Issues with one metric name (empty list = conforming)."""
    if name in LEGACY_NAMES:
        return []
    issues = []
    if not _NAME_RE.match(name):
        issues.append(
            f"{name!r}: not lowercase snake_case with >= 2 tokens"
        )
        return issues
    tokens = name.split("_")
    if tokens[0] not in SUBSYSTEMS:
        issues.append(
            f"{name!r}: unknown subsystem prefix {tokens[0]!r} "
            f"(known: {sorted(SUBSYSTEMS)})"
        )
    if tokens[-1] not in UNITS:
        issues.append(
            f"{name!r}: unknown unit suffix {tokens[-1]!r} "
            f"(known: {sorted(UNITS)})"
        )
    return issues


def scan_source(roots=None) -> list[tuple[str, str, str, int]]:
    """String-literal metric registrations across the package source:
    ``(name, kind, file, lineno)``.  Dynamically-built names (f-strings)
    are invisible here — the runtime kind check still covers them."""
    if roots is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        roots = [pkg, os.path.join(os.path.dirname(pkg), "bench.py")]
    hits: list[tuple[str, str, str, int]] = []
    files: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames if f.endswith(".py")
            )
    for path in sorted(files):
        if os.path.abspath(path) == os.path.abspath(__file__):
            continue
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                for m in _CALL_RE.finditer(line):
                    hits.append((m.group(2), m.group(1), path, lineno))
    return hits


def lint_source(roots=None) -> tuple[int, list[str]]:
    """Lint every registration the source scan finds.

    Returns ``(n_names, problems)`` — naming violations plus any name
    registered as two different kinds anywhere in the tree.
    """
    hits = scan_source(roots)
    problems: list[str] = []
    kinds: dict[str, dict[str, tuple[str, int]]] = {}
    for name, kind, path, lineno in hits:
        kinds.setdefault(name, {}).setdefault(kind, (path, lineno))
    for name in sorted(kinds):
        by_kind = kinds[name]
        if len(by_kind) > 1:
            sites = ", ".join(
                f"{kind} at {os.path.relpath(path)}:{lineno}"
                for kind, (path, lineno) in sorted(by_kind.items())
            )
            problems.append(
                f"{name!r} registered as multiple kinds: {sites}"
            )
        kind = next(iter(by_kind))
        for issue in lint_name(name, kind):
            path, lineno = by_kind[kind]
            problems.append(
                f"{issue} (first seen {os.path.relpath(path)}:{lineno})"
            )
    return len(kinds), problems
