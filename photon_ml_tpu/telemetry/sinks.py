"""Telemetry sinks: JSONL event log, Chrome trace export, logger summary.

Sink contract: ``emit(record)`` receives every span/event/meta record
(already JSON-sanitized attrs, monotonic ``ts``/``dur`` in SECONDS since
the hub's epoch); ``close(metrics_snapshot)`` flushes/finalizes.  The hub
serializes ``emit`` calls under one lock and swallows sink exceptions —
observability must never sink the job it observes.
"""

from __future__ import annotations

import json
import os
import threading


class Sink:
    def emit(self, record: dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self, snapshot: dict) -> None:
        pass


class JsonlSink(Sink):
    """One JSON object per line in ``events.jsonl`` — the source of truth
    every other view (trace, summary) can be rebuilt from."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w", buffering=1)  # line-buffered

    def emit(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")

    def close(self, snapshot: dict) -> None:
        try:
            self._f.write(
                json.dumps({"type": "metrics", "snapshot": snapshot}) + "\n"
            )
        finally:
            self._f.close()


class ChromeTraceSink(Sink):
    """Buffers records and writes a Chrome trace-event ARRAY at close —
    loadable in Perfetto / ``chrome://tracing``.

    Spans become complete ("X") events, instants become "i" events, and
    counter/gauge metrics are appended as one final "C" sample so the
    trace carries the end-of-run numbers.  The buffer is bounded: a
    runaway emitter degrades to a truncated trace (with a drop marker),
    never to unbounded host memory.

    Timestamps are WALL-ANCHORED by default: the hub's monotonic ``ts``
    offsets are shifted by the ``wall_epoch`` the meta record carries,
    so traces from several processes (a training driver + its tuning
    workers + a serving sidecar) concatenate into ONE merged Perfetto
    timeline with correct relative placement.  ``anchor_wall=False``
    keeps the raw run-relative offsets.
    """

    MAX_RECORDS = 500_000

    def __init__(self, path: str, anchor_wall: bool = True):
        self.path = path
        self.anchor_wall = anchor_wall
        self._records: list[dict] = []
        self._dropped = 0
        self._pid = os.getpid()
        self._wall_epoch = 0.0

    def emit(self, record: dict) -> None:
        if record.get("type") == "meta" and record.get("wall_epoch"):
            self._wall_epoch = float(record["wall_epoch"])
        if len(self._records) >= self.MAX_RECORDS:
            self._dropped += 1
            return
        self._records.append(record)

    def _anchor(self) -> float:
        return self._wall_epoch if self.anchor_wall else 0.0

    def _convert(self, record: dict) -> dict | None:
        kind = record.get("type")
        ts_us = (record.get("ts", 0.0) + self._anchor()) * 1e6
        base = {
            "name": record.get("name", "?"),
            "pid": self._pid,
            "tid": record.get("tid", 0),
            "ts": ts_us,
        }
        args = dict(record.get("attrs") or {})
        if record.get("error"):
            args["error"] = record["error"]
        if kind == "span":
            base["ph"] = "X"
            base["dur"] = record.get("dur", 0.0) * 1e6
            args["span_id"] = record.get("id")
            if record.get("parent") is not None:
                args["parent_span_id"] = record["parent"]
            # Distributed-trace stitching fields (telemetry/core.py
            # trace-context section): the shared trace id, this span's
            # GLOBAL id, the remote parent's global id, and the
            # tail-retention marker — concatenated per-process traces
            # merge into one Perfetto timeline that preserves the
            # cross-process parent links through these args.
            for key in ("trace", "gid", "rparent", "tail"):
                if record.get(key) is not None:
                    args[key] = record[key]
        elif kind == "event":
            base["ph"] = "i"
            base["s"] = "t"  # thread-scoped instant
        elif kind == "meta":
            base["ph"] = "i"
            base["s"] = "g"  # global instant marking run start
            args["wall_epoch"] = record.get("wall_epoch")
        else:
            return None
        if args:
            base["args"] = args
        return base

    def close(self, snapshot: dict) -> None:
        events = []
        for record in self._records:
            ev = self._convert(record)
            if ev is not None:
                events.append(ev)
        last_ts = max((e["ts"] for e in events), default=0.0)
        for kind in ("counters", "gauges"):
            for name, value in (snapshot.get(kind) or {}).items():
                if isinstance(value, (int, float)):
                    events.append({
                        "name": name, "ph": "C", "pid": self._pid,
                        "tid": 0, "ts": last_ts,
                        "args": {"value": value},
                    })
        if self._dropped:
            events.append({
                "name": "trace_truncated", "ph": "i", "s": "g",
                "pid": self._pid, "tid": 0, "ts": last_ts,
                "args": {"dropped_records": self._dropped},
            })
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(events, f)
        os.replace(tmp, self.path)
        self._records = []


class LoggerSummarySink(Sink):
    """Human-readable end-of-run summary through ``PhotonLogger``:
    per-span-name aggregate wall clock plus the metric values — the
    at-a-glance "where did the time go" the reference read off the Spark
    UI."""

    MAX_LINES = 40

    def __init__(self, logger):
        self.logger = logger
        self._lock = threading.Lock()
        # name -> [count, total_seconds]
        self._spans: dict[str, list] = {}
        self._events: dict[str, int] = {}

    def emit(self, record: dict) -> None:
        kind = record.get("type")
        name = record.get("name", "?")
        with self._lock:
            if kind == "span":
                agg = self._spans.setdefault(name, [0, 0.0])
                agg[0] += 1
                agg[1] += record.get("dur", 0.0)
            elif kind == "event":
                self._events[name] = self._events.get(name, 0) + 1

    def close(self, snapshot: dict) -> None:
        log = self.logger
        if log is None:
            return
        with self._lock:
            spans = sorted(
                self._spans.items(), key=lambda kv: -kv[1][1]
            )
            events = dict(self._events)
        log.info("telemetry summary (spans, by total wall):")
        for name, (count, total) in spans[: self.MAX_LINES]:
            log.info(
                "  %-28s %6d x  %9.3fs total  %9.3fs mean",
                name, count, total, total / count,
            )
        if events:
            log.info(
                "telemetry events: %s",
                {k: events[k] for k in sorted(events)},
            )
        for kind in ("counters", "gauges"):
            table = snapshot.get(kind) or {}
            if table:
                log.info("telemetry %s: %s", kind, table)
        hists = snapshot.get("histograms") or {}
        for name in sorted(hists):
            h = hists[name]
            if not h["count"]:
                continue
            log.info(
                "telemetry histogram %s: n=%d mean=%.6g min=%.6g max=%.6g",
                name, h["count"], h["mean"], h["min"], h["max"],
            )
