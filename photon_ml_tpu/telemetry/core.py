"""Telemetry core: hierarchical spans, a metrics registry, and the hub.

The reference leaned on Spark's UI and executor logs for run visibility;
a single-process TPU driver has neither, so this package is the common
event stream the scattered fragments (``PhotonLogger`` lines, ``Timer``
measurements, ``TransferStats``, watchdog decisions) feed into:

- **Spans** — hierarchical wall-clock intervals (``run → coordinate →
  solver → chunk``) with monotonic timestamps and structured attributes.
  Nesting is tracked per thread; spans opened on other threads (the
  prefetch producer) become roots of their own stacks.
- **Metrics registry** — named counters, gauges, and histograms
  (``h2d_gbps``, ``consumer_stall_seconds``, ``solver_iterations``, ...)
  snapshotted to JSON at end of run.
- **Sinks** (telemetry/sinks.py) — JSONL event log (source of truth),
  Chrome trace-event ``trace.json`` (Perfetto / ``chrome://tracing``),
  and a human-readable end-of-run summary through ``PhotonLogger``.

Cost contract: telemetry is default-on but must be no-op cheap — a
disabled or sink-less hub costs ONE branch per event/span, and nothing
in this package ever touches a device array's values or forces a sync
the caller didn't already do (device arrays in attributes are recorded
as shape/dtype placeholders, never materialized).
"""

from __future__ import annotations

import bisect
import contextlib
import itertools
import json
import math
import os
import threading
import time
import uuid
from typing import Optional


# ---------------------------------------------------------------------------
# JSON sanitization (device-sync-safe)
# ---------------------------------------------------------------------------

def json_safe(value):
    """Best-effort conversion of an attribute value to JSON-able data.

    Never materializes a device array: anything exposing ``shape``/
    ``dtype`` that is not a host numpy array becomes a placeholder
    string (reading ``.shape`` does not sync; ``str(arr)`` would).
    """
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, float):
        # NaN/inf are not valid JSON; keep the record parseable.
        return value if math.isfinite(value) else repr(value)
    import numpy as np

    if isinstance(value, np.generic):
        return json_safe(value.item())
    if isinstance(value, np.ndarray):
        if value.size <= 32:
            return [json_safe(v) for v in value.tolist()]
        return f"<ndarray shape={value.shape} dtype={value.dtype}>"
    if hasattr(value, "shape") and hasattr(value, "dtype"):
        # jax.Array (possibly still executing on device): shape/dtype are
        # metadata reads, str() would block on the computation.
        return f"<array shape={tuple(value.shape)} dtype={value.dtype}>"
    if isinstance(value, dict):
        return {str(k): json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_safe(v) for v in value]
    if isinstance(value, BaseException):
        return f"{type(value).__name__}: {value}"
    try:
        json.dumps(value)
        return value
    except TypeError:
        return str(value)


# ---------------------------------------------------------------------------
# Distributed trace context
# ---------------------------------------------------------------------------

#: HTTP header carrying the serialized trace context on the JSON path.
TRACE_HEADER = "X-Photon-Trace"


class TraceContext:
    """The compact context that rides every transport hop.

    Three fields, two encodings: the string form
    (``"<trace16hex>-<span16hex>-<0|1>"``) travels as an HTTP header and
    as a string column in wire frames; :meth:`to_words` packs the same
    data into three fixed integers for binary slot headers (shm ring).
    ``span_id`` is the GLOBAL id of the remote parent span (0 = the
    trace root: no parent yet); ``sampled`` is the head-sampling verdict
    made once at the edge and honored by every hop downstream, so one
    request is either traced everywhere or nowhere (tail retention
    excepted — see :meth:`Telemetry.configure_tracing`).
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: int = 0,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = int(span_id)
        self.sampled = bool(sampled)

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id!r}, "
                f"{self.span_id:#x}, sampled={self.sampled})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.sampled == other.sampled)

    def header_value(self) -> str:
        """String form for headers / wire string columns."""
        return f"{self.trace_id}-{self.span_id:016x}-{int(self.sampled)}"

    @classmethod
    def parse(cls, text) -> Optional["TraceContext"]:
        """Parse :meth:`header_value` output; None on anything malformed
        (propagation is best-effort — a bad header degrades to an
        untraced request, never a failed one)."""
        if not text or not isinstance(text, str):
            return None
        parts = text.strip().split("-")
        if len(parts) != 3 or len(parts[0]) != 16:
            return None
        try:
            trace_word = int(parts[0], 16)
            span_id = int(parts[1], 16)
            sampled = bool(int(parts[2]))
        except ValueError:
            return None
        if trace_word == 0:
            return None
        return cls(parts[0], span_id, sampled)

    def to_words(self) -> tuple:
        """``(trace_word, span_word, flags)`` — three unsigned ints for
        fixed binary headers.  ``trace_word`` is never 0 for a live
        context, so 0 doubles as "no context" on the wire."""
        return (int(self.trace_id, 16), self.span_id,
                1 if self.sampled else 0)

    @classmethod
    def from_words(cls, trace_word: int, span_word: int,
                   flags: int) -> Optional["TraceContext"]:
        if not trace_word:
            return None
        return cls(f"{trace_word:016x}", span_word, bool(flags & 1))


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

class Counter:
    """Monotonically increasing count (events, retries, bytes moved)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-written value (rates, depths, sizes)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = None

    def set(self, v) -> None:
        with self._lock:
            self.value = v


#: Log-spaced histogram bucket upper bounds: ~10 per decade over
#: 1e-9 .. 1e10 — wide enough for nanosecond latencies through terabyte
#: counts without per-histogram configuration.  Bucket resolution bounds
#: the quantile error: a bound is ≤ 1.26x its predecessor, and
#: :meth:`Histogram.quantile` interpolates inside the bucket, so
#: quantiles land within a few percent of the exact order statistic.
_BUCKET_MANTISSAS = (1.0, 1.25, 1.6, 2.0, 2.5, 3.15, 4.0, 5.0, 6.3, 8.0)
BUCKET_BOUNDS = tuple(
    m * 10.0 ** e for e in range(-9, 11) for m in _BUCKET_MANTISSAS
)


class Histogram:
    """Streaming summary of observed values.

    Tracks count/sum/min/max/last exactly plus a fixed log-spaced bucket
    grid (:data:`BUCKET_BOUNDS`) that supports :meth:`quantile` without
    retaining observations — the ad-hoc ``np.percentile`` over saved
    sample lists this replaces kept O(n) host memory per metric.
    Values ≤ 0 land in the underflow bucket and quantiles clamp to the
    exact observed min/max.
    """

    __slots__ = ("_lock", "count", "sum", "min", "max", "last", "_buckets")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.last = None
        self._buckets = [0] * (len(BUCKET_BOUNDS) + 1)

    def observe(self, v) -> None:
        v = float(v)
        idx = bisect.bisect_left(BUCKET_BOUNDS, v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            self._buckets[idx] += 1

    def _quantile_locked(self, q: float) -> Optional[float]:
        if self.count == 0:
            return None
        if q <= 0.0:
            return self.min
        if q >= 1.0:
            return self.max
        target = q * self.count
        cum = 0
        for i, c in enumerate(self._buckets):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else self.min
                hi = (
                    BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else self.max
                )
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi <= lo:
                    return lo
                return lo + (target - prev) / c * (hi - lo)
        return self.max

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (q in [0, 1]) from the bucket grid,
        linearly interpolated within the covering bucket; None when the
        histogram is empty.  Exact at the min/max endpoints."""
        with self._lock:
            return self._quantile_locked(q)

    def summary(self) -> dict:
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "mean": self.sum / self.count if self.count else None,
                "last": self.last,
                "p50": self._quantile_locked(0.5),
                "p90": self._quantile_locked(0.9),
                "p99": self._quantile_locked(0.99),
            }

    def transport(self) -> dict:
        """Raw cross-process form: exact state INCLUDING the bucket
        vector.  :meth:`summary` interpolates quantiles and cannot be
        merged; this can — serving worker processes ship it over their
        metrics pipe and the parent folds it in with
        :meth:`absorb_delta`."""
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "last": self.last,
                "buckets": list(self._buckets),
            }

    def absorb_delta(self, new: dict, prev: Optional[dict] = None) -> None:
        """Fold another process's :meth:`transport` state in as a delta
        against ``prev`` (the previous snapshot absorbed from the same
        source): count/sum/buckets add their increments, min/max merge,
        last adopts the source's latest.  The sender's state is
        cumulative, so a dropped snapshot loses nothing — the next one
        carries the missed increments."""
        prev = prev or {}
        prev_buckets = prev.get("buckets")
        with self._lock:
            self.count += new["count"] - prev.get("count", 0)
            self.sum += new["sum"] - prev.get("sum", 0.0)
            for i, c in enumerate(new["buckets"]):
                self._buckets[i] += c - (prev_buckets[i] if prev_buckets
                                         else 0)
            if new["min"] is not None:
                self.min = (new["min"] if self.min is None
                            else min(self.min, new["min"]))
            if new["max"] is not None:
                self.max = (new["max"] if self.max is None
                            else max(self.max, new["max"]))
            if new["last"] is not None:
                self.last = new["last"]


class _NullMetric:
    """Shared no-op metric: one attribute call and out."""

    __slots__ = ()

    def inc(self, n=1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass


_NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Named counters/gauges/histograms, thread-safe, JSON-snapshottable.

    Disabled registries hand back a shared no-op metric object, so an
    instrumented call site pays one branch whether telemetry is on or
    off.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _get(self, table: dict, name: str, cls):
        if not self.enabled:
            return _NULL_METRIC
        with self._lock:
            m = table.get(name)
            if m is None:
                for kind, other in (
                    ("counter", self._counters),
                    ("gauge", self._gauges),
                    ("histogram", self._histograms),
                ):
                    if other is not table and name in other:
                        raise ValueError(
                            f"metric {name!r} is already registered as a "
                            f"{kind}; one name = one kind (the Prometheus "
                            "exposition cannot represent both)"
                        )
                m = table[name] = cls(self._lock)
            return m

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def snapshot(self) -> dict:
        """JSON-able view of every metric, stable key order."""
        with self._lock:
            counters = {k: self._counters[k].value
                        for k in sorted(self._counters)}
            gauges = {k: json_safe(self._gauges[k].value)
                      for k in sorted(self._gauges)}
            hists = dict(sorted(self._histograms.items()))
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.summary() for k, h in hists.items()},
        }

    def transport_snapshot(self) -> dict:
        """Mergeable cross-process snapshot: counter/gauge values plus
        each histogram's raw :meth:`Histogram.transport` state
        (:meth:`snapshot`'s summaries interpolate quantiles and cannot
        be merged).  Serving worker processes ship this over their
        heartbeat pipe; the parent registry folds it in with
        :meth:`absorb_delta`, so /metrics, /stats, and the admission
        tiers see one pool-wide view."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: json_safe(g.value) for k, g in self._gauges.items()}
            hists = dict(self._histograms)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.transport() for k, h in hists.items()},
        }

    def absorb_delta(self, new: dict, prev: Optional[dict] = None) -> None:
        """Merge another registry's :meth:`transport_snapshot`: counters
        add the increment since ``prev`` (the previous snapshot absorbed
        from the SAME source), gauges adopt the source's latest value,
        histograms fold their bucket deltas.  Senders keep cumulative
        state, so the merge is loss-tolerant and idempotent per
        (snapshot, prev) pair."""
        if not self.enabled:
            return
        prev = prev or {}
        prev_counters = prev.get("counters", {})
        for name, value in (new.get("counters") or {}).items():
            delta = value - prev_counters.get(name, 0)
            if delta:
                self.counter(name).inc(delta)
        for name, value in (new.get("gauges") or {}).items():
            self.gauge(name).set(value)
        prev_hists = prev.get("histograms", {})
        for name, state in (new.get("histograms") or {}).items():
            self.histogram(name).absorb_delta(state, prev_hists.get(name))


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

class _NullSpan:
    """Shared no-op span for the disabled path (no allocation per call)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Adopt:
    """Context manager behind :meth:`Telemetry.adopt`: installs a remote
    :class:`TraceContext` as this thread's distributed context for the
    duration.  ``ctx=None`` degrades to a no-op enter/exit — cheap
    enough that every transport handler wraps unconditionally."""

    __slots__ = ("_hub", "_ctx", "_prev")

    def __init__(self, hub: "Telemetry", ctx):
        self._hub = hub
        self._ctx = ctx

    def __enter__(self) -> "Telemetry":
        if self._ctx is not None:
            local = self._hub._local
            self._prev = getattr(local, "remote", None)
            local.remote = self._ctx
        return self._hub

    def __exit__(self, *exc) -> bool:
        if self._ctx is not None:
            self._hub._local.remote = self._prev
        return False


class Span:
    """One wall-clock interval; emits a record to the hub's sinks on exit.

    Timestamps are monotonic (``perf_counter``) relative to the hub's
    epoch, so span math is immune to wall-clock steps; the hub's meta
    record carries the wall-clock epoch for correlation.
    """

    __slots__ = ("_hub", "name", "attrs", "span_id", "parent_id", "t0",
                 "_tid", "_remote")

    def __init__(self, hub: "Telemetry", name: str, attrs: dict):
        self._hub = hub
        self.name = name
        self.attrs = attrs
        self.span_id = None
        self.parent_id = None
        self.t0 = None
        self._tid = None
        self._remote = None

    def set(self, **attrs) -> "Span":
        """Attach attributes mid-span (solver iteration counts, sizes)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        hub = self._hub
        stack = hub._span_stack()
        # Parent: the innermost span on THIS thread, else an attached
        # cross-thread context (hub.attach) — how the prefetch pack/
        # transfer threads, the serving dispatch thread, and the tuning
        # workers nest under the span that spawned their work.
        self.parent_id = (
            stack[-1].span_id if stack
            else getattr(hub._local, "inherit", None)
        )
        self.span_id = next(hub._ids)
        self._tid = threading.get_ident()
        self._remote = getattr(hub._local, "remote", None)
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        hub = self._hub
        stack = hub._span_stack()
        # Defensive pop: a mismatched exit (caller error) must not corrupt
        # sibling spans' parents for the rest of the run.
        while stack and stack.pop() is not self:
            pass
        remote = self._remote
        tail = False
        if remote is not None and not remote.sampled \
                and exc_type is None:
            # Head-unsampled request: drop the span record UNLESS the
            # hop blew the tail-retention SLO (then keep it, tagged) —
            # the slow 1-in-N request is exactly the one worth a trace.
            # Errored spans always emit.  Metrics are sampling-blind.
            slo = hub.trace_tail_slo_s
            if slo is None or (t1 - self.t0) < slo:
                return False
            tail = True
        record = {
            "type": "span",
            "name": self.name,
            "ts": self.t0 - hub._epoch_perf,
            "dur": t1 - self.t0,
            "id": self.span_id,
            "parent": self.parent_id,
            "tid": self._tid,
        }
        if remote is not None:
            # Cross-process stitching fields: the distributed trace id,
            # this span's GLOBAL id, and — for the local root of the
            # adopted subtree — the remote parent's global id.
            record["trace"] = remote.trace_id
            record["gid"] = f"{hub._global_span_id(self.span_id):016x}"
            if self.parent_id is None and remote.span_id:
                record["rparent"] = f"{remote.span_id:016x}"
            if tail:
                record["tail"] = True
        if exc_type is not None:
            record["error"] = f"{exc_type.__name__}: {exc}"
        if self.attrs:
            record["attrs"] = {k: json_safe(v)
                               for k, v in self.attrs.items()}
        hub._emit(record)
        return False


# ---------------------------------------------------------------------------
# The hub
# ---------------------------------------------------------------------------

class Telemetry:
    """Span + event + metrics hub feeding a list of sinks.

    ``output_dir`` builds the standard sink set: ``events.jsonl``
    (JSONL, source of truth), ``trace.json`` (Chrome trace-event array),
    and — when ``logger`` is given — an end-of-run summary through it.
    ``enabled=False`` (or an empty sink list) makes every span/event a
    single-branch no-op; the metrics registry follows ``enabled``.

    Use as a context manager to install as the process-current hub
    (:func:`current`), restoring the previous one and closing sinks on
    exit::

        with Telemetry(output_dir=out, logger=logger) as tel:
            with tel.span("run", driver="glm"):
                ...
    """

    def __init__(
        self,
        output_dir: Optional[str] = None,
        sinks=None,
        logger=None,
        enabled: bool = True,
        run_name: str = "run",
    ):
        self.enabled = enabled
        self.run_name = run_name
        self.output_dir = output_dir
        self._epoch_perf = time.perf_counter()
        self._epoch_wall = time.time()
        #: process-unique trace id: spans/events carry it implicitly (one
        #: hub = one trace); the meta record publishes it so traces from
        #: several processes can be correlated after a Perfetto merge.
        self.trace_id = uuid.uuid4().hex[:16]
        #: 32-bit node tag mixed into GLOBAL span ids: two hubs (even in
        #: one process — tests run several) never collide, so a merged
        #: multi-process trace keeps its parent links unambiguous.
        self._node = int(uuid.uuid4().hex[:8], 16)
        #: head sampling: a fresh trace is sampled iff its 64-bit id is
        #: 0 mod this (deterministic — every hop agrees without talking).
        self.trace_sample_every = 256
        #: tail retention: an UNSAMPLED hop slower than this still emits
        #: its span records, tagged ``"tail": true``.  None = off.
        self.trace_tail_slo_s: Optional[float] = None
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._emit_lock = threading.Lock()
        self._closed = False
        self._restore_token = None
        self.metrics = MetricsRegistry(enabled=enabled)
        if sinks is None:
            sinks = []
            if enabled and output_dir is not None:
                from photon_ml_tpu.telemetry.recorder import FlightRecorder
                from photon_ml_tpu.telemetry.sinks import (
                    ChromeTraceSink,
                    JsonlSink,
                    LoggerSummarySink,
                )

                os.makedirs(output_dir, exist_ok=True)
                sinks.append(
                    JsonlSink(os.path.join(output_dir, "events.jsonl"))
                )
                sinks.append(
                    ChromeTraceSink(os.path.join(output_dir, "trace.json"))
                )
                # Always-on forensics ring: bounded memory, dumped only
                # on crash / watchdog-fatal / injected chaos fault.
                sinks.append(FlightRecorder())
                if logger is not None:
                    sinks.append(LoggerSummarySink(logger))
        self._sinks = list(sinks)
        if self.active:
            self._emit({
                "type": "meta",
                "name": run_name,
                "ts": 0.0,
                "wall_epoch": self._epoch_wall,
                "pid": os.getpid(),
                "trace": self.trace_id,
                "node": f"{self._node:08x}",
            })

    # -- state ---------------------------------------------------------------
    @property
    def active(self) -> bool:
        """True when events/spans actually reach a sink."""
        return self.enabled and bool(self._sinks) and not self._closed

    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- trace-context propagation -------------------------------------------
    def _global_span_id(self, local_id: int) -> int:
        """Process-transcending span id: node tag (high 32) | local id
        (low 32).  What :class:`TraceContext` carries across hops and
        span records publish as ``gid``."""
        return ((self._node & 0xFFFFFFFF) << 32) \
            | (int(local_id) & 0xFFFFFFFF)

    def configure_tracing(
        self,
        sample_every: Optional[int] = None,
        tail_slo_s: Optional[float] = None,
    ) -> "Telemetry":
        """Set the distributed-tracing knobs (docs/telemetry.md):
        ``sample_every`` — head-sample 1 in N new traces (1 = all);
        ``tail_slo_s`` — emit UNSAMPLED hops slower than this anyway,
        tagged ``tail``.  Returns self for chaining."""
        if sample_every is not None:
            sample_every = int(sample_every)
            if sample_every < 1:
                raise ValueError(
                    f"sample_every must be >= 1, got {sample_every}"
                )
            self.trace_sample_every = sample_every
        if tail_slo_s is not None:
            tail_slo_s = float(tail_slo_s)
            if tail_slo_s <= 0:
                raise ValueError(
                    f"tail_slo_s must be > 0, got {tail_slo_s}"
                )
            self.trace_tail_slo_s = tail_slo_s
        return self

    def new_trace(self, sampled: Optional[bool] = None) -> TraceContext:
        """Mint the root context for one request entering the system
        (the fleet router / service edge calls this).  The head-sampling
        verdict is decided HERE, deterministically from the trace id, so
        every downstream hop re-derives the same answer for free."""
        # os.urandom over uuid4: same 64 random bits at ~1/6 the cost —
        # this runs once per request on the serving edge.
        trace_word = int.from_bytes(os.urandom(8), "big")
        while trace_word == 0:  # 0 means "no context" on binary wires
            trace_word = int.from_bytes(os.urandom(8), "big")
        if sampled is None:
            every = self.trace_sample_every
            sampled = every <= 1 or trace_word % every == 0
        return TraceContext(f"{trace_word:016x}", 0, sampled)

    def adopt(self, ctx: Optional[TraceContext]) -> "_Adopt":
        """Adopt a remote hop's :class:`TraceContext` for spans opened
        on this thread: their records gain the distributed ``trace`` /
        ``gid`` fields, the first one parents to the remote span
        (``rparent``), and the sampling verdict applies.  None → no-op,
        so transport handlers adopt unconditionally.  (A slotted context
        manager, not contextlib — this sits on the per-request path.)"""
        return _Adopt(self, ctx if self.active else None)

    def propagation_context(self) -> Optional[TraceContext]:
        """The :class:`TraceContext` to send DOWNSTREAM from here: the
        adopted remote trace with the current span's global id as the
        parent.  None when no remote context is active — background work
        pays one branch and sends nothing."""
        if not self.active:
            return None
        remote = getattr(self._local, "remote", None)
        if remote is None:
            return None
        stack = self._span_stack()
        if stack:
            span_id = self._global_span_id(stack[-1].span_id)
        else:
            inherit = getattr(self._local, "inherit", None)
            span_id = (self._global_span_id(inherit)
                       if inherit is not None else remote.span_id)
        return TraceContext(remote.trace_id, span_id, remote.sampled)

    def current_context(self) -> Optional[tuple]:
        """``(trace_id, span_id, remote_ctx)`` of this thread's
        innermost span — the handle a caller passes to :meth:`attach` on
        another thread so work it farms out nests under the span that
        requested it (and keeps the adopted distributed context, if
        any).  None when the hub is inactive or no span is open."""
        if not self.active:
            return None
        remote = getattr(self._local, "remote", None)
        stack = self._span_stack()
        if stack:
            return (self.trace_id, stack[-1].span_id, remote)
        inherit = getattr(self._local, "inherit", None)
        if inherit is not None:
            return (self.trace_id, inherit, remote)
        if remote is not None:
            # Adopted remote with no local span open (a transport
            # handler between hops — the worker's score loop): the
            # capture still carries the distributed context, so work
            # farmed to another thread parents to the REMOTE span.
            return (self.trace_id, None, remote)
        return None

    @contextlib.contextmanager
    def attach(self, ctx: Optional[tuple]):
        """Adopt ``ctx`` (a :meth:`current_context` capture) as this
        thread's parent for spans/events opened while attached.  No-op
        for None / inactive hubs, so threads attach unconditionally at
        one-branch cost when telemetry is off.  Accepts the legacy
        2-tuple form; the 3-tuple form also restores the distributed
        remote context across the thread hop."""
        if ctx is None or not self.active:
            yield self
            return
        prev = getattr(self._local, "inherit", None)
        prev_remote = getattr(self._local, "remote", None)
        self._local.inherit = ctx[1]
        has_remote = len(ctx) > 2
        if has_remote:
            self._local.remote = ctx[2]
        try:
            yield self
        finally:
            self._local.inherit = prev
            if has_remote:
                self._local.remote = prev_remote

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager for a hierarchical wall-clock span."""
        if not self.active:
            return _NULL_SPAN
        # Head-unsampled distributed request with tail retention off:
        # nothing under this span can ever emit (every hop shares the
        # verdict), so skip the Span bookkeeping entirely — this is the
        # 255-in-256 per-request path on the serving edge.
        remote = getattr(self._local, "remote", None)
        if remote is not None and not remote.sampled \
                and self.trace_tail_slo_s is None:
            return _NULL_SPAN
        return Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        """Instant (zero-duration) event under the current span."""
        if not self.active:
            return
        stack = self._span_stack()
        record = {
            "type": "event",
            "name": name,
            "ts": time.perf_counter() - self._epoch_perf,
            "parent": (
                stack[-1].span_id if stack
                else getattr(self._local, "inherit", None)
            ),
            "tid": threading.get_ident(),
        }
        if attrs:
            record["attrs"] = {k: json_safe(v) for k, v in attrs.items()}
        self._emit(record)

    def counter(self, name: str) -> Counter:
        return self.metrics.counter(name)

    def gauge(self, name: str) -> Gauge:
        return self.metrics.gauge(name)

    def histogram(self, name: str) -> Histogram:
        return self.metrics.histogram(name)

    def _emit(self, record: dict) -> None:
        with self._emit_lock:
            for sink in self._sinks:
                try:
                    sink.emit(record)
                except Exception:
                    # Observability must never sink the job it observes.
                    pass

    # -- flight recorder -----------------------------------------------------
    @property
    def recorder(self):
        """The hub's :class:`~photon_ml_tpu.telemetry.recorder.
        FlightRecorder` sink, or None (only hubs built with an
        ``output_dir`` install one by default)."""
        from photon_ml_tpu.telemetry.recorder import FlightRecorder

        for sink in self._sinks:
            if isinstance(sink, FlightRecorder):
                return sink
        return None

    def dump_flight_recorder(
        self, reason: str, path: Optional[str] = None
    ) -> Optional[str]:
        """Write the flight-recorder ring to ``flightrecorder.json`` in
        the output dir (or ``path``); returns the path, or None when no
        recorder/destination exists.  Never raises — forensics must not
        mask the failure being recorded."""
        rec = self.recorder
        if rec is None:
            return None
        if path is None:
            if self.output_dir is None:
                return None
            path = os.path.join(self.output_dir, "flightrecorder.json")
        try:
            return rec.dump(
                path, reason=reason, wall_epoch=self._epoch_wall,
                trace=self.trace_id,
            )
        except Exception:
            return None

    # -- snapshot / shutdown -------------------------------------------------
    def snapshot(self) -> dict:
        return self.metrics.snapshot()

    def write_snapshot(self, path: Optional[str] = None) -> Optional[str]:
        """Write the metrics snapshot JSON; defaults to
        ``<output_dir>/metrics.json``.  Safe to call repeatedly (the
        drivers write once at end of run)."""
        if path is None:
            if self.output_dir is None:
                return None
            path = os.path.join(self.output_dir, "metrics.json")
        snap = self.snapshot()
        snap["wall_epoch"] = self._epoch_wall
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=2)
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        """Flush and close every sink (passing them the final metrics
        snapshot) and write ``metrics.json``.  Idempotent."""
        if self._closed:
            return
        snap = self.snapshot()
        self._closed = True
        for sink in self._sinks:
            try:
                sink.close(snap)
            except Exception:
                pass
        if self.enabled and self.output_dir is not None:
            try:
                self.write_snapshot()
            except OSError:
                pass

    # -- context manager: install as current ----------------------------------
    def __enter__(self) -> "Telemetry":
        self._restore_token = set_current(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        set_current(self._restore_token)
        self._restore_token = None
        if exc_type is not None and not self._closed:
            # Crash forensics: the last-N events leading into the
            # failure, dumped before sinks close (drivers run context-
            # managed, so every crashed run leaves flightrecorder.json).
            self.dump_flight_recorder(
                reason=f"crash: {exc_type.__name__}: {exc}"[:300]
            )
        self.close()
        return False


# ---------------------------------------------------------------------------
# Process-current hub
# ---------------------------------------------------------------------------

#: Shared disabled hub: the default target for instrumented call sites, so
#: library use without a driver costs one branch per event.
NULL = Telemetry(enabled=False, sinks=[])

_current: Telemetry = NULL
_current_lock = threading.Lock()


def current() -> Telemetry:
    """The process-current telemetry hub (a disabled no-op by default)."""
    return _current


def set_current(hub: Optional[Telemetry]) -> Telemetry:
    """Install ``hub`` (None → the disabled NULL hub) as process-current;
    returns the previous hub so callers can restore it."""
    global _current
    with _current_lock:
        prev = _current
        _current = hub if hub is not None else NULL
        return prev


def dump_flight_recorder(reason: str, path=None) -> Optional[str]:
    """Dump the process-current hub's flight recorder (see
    :meth:`Telemetry.dump_flight_recorder`).  The chaos injector and the
    watchdog's fatal path call this so every deliberate or fatal failure
    leaves its trailing event window on disk."""
    return current().dump_flight_recorder(reason, path)
