"""Fleet metrics aggregation + SLO burn-rate engine.

PR 7's ops plane observes ONE process; PR 15's router spreads a fleet
over N of them.  This module is the missing fold: a
:class:`FleetAggregator` the router (or a standalone CLI) runs that
scrapes each host's ``/snapshot``, merges every registry through the
same ``absorb_delta`` transport the worker heartbeat pipe already uses,
and serves ONE fleet-wide view:

- ``GET /metrics`` — Prometheus text with a ``host`` label dimension:
  every metric appears once per host (``{host="h0"}``) plus an
  unlabeled fleet-wide fold, so a dashboard reads either grain from one
  scrape.
- ``GET /slo`` — the burn-rate report as JSON.

**Scrape robustness** (the partition contract one tier up from the
lease client): a DOWN host degrades to its last-seen snapshot —
``fleet_scrape_failures_total`` counts, ``fleet_scrape_staleness_seconds``
ages, the loop never wedges.  The ``telemetry.scrape`` chaos seam makes
the failure FaultPlan-scriptable.

**SLO engine**: each :class:`SloPolicy` declares a latency target and an
error budget; the evaluator computes the error-budget burn rate over a
fast and a slow window (classic multi-window alerting: the fast window
catches the fire, the slow window suppresses blips) from deltas of the
aggregated counters and histogram bucket vectors.  When BOTH windows
burn past the threshold it emits an ``slo.burn`` event, increments
``slo_burn_alerts_total``, and trips a flight-recorder dump — the last-N
events leading into the burn land on disk before anyone pages.

See docs/telemetry.md "Distributed tracing + fleet aggregation".
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.telemetry.core import (
    BUCKET_BOUNDS,
    MetricsRegistry,
)
from photon_ml_tpu.telemetry.exporter import _fmt, _sanitize


# ---------------------------------------------------------------------------
# SLO policy + burn math
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """One SLO: a latency target and an error budget for one traffic
    slice (fleet-wide or per-tenant, chosen by which metric family the
    policy points at).

    A request is BAD when it errors or lands slower than ``p99_s``; the
    budget says what fraction of bad requests is acceptable; the burn
    rate is ``bad_fraction / budget`` (1.0 = burning the budget exactly
    as fast as it refills; 10x = the classic page-now threshold on a
    5m window)."""

    name: str
    latency_metric: str = "serving_request_latency_seconds"
    p99_s: Optional[float] = 0.5
    error_counter: Optional[str] = None
    error_budget: float = 0.01
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.error_budget <= 1.0):
            raise ValueError(
                f"error_budget must be in (0, 1], got {self.error_budget}"
            )
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise ValueError(
                "windows must be > 0, got "
                f"{self.fast_window_s}/{self.slow_window_s}"
            )
        if self.fast_window_s > self.slow_window_s:
            raise ValueError(
                f"fast window ({self.fast_window_s}s) must not exceed "
                f"slow window ({self.slow_window_s}s)"
            )
        if self.p99_s is None and self.error_counter is None:
            raise ValueError(
                f"policy {self.name!r} needs a latency target and/or an "
                "error counter — with neither, nothing can ever be bad"
            )


def _hist_bad_split(new: Optional[dict], old: Optional[dict],
                    p99_s: float) -> tuple[int, int]:
    """``(total, bad)`` request deltas between two histogram transports:
    bad = observations ABOVE the bucket covering ``p99_s``.  Bucket
    granularity (≤ 1.26x) bounds the misclassification band."""
    if not new:
        return 0, 0
    idx = bisect.bisect_left(BUCKET_BOUNDS, p99_s)
    new_buckets = new.get("buckets") or []
    old_buckets = (old or {}).get("buckets") or []
    total = new.get("count", 0) - (old or {}).get("count", 0)
    ok = sum(new_buckets[: idx + 1]) - sum(old_buckets[: idx + 1])
    return max(0, total), max(0, total - max(0, ok))


@dataclasses.dataclass
class _BurnState:
    alerting: bool = False
    alerts: int = 0
    last: Optional[dict] = None


# ---------------------------------------------------------------------------
# Per-host scrape state
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _HostState:
    host_id: str
    url: str
    registry: MetricsRegistry = dataclasses.field(
        default_factory=MetricsRegistry
    )
    prev: Optional[dict] = None
    last_snapshot: Optional[dict] = None
    last_success_t: Optional[float] = None
    scrapes: int = 0
    failures: int = 0
    stale: bool = False
    identity: Optional[dict] = None
    #: set when membership says the host left the fleet; the series is
    #: marked stale immediately and DROPPED after ``stale_drop_s``.
    departed_t: Optional[float] = None


def _default_fetch(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# The aggregator
# ---------------------------------------------------------------------------

class FleetAggregator:
    """Scrape N hosts' ``/snapshot`` endpoints into one fleet registry.

    ``hosts`` maps host_id -> base URL (the exporter's root; the
    aggregator appends ``/snapshot``).  ``fetch`` is injectable for
    tests: ``(url, timeout_s) -> snapshot dict``.  Drive it manually
    with :meth:`poll_once` or on a thread with :meth:`start`/``stop``.

    The host set FOLLOWS membership: :meth:`sync_membership` (fed by
    the cluster tier's ``MembershipWatcher``, or any discovery source)
    adds new hosts, re-adopts returners, and marks departed hosts —
    whose series are flagged stale immediately
    (``fleet_host_stale_count{host=...} 1``), stop being scraped, and
    are DROPPED from the exposition after ``stale_drop_s``.  A dead
    host's last-seen numbers never sum forever into the fleet totals;
    they age out on a bounded schedule an alert can ride.
    """

    def __init__(
        self,
        hosts: dict,
        policies=(),
        scrape_timeout_s: float = 5.0,
        interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        fetch: Optional[Callable[[str, float], dict]] = None,
        max_samples: int = 4096,
        stale_drop_s: float = 30.0,
    ):
        if not hosts:
            raise ValueError("FleetAggregator needs at least one host")
        self.stale_drop_s = float(stale_drop_s)
        self.policies = list(policies)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._fetch = fetch or _default_fetch
        self._lock = sanitizers.tracked(
            threading.Lock(), "telemetry.fleet_aggregator"
        )
        self._hosts = {
            str(hid): _HostState(
                host_id=str(hid), url=str(url).rstrip("/")
            )
            for hid, url in dict(hosts).items()
        }
        #: the fleet-wide fold every host's deltas land in; the
        #: aggregator's own fleet_*/slo_* meta-metrics live here too, so
        #: one /metrics scrape carries both.
        self.registry = MetricsRegistry()
        self.registry.gauge("fleet_hosts_count").set(len(self._hosts))
        #: (t_mono, fleet transport_snapshot) ring the burn evaluator
        #: differentiates; bounded so a long-lived aggregator cannot
        #: grow without bound.
        self._samples: list[tuple[float, dict]] = []
        self._max_samples = int(max_samples)
        self._burn: dict[str, _BurnState] = {
            p.name: _BurnState() for p in self.policies
        }
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._server_thread: Optional[threading.Thread] = None

    # -- membership ----------------------------------------------------------
    def sync_membership(self, hosts: dict) -> dict:
        """Converge the scraped host set onto ``hosts`` (host_id ->
        metrics base URL): new ids join, departed ids are marked (stale
        now, dropped after ``stale_drop_s``), returners are re-adopted
        in place — their series resume under the same ``host`` label.
        Returns ``{"added": [...], "departed": [...], "returned":
        [...]}``.  Safe from any thread; the watcher calls it between
        scrapes."""
        now = self._clock()
        added, departed, returned = [], [], []
        with self._lock:
            for hid, url in dict(hosts).items():
                hid = str(hid)
                hs = self._hosts.get(hid)
                if hs is None:
                    self._hosts[hid] = _HostState(
                        host_id=hid, url=str(url).rstrip("/")
                    )
                    added.append(hid)
                else:
                    hs.url = str(url).rstrip("/")
                    if hs.departed_t is not None:
                        hs.departed_t = None
                        returned.append(hid)
            for hid, hs in self._hosts.items():
                if hid not in hosts and hs.departed_t is None:
                    hs.departed_t = now
                    hs.stale = True
                    departed.append(hid)
            live = sum(
                1 for hs in self._hosts.values()
                if hs.departed_t is None
            )
            self.registry.gauge("fleet_hosts_count").set(live)
            if added or departed or returned:
                self.registry.counter(
                    "fleet_membership_changes_total"
                ).inc(len(added) + len(departed) + len(returned))
        if added or departed or returned:
            telemetry_mod.current().event(
                "fleet.membership_changed",
                added=added, departed=departed, returned=returned,
            )
        return {
            "added": added, "departed": departed, "returned": returned,
        }

    def _drop_departed_locked(self, now: float) -> None:
        # Caller holds self._lock.  A departed host's series stay
        # visible (marked stale) for stale_drop_s, then disappear from
        # the exposition entirely — bounded aging, not forever-sums.
        drop = [
            hid for hid, hs in self._hosts.items()
            if hs.departed_t is not None
            and now - hs.departed_t > self.stale_drop_s
        ]
        for hid in drop:
            del self._hosts[hid]
            self.registry.counter("fleet_hosts_dropped_total").inc()
        for hid in drop:
            telemetry_mod.current().event(
                "fleet.host_dropped",
                host=hid, stale_drop_s=self.stale_drop_s,
            )

    # -- scraping ------------------------------------------------------------
    def _scrape_host(self, hs: _HostState, now: float) -> bool:
        from photon_ml_tpu.chaos import core as chaos_mod

        hs.scrapes += 1
        try:
            # The partition seam: a fault here is this host dropping off
            # the network mid-scrape — degrade to last-seen, never wedge.
            chaos_mod.maybe_fail("telemetry.scrape", host=hs.host_id)
            snap = self._fetch(hs.url + "/snapshot", self.scrape_timeout_s)
        except Exception as exc:  # noqa: BLE001 — degrade, never die
            hs.failures += 1
            self.registry.counter("fleet_scrape_failures_total").inc()
            if not hs.stale:
                hs.stale = True
                telemetry_mod.current().event(
                    "fleet.scrape_stale", host=hs.host_id,
                    reason=str(exc)[:200],
                )
            return False
        transport = snap.get("transport")
        if not isinstance(transport, dict):
            # Pre-PR-17 host: /snapshot without mergeable state.  Fold
            # what we can (counters/gauges merge from summaries too).
            transport = {
                "counters": snap.get("counters") or {},
                "gauges": snap.get("gauges") or {},
                "histograms": {},
            }
        hs.registry.absorb_delta(transport, hs.prev)
        self.registry.absorb_delta(transport, hs.prev)
        hs.prev = transport
        hs.last_snapshot = snap
        hs.last_success_t = now
        hs.identity = snap.get("host") or {
            "host_id": hs.host_id, "pid": snap.get("pid")
        }
        if hs.stale:
            hs.stale = False
            telemetry_mod.current().event(
                "fleet.scrape_recovered", host=hs.host_id
            )
        return True

    def poll_once(self, now: Optional[float] = None) -> dict:
        """One scrape + burn-evaluation round; returns the SLO report.
        Every failure mode degrades (stale host, bad body, chaos fault)
        — the loop's only job is to keep folding what it CAN see."""
        now = self._clock() if now is None else now
        with self._lock:
            self._drop_departed_locked(now)
            for hs in list(self._hosts.values()):
                if hs.departed_t is None:
                    self._scrape_host(hs, now)
            self.registry.counter("fleet_scrapes_total").inc()
            staleness = max(
                (
                    now - hs.last_success_t
                    for hs in self._hosts.values()
                    if hs.last_success_t is not None
                    and hs.departed_t is None
                ),
                default=0.0,
            )
            self.registry.gauge("fleet_scrape_staleness_seconds").set(
                round(staleness, 6)
            )
            self._samples.append(
                (now, self.registry.transport_snapshot())
            )
            if len(self._samples) > self._max_samples:
                del self._samples[: len(self._samples)
                                  - self._max_samples]
            return self._evaluate_locked(now)

    # -- burn evaluation -----------------------------------------------------
    def _baseline(self, cutoff: float) -> Optional[dict]:
        """Newest sample at/before ``cutoff`` (else the oldest one —
        a partial window early in the run beats no signal)."""
        if not self._samples:
            return None
        base = self._samples[0][1]
        for t, snap in self._samples:
            if t > cutoff:
                break
            base = snap
        return base

    def _window_burn(
        self, policy: SloPolicy, cur: dict, now: float, window_s: float
    ) -> dict:
        base = self._baseline(now - window_s) or {}
        total, bad = 0, 0
        if policy.p99_s is not None:
            total, bad = _hist_bad_split(
                (cur.get("histograms") or {}).get(policy.latency_metric),
                (base.get("histograms") or {}).get(policy.latency_metric),
                policy.p99_s,
            )
        if policy.error_counter is not None:
            errs = (cur.get("counters") or {}).get(
                policy.error_counter, 0
            ) - (base.get("counters") or {}).get(policy.error_counter, 0)
            errs = max(0, errs)
            total += errs
            bad += errs
        ratio = bad / total if total else 0.0
        return {
            "window_s": window_s,
            "total": total,
            "bad": bad,
            "bad_ratio": round(ratio, 6),
            "burn": round(ratio / policy.error_budget, 4),
        }

    def _evaluate_locked(self, now: float) -> dict:
        cur = self._samples[-1][1] if self._samples else {}
        tel = telemetry_mod.current()
        report_policies = []
        worst_fast = 0.0
        for policy in self.policies:
            fast = self._window_burn(policy, cur, now,
                                     policy.fast_window_s)
            slow = self._window_burn(policy, cur, now,
                                     policy.slow_window_s)
            state = self._burn[policy.name]
            firing = (
                fast["total"] > 0
                and fast["burn"] >= policy.burn_threshold
                and slow["burn"] >= policy.burn_threshold
            )
            worst_fast = max(worst_fast, fast["burn"])
            if firing and not state.alerting:
                # Edge-triggered: one alert per excursion, re-armed when
                # the burn falls back under threshold.
                state.alerts += 1
                self.registry.counter("slo_burn_alerts_total").inc()
                tel.event(
                    "slo.burn",
                    policy=policy.name,
                    fast_burn=fast["burn"],
                    slow_burn=slow["burn"],
                    bad_ratio=fast["bad_ratio"],
                    budget=policy.error_budget,
                    threshold=policy.burn_threshold,
                )
                tel.dump_flight_recorder(
                    reason=f"slo.burn: {policy.name} fast={fast['burn']}"
                           f"x slow={slow['burn']}x"
                )
            state.alerting = firing
            entry = {
                "policy": policy.name,
                "latency_metric": policy.latency_metric,
                "p99_s": policy.p99_s,
                "error_budget": policy.error_budget,
                "threshold": policy.burn_threshold,
                "fast": fast,
                "slow": slow,
                "alerting": firing,
                "alerts": state.alerts,
            }
            state.last = entry
            report_policies.append(entry)
        self.registry.gauge("slo_burn_fast_ratio").set(
            round(worst_fast, 4)
        )
        return {
            "policies": report_policies,
            "hosts": self._host_report_locked(now),
        }

    def _host_report_locked(self, now: float) -> dict:
        return {
            hs.host_id: {
                "url": hs.url,
                "stale": hs.stale,
                "departed": hs.departed_t is not None,
                "staleness_s": (
                    None if hs.last_success_t is None
                    else round(now - hs.last_success_t, 6)
                ),
                "scrapes": hs.scrapes,
                "failures": hs.failures,
                "identity": hs.identity,
            }
            for hs in self._hosts.values()
        }

    # -- views ---------------------------------------------------------------
    def slo_report(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "policies": [
                    self._burn[p.name].last
                    or {"policy": p.name, "alerting": False, "alerts": 0}
                    for p in self.policies
                ],
                "hosts": self._host_report_locked(now),
            }

    def prometheus_text(self) -> str:
        """Fleet exposition: the unlabeled fleet-wide fold, then every
        metric again per host as ``name{host="hid"}``, each host also
        carrying ``fleet_host_stale_count{host=...}`` (1 = last scrape
        failed or membership departed — the series is last-seen data,
        not live)."""
        with self._lock:
            fleet = self.registry.snapshot()
            per_host = {
                hs.host_id: (hs.registry.snapshot(), hs.stale)
                for hs in self._hosts.values()
                if hs.last_success_t is not None
            }
        lines = _exposition_lines(fleet, None, emit_type=True)
        lines.append("# TYPE fleet_host_stale_count gauge")
        for hid in sorted(per_host):
            snap, stale = per_host[hid]
            lines.append(
                f'fleet_host_stale_count{{host="{hid}"}} '
                f"{1 if stale else 0}"
            )
            lines.extend(_exposition_lines(snap, hid, emit_type=False))
        return "\n".join(lines) + "\n"

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fleet-aggregator", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the fold must survive
                pass

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Mount the fleet HTTP plane (``/metrics`` ``/slo``
        ``/healthz``); returns the bound port."""
        if self._server is not None:
            return self._server.server_address[1]
        self._server = _FleetServer((host, port), _FleetHandler)
        self._server.aggregator = self
        self._server_thread = threading.Thread(
            target=self._server.serve_forever,
            name="fleet-aggregator-http", daemon=True,
        )
        self._server_thread.start()
        return self._server.server_address[1]

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)
        server, sthread = self._server, self._server_thread
        self._server, self._server_thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if sthread is not None:
            sthread.join(timeout=timeout)

    def __enter__(self) -> "FleetAggregator":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


def _exposition_lines(
    snapshot: dict, host: Optional[str], emit_type: bool
) -> list[str]:
    """Prometheus lines for one snapshot, optionally ``host``-labeled.
    (exporter.prometheus_text renders unlabeled text; the fleet view
    needs the label merged INSIDE existing quantile braces, so this is
    its own renderer rather than a post-hoc string patch.)"""

    def _labels(extra: Optional[str] = None) -> str:
        parts = []
        if extra:
            parts.append(extra)
        if host is not None:
            parts.append(f'host="{host}"')
        return "{" + ",".join(parts) + "}" if parts else ""

    lines: list[str] = []
    for name in sorted(snapshot.get("counters") or {}):
        value = snapshot["counters"][name]
        if not isinstance(value, (int, float)):
            continue
        safe = _sanitize(name)
        if emit_type:
            lines.append(f"# TYPE {safe} counter")
        lines.append(f"{safe}{_labels()} {_fmt(value)}")
    for name in sorted(snapshot.get("gauges") or {}):
        value = snapshot["gauges"][name]
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        safe = _sanitize(name)
        if emit_type:
            lines.append(f"# TYPE {safe} gauge")
        lines.append(f"{safe}{_labels()} {_fmt(value)}")
    for name in sorted(snapshot.get("histograms") or {}):
        h = snapshot["histograms"][name]
        if not h.get("count"):
            continue
        safe = _sanitize(name)
        if emit_type:
            lines.append(f"# TYPE {safe} summary")
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            v = h.get(key)
            if v is not None:
                qlabel = 'quantile="%s"' % q
                lines.append(f"{safe}{_labels(qlabel)} {_fmt(v)}")
        lines.append(f"{safe}_sum{_labels()} {_fmt(h['sum'])}")
        lines.append(f"{safe}_count{_labels()} {h['count']}")
    return lines


class _FleetServer(ThreadingHTTPServer):
    daemon_threads = True
    aggregator: "FleetAggregator"


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass

    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        agg = self.server.aggregator
        if self.path == "/metrics":
            self._send(
                200, agg.prometheus_text().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif self.path == "/slo":
            self._send(
                200, json.dumps(agg.slo_report()).encode(),
                "application/json",
            )
        elif self.path in ("/healthz", "/livez"):
            self._send(
                200, json.dumps({"status": "ok"}).encode(),
                "application/json",
            )
        else:
            self._send(
                404,
                json.dumps({"error": f"no route {self.path}"}).encode(),
                "application/json",
            )
