"""Runtime sanitizers: lock-order witness tracking + thread-leak sentinel.

The static rules (rules_concurrency.py) police what the AST can see;
these sanitizers police what only execution can see — the ORDER locks
are actually taken in, and the threads actually left behind.

**Lock-order sanitizer** (the lockdep idea, witness-style): every
tracked lock carries a witness NAME (a class of locks, not an
instance — ``"serving.batcher"`` covers every MicroBatcher's lock).
While a sanitizer is installed, each acquisition records edges
``held_witness -> acquired_witness`` into a global acquisition-order
graph; an acquisition that would close a cycle (thread 1 takes A then
B, thread 2 takes B then A — even at different times, even without an
actual deadlock occurring) is reported as a potential deadlock via the
telemetry flight recorder, and raised when ``strict=True``.  This turns
a deadlock from a 1-in-1000 CI hang into a deterministic report the
first time the inverted order RUNS, on any thread, under no contention.

**Thread-leak sentinel**: snapshots live threads on entry and reports
any new thread still alive at exit (after a grace poll) — the runtime
counterpart of the ``thread-lifecycle`` static rule, catching leaks
from code paths the AST cannot prove (wedged daemons, leaked pool
workers).

**Process-leak sentinel**: the same contract one isolation level up —
any child process spawned inside the scope (serving worker processes)
must be gone at exit.  A leaked process is worse than a leaked thread:
it pins shared-memory model segments and sockets, and survives the
parent interpreter.  Runtime counterpart of ``process-lifecycle``.

Cost contract (mirrors chaos/core.py): with no sanitizer installed,
``tracked()`` returns the RAW lock — zero added cost on the hot path,
cheaper than chaos's one-branch contract.  Locks created WHILE a
sanitizer is installed pay one module-global read + branch per
acquire/release plus the witness bookkeeping; ``bench.py``'s
``BENCH_ONLY=analysis`` section gates the enabled cost at ≤ 1% of a
streamed pass.  Consequence of the construction-time choice: install
the sanitizer BEFORE building the objects under test (the tests and
selfcheck do).
"""

from __future__ import annotations

import multiprocessing
import threading
import time
from typing import Iterable, Optional

from photon_ml_tpu import telemetry as telemetry_mod


class LockOrderViolation(RuntimeError):
    """Raised (strict mode) when an acquisition closes a cycle in the
    lock acquisition-order graph — a potential deadlock."""


class ThreadLeakError(RuntimeError):
    """Raised (strict mode) when threads created inside a sentinel
    scope are still alive at scope exit."""


class ProcessLeakError(RuntimeError):
    """Raised (strict mode) when child processes spawned inside a
    sentinel scope are still alive at scope exit."""


class LockOrderSanitizer:
    """Witness-based acquisition-order tracker.  Install with
    :meth:`install`/:meth:`uninstall` or as a context manager; only one
    sanitizer may be installed at a time (two would each see half the
    ordering history)."""

    def __init__(self, strict: bool = False):
        self.strict = strict
        #: confirmed orderings: witness -> witnesses acquired while it
        #: was held, with the first site that witnessed each edge.
        self._edges: dict[str, set[str]] = {}
        self._edge_threads: dict[tuple[str, str], str] = {}
        self._graph_lock = threading.Lock()
        self._tls = threading.local()
        #: potential-deadlock reports, in detection order (deduped per
        #: witness pair so a hot loop reports once, not per iteration).
        self.reports: list[dict] = []
        self._reported: set[tuple[str, str]] = set()

    # -- installation (FaultPlan's shape) -----------------------------------
    def install(self) -> "LockOrderSanitizer":
        global _SANITIZER
        with _INSTALL_LOCK:
            if _SANITIZER is not None and _SANITIZER is not self:
                raise RuntimeError(
                    "another LockOrderSanitizer is already installed; "
                    "uninstall it first"
                )
            _SANITIZER = self
        return self

    def uninstall(self) -> None:
        global _SANITIZER
        with _INSTALL_LOCK:
            if _SANITIZER is self:
                _SANITIZER = None

    def __enter__(self) -> "LockOrderSanitizer":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- the hot path --------------------------------------------------------
    def _stack(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquire(self, witness: str) -> None:
        """Record intent to acquire ``witness`` with the current
        thread's held set.  Called BEFORE the real acquire — a blocked
        acquire must still have recorded the ordering that blocked it."""
        stack = self._stack()
        if stack:
            cycle = None
            with self._graph_lock:
                for held in stack:
                    if held == witness:
                        continue  # same-witness nesting: distinct
                        # instances sharing a class; legal here (the
                        # graph tracks classes, instances may nest)
                    path = self._path(witness, held)
                    if path is not None:
                        cycle = path + [witness]
                        break
                    self._edges.setdefault(held, set()).add(witness)
                    self._edge_threads.setdefault(
                        (held, witness), threading.current_thread().name
                    )
            if cycle is not None:
                self._report(witness, stack, cycle)
        stack.append(witness)

    def note_release(self, witness: str) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack:
            # remove the most recent occurrence (locks release LIFO in
            # with-blocks, but tolerate hand-over-hand patterns)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == witness:
                    del stack[i]
                    break

    def _path(self, src: str, dst: str) -> Optional[list[str]]:
        """Existing-edge path src -> ... -> dst, else None (DFS over a
        graph of a handful of witnesses; runs under _graph_lock)."""
        seen = {src}
        order = [(src, [src])]
        while order:
            node, path = order.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    order.append((nxt, path + [nxt]))
        return None

    def _report(
        self, witness: str, held: list[str], cycle: list[str]
    ) -> None:
        key = (cycle[0], cycle[-2] if len(cycle) > 1 else cycle[0])
        with self._graph_lock:
            if key in self._reported:
                return
            self._reported.add(key)
            first_thread = self._edge_threads.get(
                (cycle[0], cycle[1]) if len(cycle) > 1 else key, "?"
            )
            report = {
                "kind": "lock-order-inversion",
                "acquiring": witness,
                "held": list(held),
                "cycle": cycle,
                "thread": threading.current_thread().name,
                "first_seen_thread": first_thread,
            }
            self.reports.append(report)
        tel = telemetry_mod.current()
        tel.counter("analysis_lock_order_reports_total").inc()
        tel.event("analysis.lock_order_inversion", **report)
        # Same forensics contract as a chaos fault: the flight-recorder
        # ring is dumped ENDING at the inversion event, so the report
        # arrives with the event window that led to it.
        telemetry_mod.dump_flight_recorder(
            reason=f"lockorder:{'->'.join(cycle)}"
        )
        if self.strict:
            raise LockOrderViolation(
                f"lock acquisition order inversion: acquiring "
                f"{witness!r} while holding {held!r} closes the cycle "
                f"{' -> '.join(cycle)} (first seen on thread "
                f"{first_thread!r}); two threads taking these in "
                "opposite orders can deadlock"
            )


_INSTALL_LOCK = threading.Lock()
_SANITIZER: Optional[LockOrderSanitizer] = None


def current_sanitizer() -> Optional[LockOrderSanitizer]:
    return _SANITIZER


class TrackedLock:
    """A lock proxy that reports acquisition order to the installed
    sanitizer.  Disabled path (sanitizer uninstalled after creation):
    one module-global read + branch per operation, the chaos
    ``maybe_fail`` contract."""

    __slots__ = ("_lock", "witness")

    def __init__(self, lock, witness: str):
        self._lock = lock
        self.witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = _SANITIZER
        if s is not None:
            s.note_acquire(self.witness)
        ok = self._lock.acquire(blocking, timeout)
        if not ok and s is not None:
            s.note_release(self.witness)  # failed try-acquire: unwind
        return ok

    def release(self) -> None:
        self._lock.release()
        s = _SANITIZER
        if s is not None:
            s.note_release(self.witness)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def tracked(lock, witness: str):
    """Wrap ``lock`` for lock-order tracking under witness class
    ``witness`` — or return it untouched when no sanitizer is installed
    (zero overhead; the construction-time decision the module docstring
    documents).  Subsystems wire their locks through this at creation:

        self._lock = sanitizers.tracked(threading.Lock(), "serving.batcher")
    """
    if _SANITIZER is None:
        return lock
    return TrackedLock(lock, witness)


# ---------------------------------------------------------------------------
# Thread-leak sentinel
# ---------------------------------------------------------------------------

class ThreadLeakSentinel:
    """Context manager: any thread created inside the scope must be
    gone by exit (after a ``grace_s`` poll — healthy daemon threads
    finish in microseconds once their work is consumed).

    ``allow`` lists thread-name prefixes that may legitimately outlive
    the scope (e.g. a process-lifetime exporter).  ``leaked`` holds the
    offending thread names after exit; ``strict=True`` raises
    :class:`ThreadLeakError` instead (unless the body is already
    unwinding an exception — the original error keeps priority, the
    leak is still counted, prefetch's join-timeout discipline)."""

    def __init__(
        self,
        grace_s: float = 2.0,
        allow: Iterable[str] = (),
        strict: bool = False,
    ):
        self.grace_s = grace_s
        self.allow = tuple(allow)
        self.strict = strict
        self.leaked: list[str] = []
        self._before: set[int] = set()

    def __enter__(self) -> "ThreadLeakSentinel":
        self._before = {
            t.ident for t in threading.enumerate() if t.ident is not None
        }
        return self

    def _new_alive(self) -> list[threading.Thread]:
        return [
            t for t in threading.enumerate()
            if t.ident is not None
            and t.ident not in self._before
            and t.is_alive()
            and not t.name.startswith(self.allow)
        ]

    def __exit__(self, exc_type, exc, tb) -> bool:
        deadline = time.monotonic() + self.grace_s
        alive = self._new_alive()
        while alive and time.monotonic() < deadline:
            time.sleep(0.01)
            alive = self._new_alive()
        if alive:
            self.leaked = sorted(t.name for t in alive)
            tel = telemetry_mod.current()
            tel.counter("analysis_thread_leak_total").inc(len(alive))
            tel.event("analysis.thread_leak", threads=self.leaked)
            telemetry_mod.dump_flight_recorder(
                reason=f"threadleak:{','.join(self.leaked)}"
            )
            if self.strict and exc_type is None:
                raise ThreadLeakError(
                    f"thread(s) {self.leaked} created inside the "
                    f"sentinel scope are still alive {self.grace_s}s "
                    "after exit: a background thread leaked past its "
                    "owner's lifecycle"
                )
        return False


class ProcessLeakSentinel:
    """Context manager: any CHILD PROCESS spawned inside the scope must
    be gone by exit — the runtime counterpart of the
    ``process-lifecycle`` static rule, and the serving worker pool's
    shutdown acceptance gate (a leaked worker pins its shared-memory
    mapping and a socket, not just a thread stack).

    Mirrors :class:`ThreadLeakSentinel`: ``allow`` lists process-name
    prefixes that may outlive the scope, ``leaked`` holds offending
    process names after exit, ``strict=True`` raises
    :class:`ProcessLeakError` unless the body is already unwinding an
    exception.  The grace default is longer than the thread sentinel's —
    a worker draining its batcher is finishing real scoring work.
    Polling uses ``multiprocessing.active_children()``, which also reaps
    finished children, so a passed scope leaves no zombies either."""

    def __init__(
        self,
        grace_s: float = 10.0,
        allow: Iterable[str] = (),
        strict: bool = False,
    ):
        self.grace_s = grace_s
        self.allow = tuple(allow)
        self.strict = strict
        self.leaked: list[str] = []
        self._before: set[Optional[int]] = set()

    def __enter__(self) -> "ProcessLeakSentinel":
        self._before = {
            p.pid for p in multiprocessing.active_children()
        }
        return self

    def _new_alive(self) -> list:
        return [
            p for p in multiprocessing.active_children()
            if p.pid not in self._before
            and p.is_alive()
            and not p.name.startswith(self.allow)
        ]

    def __exit__(self, exc_type, exc, tb) -> bool:
        deadline = time.monotonic() + self.grace_s
        alive = self._new_alive()
        while alive and time.monotonic() < deadline:
            time.sleep(0.05)
            alive = self._new_alive()
        if alive:
            self.leaked = sorted(
                f"{p.name}(pid={p.pid})" for p in alive
            )
            tel = telemetry_mod.current()
            tel.counter("analysis_process_leak_total").inc(len(alive))
            tel.event("analysis.process_leak", processes=self.leaked)
            telemetry_mod.dump_flight_recorder(
                reason=f"processleak:{','.join(self.leaked)}"
            )
            if self.strict and exc_type is None:
                raise ProcessLeakError(
                    f"child process(es) {self.leaked} spawned inside "
                    f"the sentinel scope are still alive {self.grace_s}s "
                    "after exit: a worker leaked past its owner's "
                    "lifecycle (and pins its shared-memory mappings)"
                )
        return False
