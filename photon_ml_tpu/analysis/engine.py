"""AST-based rule engine for the project-wide invariant checker.

The codebase guarantees invariants no generic linter knows about —
bit-for-bit resume, donated-buffer safety, thread seams that must never
leak, fault sites and metric names that must stay in sync with their
registries.  ``flake8`` cannot police "every ``threading.Thread`` is
daemon or provably joined" or "no buffer is read after ``donate_argnums``
handed it to XLA"; this engine can, because the rules are written
against THIS repo's idioms (see rules_concurrency.py / rules_jax.py /
rules_registry.py).

Mechanics (all stdlib, no new deps):

- Every ``.py`` file under the scanned roots is parsed ONCE into an
  :class:`PyFile` (source lines + ``ast`` tree + a parent map rules can
  share); rules walk those trees and emit :class:`Finding`\\ s with
  ``file:line`` positions and stable messages.
- **Suppressions**: a ``# photon: disable=rule-a,rule-b`` comment on the
  flagged line (or on a comment-only line directly above it) silences
  those rules for that line — ``disable=all`` silences everything.
  Suppressions are deliberate, reviewable, and local; prefer them over
  baseline entries for new code.
- **Baseline**: grandfathered findings live in a committed JSON file
  (``analysis/baseline.json``) keyed by ``(rule, path, message)`` — NOT
  by line number, so unrelated edits above a finding do not invalidate
  the baseline.  ``--check`` fails only on findings outside the
  baseline; ``--update-baseline`` rewrites it (preserving per-entry
  ``justification`` strings, which every committed entry must carry).
  Stale entries (matching nothing) are reported so the list burns down
  instead of fossilizing.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Optional

#: Comment grammar: ``# photon: disable=rule-a,rule-b`` (or ``=all``).
_SUPPRESS_RE = re.compile(r"#\s*photon:\s*disable=([a-z0-9_,\-]+|all)")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source position.

    ``message`` must be STABLE for a given defect (no line numbers, no
    volatile paths inside it): the baseline matches on
    ``(rule, path, message)`` so the entry survives line drift.
    """

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.message}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    """One named invariant: ``fn(tree) -> Iterable[Finding]``.

    ``summary`` is the one-liner ``--list-rules`` prints; ``explain`` is
    the full story ``--explain RULE`` prints — what the rule checks, why
    the invariant matters in THIS codebase, and what a fix looks like.
    """

    id: str
    family: str  # "concurrency" | "jax" | "registry"
    summary: str
    explain: str
    fn: Callable[["SourceTree"], Iterable[Finding]]

    def run(self, tree: "SourceTree") -> list[Finding]:
        return list(self.fn(tree))


class PyFile:
    """One parsed source file: lines, AST, parent links, suppressions."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as exc:  # surfaced as a finding by run_rules
            self.parse_error = exc
        self._parents: Optional[dict[ast.AST, ast.AST]] = None
        self._suppress: Optional[dict[int, set[str]]] = None

    # -- shared AST services -------------------------------------------------
    @property
    def parents(self) -> dict[ast.AST, ast.AST]:
        """child node -> parent node, built lazily once per file."""
        if self._parents is None:
            parents: dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def parent_chain(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.FunctionDef]:
        for anc in self.parent_chain(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # -- suppressions --------------------------------------------------------
    @property
    def suppressions(self) -> dict[int, set[str]]:
        """line number (1-based) -> rule ids disabled on that line."""
        if self._suppress is None:
            sup: dict[int, set[str]] = {}
            for i, line in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                rules = set(m.group(1).split(","))
                sup.setdefault(i, set()).update(rules)
                # A comment-only suppression line covers the next line
                # (for statements too long to carry an inline comment).
                if _COMMENT_ONLY_RE.match(line):
                    sup.setdefault(i + 1, set()).update(rules)
            self._suppress = sup
        return self._suppress

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class SourceTree:
    """All scanned files plus the repo-root used for relative paths."""

    def __init__(self, roots=None, repo_root: Optional[str] = None):
        if repo_root is None:
            repo_root = default_repo_root()
        if roots is None:
            roots = default_roots(repo_root)
        self.repo_root = os.path.abspath(repo_root)
        self.files: list[PyFile] = []
        seen: set[str] = set()
        for root in roots:
            for path in sorted(_py_files(root)):
                apath = os.path.abspath(path)
                if apath in seen:
                    continue
                seen.add(apath)
                rel = os.path.relpath(apath, self.repo_root)
                with open(apath, encoding="utf-8") as f:
                    text = f.read()
                self.files.append(PyFile(apath, rel, text))

    def file(self, relpath_suffix: str) -> Optional[PyFile]:
        for f in self.files:
            if f.relpath.endswith(relpath_suffix):
                return f
        return None


def _py_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def default_repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def default_roots(repo_root: Optional[str] = None) -> list[str]:
    """What ``--check`` scans by default: the package + bench.py (the
    same surface the metric-name lint always covered).  Tests are NOT
    scanned — they exist to poke invariants, including violating them
    on purpose in fixtures."""
    if repo_root is None:
        repo_root = default_repo_root()
    return [
        os.path.join(repo_root, "photon_ml_tpu"),
        os.path.join(repo_root, "bench.py"),
    ]


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


class Baseline:
    """Committed grandfathered findings, each with a justification."""

    def __init__(self, entries: list[dict]):
        self.entries = entries
        self._keys = {
            f"{e['rule']}::{e['path']}::{e['message']}" for e in entries
        }

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if path is None or not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("entries", [])
        for e in entries:
            missing = {"rule", "path", "message"} - set(e)
            if missing:
                raise ValueError(
                    f"baseline entry missing fields {sorted(missing)}: {e}"
                )
            just = str(e.get("justification", "")).strip()
            if not just or just.startswith("TODO"):
                raise ValueError(
                    "every baseline entry must carry a one-line "
                    "justification (not a TODO placeholder); "
                    f"missing on {e['rule']}::{e['path']}"
                )
        return cls(entries)

    def contains(self, finding: Finding) -> bool:
        return finding.key in self._keys

    def stale(self, findings: Iterable[Finding]) -> list[dict]:
        live = {f.key for f in findings}
        return [
            e for e in self.entries
            if f"{e['rule']}::{e['path']}::{e['message']}" not in live
        ]

    @staticmethod
    def write(path: str, findings: Iterable[Finding],
              old: "Baseline") -> None:
        """Rewrite the baseline from current findings, carrying forward
        existing justifications; new entries get a TODO placeholder the
        loader will refuse until a human fills it in."""
        just = {
            f"{e['rule']}::{e['path']}::{e['message']}":
                e.get("justification", "")
            for e in old.entries
        }
        entries = [
            {
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
                "justification": just.get(
                    f.key, "TODO: justify or fix this finding"
                ),
            }
            for f in sorted(
                findings, key=lambda f: (f.rule, f.path, f.message)
            )
        ]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"entries": entries}, f, indent=2, sort_keys=False)
            f.write("\n")


# ---------------------------------------------------------------------------
# Check driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CheckReport:
    findings: list[Finding]  # actionable: not suppressed, not baselined
    suppressed: int
    baselined: int
    stale_baseline: list[dict]
    parse_errors: list[str]
    files: int
    rules: int

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors


def run_rules(tree: SourceTree, rules: Iterable[Rule]) -> list[Finding]:
    """All raw findings (before suppression/baseline filtering)."""
    findings: list[Finding] = []
    for rule in rules:
        findings.extend(rule.run(tree))
    return findings


def run_check(
    rules: Iterable[Rule],
    roots=None,
    repo_root: Optional[str] = None,
    baseline_path: Optional[str] = None,
) -> CheckReport:
    rules = list(rules)
    tree = SourceTree(roots=roots, repo_root=repo_root)
    baseline = Baseline.load(
        default_baseline_path() if baseline_path is None else baseline_path
    )
    raw = run_rules(tree, rules)
    by_rel = {f.relpath: f for f in tree.files}
    actionable: list[Finding] = []
    suppressed = baselined = 0
    for f in raw:
        pf = by_rel.get(f.path)
        if pf is not None and pf.is_suppressed(f.rule, f.line):
            suppressed += 1
        elif baseline.contains(f):
            baselined += 1
        else:
            actionable.append(f)
    parse_errors = [
        f"{pf.relpath}:{pf.parse_error.lineno}: syntax error: "
        f"{pf.parse_error.msg}"
        for pf in tree.files if pf.parse_error is not None
    ]
    actionable.sort(key=lambda f: (f.path, f.line, f.rule))
    return CheckReport(
        findings=actionable,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=baseline.stale(raw),
        parse_errors=parse_errors,
        files=len(tree.files),
        rules=len(rules),
    )


# -- small AST helpers shared by the rule modules ---------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None
