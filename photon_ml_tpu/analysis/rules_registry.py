"""Cross-registry rules: chaos sites and metric names stay in sync.

Two registries in this repo are load-bearing conventions:

- ``chaos/core.py``'s :data:`KNOWN_SITES` — every name a ``FaultPlan``
  may target.  A registered site with no ``maybe_fail`` call-site means
  chaos tests "pass" without ever killing anything; a call-site with an
  unregistered name can never be scripted (``FaultSpec`` refuses it),
  so the seam is silently untestable.  ``chaos-site-sync`` checks both
  directions against the live source.
- The metric-name convention ``<subsystem>_<name>_<unit>`` with one
  kind per name (PR 7's ``telemetry/lint.py``), migrated here as the
  ``metric-naming`` rule.  ``python -m photon_ml_tpu.telemetry
  --lint-metrics`` remains a thin alias over this module so existing
  check.sh invocations keep working.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from photon_ml_tpu.analysis.engine import (
    Finding,
    Rule,
    SourceTree,
    const_str,
    dotted_name,
)

# ---------------------------------------------------------------------------
# metric-naming (migrated from telemetry/lint.py, PR 7)
# ---------------------------------------------------------------------------

#: First name token: which subsystem emits the metric.
SUBSYSTEMS = frozenset({
    "h2d", "hbm", "prefetch", "stream", "streaming", "staging",
    "solver", "solvers", "cd", "grid", "game", "glm", "watchdog", "checkpoint",
    "chaos", "serving", "tuning", "compile", "run", "telemetry",
    "evaluation", "model", "analysis", "freshness", "fleet", "slo",
    "cluster",
})

#: Last name token: what the value measures.
UNITS = frozenset({
    "total", "seconds", "bytes", "ratio", "gbps", "rows", "ms",
    "count", "entries", "iterations", "retries", "depth", "version",
    "tier", "rps", "residual",
})

#: Pre-convention names (PRs 1-6), grandfathered verbatim.  Do NOT add
#: to this list — rename or conform instead; each entry is a pending
#: rename chore.
LEGACY_NAMES = frozenset({
    "chaos_faults_injected",
    "checkpoint_corruptions",
    "checkpoint_fallbacks",
    "checkpoint_restores",
    "checkpoint_saves",
    "compile_cache_warmup_compiles",
    "consumer_stall_seconds",
    "consumer_stalls",
    "producer_stall_seconds",
    "producer_stalls",
    "prefetch_max_live",
    "prefetch_passes",
    "prefetch_thread_leak",
    "scored_rows",
    "serving_batch_occupancy",
    "serving_degraded",
    "tuning_best_metric",
    "tuning_trials_completed",
    "tuning_trials_failed",
    "tuning_trials_pruned",
    "tuning_trials_started",
})

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")
_CALL_RE = re.compile(r"\.(counter|gauge|histogram)\(\s*\"([a-z0-9_]+)\"")

#: Files whose metric-name string literals are convention DATA, not
#: registrations (this module and its pre-migration shim).
_LINT_EXEMPT_SUFFIXES = (
    "photon_ml_tpu/analysis/rules_registry.py",
    "photon_ml_tpu/telemetry/lint.py",
)


def lint_name(name: str, kind: Optional[str] = None) -> list[str]:
    """Issues with one metric name (empty list = conforming)."""
    if name in LEGACY_NAMES:
        return []
    issues = []
    if not _NAME_RE.match(name):
        issues.append(
            f"{name!r}: not lowercase snake_case with >= 2 tokens"
        )
        return issues
    tokens = name.split("_")
    if tokens[0] not in SUBSYSTEMS:
        issues.append(
            f"{name!r}: unknown subsystem prefix {tokens[0]!r} "
            f"(known: {sorted(SUBSYSTEMS)})"
        )
    if tokens[-1] not in UNITS:
        issues.append(
            f"{name!r}: unknown unit suffix {tokens[-1]!r} "
            f"(known: {sorted(UNITS)})"
        )
    return issues


def scan_tree(tree: SourceTree) -> list[tuple[str, str, str, int]]:
    """String-literal metric registrations: ``(name, kind, relpath,
    lineno)``.  Dynamically-built names (f-strings) are invisible here —
    the runtime kind check in MetricsRegistry still covers them."""
    hits: list[tuple[str, str, str, int]] = []
    for pf in tree.files:
        if pf.relpath.replace("\\", "/").endswith(_LINT_EXEMPT_SUFFIXES):
            continue
        for lineno, line in enumerate(pf.lines, 1):
            for m in _CALL_RE.finditer(line):
                hits.append((m.group(2), m.group(1), pf.relpath, lineno))
    return hits


def _check_metric_naming(tree: SourceTree) -> Iterable[Finding]:
    hits = scan_tree(tree)
    kinds: dict[str, dict[str, tuple[str, int]]] = {}
    for name, kind, path, lineno in hits:
        kinds.setdefault(name, {}).setdefault(kind, (path, lineno))
    for name in sorted(kinds):
        by_kind = kinds[name]
        if len(by_kind) > 1:
            sites = ", ".join(
                f"{kind} at {path}:{lineno}"
                for kind, (path, lineno) in sorted(by_kind.items())
            )
            path, lineno = next(iter(sorted(by_kind.values())))
            yield Finding(
                "metric-naming", path, lineno,
                f"{name!r} registered as multiple kinds: {sites}",
            )
        kind = next(iter(by_kind))
        path, lineno = by_kind[kind]
        for issue in lint_name(name, kind):
            yield Finding("metric-naming", path, lineno, issue)


def lint_source(roots=None) -> tuple[int, list[str]]:
    """Compatibility surface for ``python -m photon_ml_tpu.telemetry
    --lint-metrics``: ``(n_names, problems)`` over the default scan
    roots (or explicit ``roots`` for tests)."""
    tree = SourceTree(roots=roots)
    hits = scan_tree(tree)
    problems = [
        f"{f.message} (first seen {f.path}:{f.line})"
        for f in _check_metric_naming(tree)
    ]
    return len({h[0] for h in hits}), problems


# ---------------------------------------------------------------------------
# chaos-site-sync
# ---------------------------------------------------------------------------

_CHAOS_CORE_SUFFIX = "photon_ml_tpu/chaos/core.py"


def _registry_sites(tree: SourceTree) -> dict[str, tuple[str, int]]:
    """KNOWN_SITES keys parsed from chaos/core.py's AST (no import —
    the checker must not execute the package it checks)."""
    pf = tree.file(_CHAOS_CORE_SUFFIX)
    if pf is None or pf.tree is None:
        return {}
    for node in ast.walk(pf.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "KNOWN_SITES"
            and isinstance(node.value, ast.Dict)
        ):
            out = {}
            for k in node.value.keys:
                s = const_str(k)
                if s is not None:
                    out[s] = (pf.relpath, k.lineno)
            return out
    return {}


def _maybe_fail_sites(tree: SourceTree) -> list[tuple[str, str, int]]:
    """Every ``maybe_fail("<literal>", ...)`` call outside chaos/:
    ``(site, relpath, lineno)``.  Non-literal site arguments are
    invisible — none exist today, and a dynamic site name would also
    defeat the registry's typo protection, so keep them literal."""
    out: list[tuple[str, str, int]] = []
    for pf in tree.files:
        if "/chaos/" in "/" + pf.relpath.replace("\\", "/"):
            continue
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name.rsplit(".", 1)[-1] != "maybe_fail":
                continue
            if not node.args:
                continue
            site = const_str(node.args[0])
            if site is not None:
                out.append((site, pf.relpath, node.lineno))
    return out


def _check_chaos_site_sync(tree: SourceTree) -> Iterable[Finding]:
    registry = _registry_sites(tree)
    if not registry:
        return  # tree without chaos/core.py (rule fixtures): nothing on
    calls = _maybe_fail_sites(tree)
    called = {site for site, _, _ in calls}
    for site, (path, lineno) in sorted(registry.items()):
        if site not in called:
            yield Finding(
                "chaos-site-sync", path, lineno,
                f"chaos site {site!r} is registered in KNOWN_SITES but "
                "has no maybe_fail call-site: fault plans targeting it "
                "never fire and its recovery path is untested — wire "
                "the seam or retire the registry entry",
            )
    for site, path, lineno in calls:
        if site not in registry:
            yield Finding(
                "chaos-site-sync", path, lineno,
                f"maybe_fail site {site!r} is not in chaos/core.py "
                "KNOWN_SITES: no FaultPlan can ever target it "
                "(FaultSpec refuses unknown sites), so the seam is "
                "silently untestable — register it with a description",
            )


# ---------------------------------------------------------------------------
# chaos-site-tested
# ---------------------------------------------------------------------------


def _test_texts(tree: SourceTree) -> list[tuple[str, str]]:
    """``(relpath, text)`` for every ``tests/**.py`` under the repo
    root.  Tests are deliberately NOT in ``tree.files`` (they violate
    invariants on purpose in fixtures), so this rule reads them
    directly — as text, not AST: a site name counts as referenced
    however the test spells it (FaultSpec argument, plan literal,
    parametrize id)."""
    out: list[tuple[str, str]] = []
    tests_root = os.path.join(tree.repo_root, "tests")
    if not os.path.isdir(tests_root):
        return out
    for dirpath, dirnames, filenames in os.walk(tests_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as f:
                out.append((os.path.relpath(path, tree.repo_root),
                            f.read()))
    return out


def _check_chaos_site_tested(tree: SourceTree) -> Iterable[Finding]:
    registry = _registry_sites(tree)
    if not registry:
        return  # tree without chaos/core.py (rule fixtures): nothing on
    tests = _test_texts(tree)
    if not tests:
        return  # no tests/ dir alongside this tree: nothing to check
    for site, (path, lineno) in sorted(registry.items()):
        quoted = (f'"{site}"', f"'{site}'")
        if any(q in text for _, text in tests for q in quoted):
            continue
        yield Finding(
            "chaos-site-tested", path, lineno,
            f"chaos site {site!r} is registered in KNOWN_SITES but no "
            "test file references it: the recovery path behind the "
            "seam is never exercised under injected faults — add a "
            "test that scripts a FaultPlan (or flips the scripted "
            "flag) at this site, or retire the registry entry",
        )


RULES = [
    Rule(
        id="chaos-site-sync",
        family="registry",
        summary="chaos KNOWN_SITES and maybe_fail call-sites cover each "
                "other exactly",
        explain=(
            "The fault-site registry (chaos/core.py KNOWN_SITES) and "
            "the instrumented seams must stay in lockstep in BOTH "
            "directions.  A registered site with no call-site is a "
            "recovery path that silently stopped being exercised (a "
            "refactor moved the seam and dropped the hook); a "
            "maybe_fail with an unregistered name can never fire from a "
            "plan because FaultSpec validates sites at construction.  "
            "The rule parses KNOWN_SITES from the AST (never importing "
            "the package under check) and cross-references every "
            "maybe_fail string literal outside chaos/ itself.  "
            "Fix: add the KNOWN_SITES entry (with the what-a-fault-"
            "here-simulates description docs/robustness.md renders) or "
            "wire/remove the call-site."
        ),
        fn=_check_chaos_site_sync,
    ),
    Rule(
        id="chaos-site-tested",
        family="registry",
        summary="every chaos KNOWN_SITES entry is referenced by at "
                "least one test file",
        explain=(
            "chaos-site-sync guarantees a registered site has a "
            "maybe_fail call-site, but a seam nobody scripts a fault "
            "at is still an untested recovery path — the hook fires in "
            "production shapes while every test runs the happy path.  "
            "This rule reads tests/**.py directly (tests are excluded "
            "from the scanned tree on purpose) and flags any "
            "KNOWN_SITES key that appears as a quoted string literal "
            "in NO test file.  Fix: add a test that targets the site "
            "with a FaultPlan/FaultSpec (or asserts the degrade "
            "behavior behind it), or retire the registry entry."
        ),
        fn=_check_chaos_site_tested,
    ),
    Rule(
        id="metric-naming",
        family="registry",
        summary="metric names follow <subsystem>_<name>_<unit>, one "
                "kind per name (migrated from telemetry/lint.py)",
        explain=(
            "Registering one metric name as two kinds (counter in one "
            "file, gauge in another) cannot be rendered in a Prometheus "
            "exposition and surfaces as silently-wrong scraped data; "
            "off-convention names break dashboards' subsystem grouping "
            "and unit inference.  The rule scans string-literal "
            "registrations (.counter(\"x\")/.gauge/.histogram) across "
            "the package + bench.py, enforcing lowercase snake_case, a "
            "known subsystem prefix, a known unit suffix, and cross-"
            "file kind consistency.  Pre-PR-7 names are grandfathered "
            "in LEGACY_NAMES (burn the list down, never grow it).  "
            "python -m photon_ml_tpu.telemetry --lint-metrics is a thin "
            "alias over this rule."
        ),
        fn=_check_metric_naming,
    ),
]
