"""JAX purity and donation rules.

The streamed solvers stake bit-identical resume on two properties no
generic linter checks:

- ``donated-buffer-reuse``: ``jax.jit(..., donate_argnums=...)`` hands
  the argument buffer to XLA — reading it after the call returns stale
  or deleted memory (jax raises at best, silently reuses at worst).
  optim/streaming.py's whole carry discipline exists because of this;
  the rule polices every OTHER donation site against the same mistake.
- ``jit-side-effect``: a Python side effect (telemetry write,
  ``maybe_fail``, ``print``, flight-recorder dump) inside a jitted
  function body runs ONCE at trace time, not per step — the metric
  silently flatlines and the chaos site never fires after the first
  call.  Side effects belong at the call site, outside the program.
- ``unseeded-rng``: module-global numpy RNG (``np.random.*``) and
  unseeded generators in package code are determinism hazards — the
  resume/replay contracts (chaos selfcheck, tuning journal) assume a
  run's randomness is fully determined by recorded seeds.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from photon_ml_tpu.analysis.engine import (
    Finding,
    PyFile,
    Rule,
    SourceTree,
    dotted_name,
    kwarg,
)

# ---------------------------------------------------------------------------
# shared: find jitted functions in a file
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit"}


def _jit_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...) used as a decorator factory
    if name in ("functools.partial", "partial") and node.args:
        return dotted_name(node.args[0]) in _JIT_NAMES
    return False


def _donated_positions(call: ast.Call) -> tuple[int, ...]:
    v = kwarg(call, "donate_argnums")
    if v is None:
        return ()
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        out = []
        for elt in v.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()  # dynamic (self._donate[kind]): positions unknown


def _function_defs(pf: PyFile) -> dict[str, list[ast.FunctionDef]]:
    defs: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _jitted_bodies(pf: PyFile) -> list[ast.AST]:
    """Function bodies that become jitted programs: decorated defs plus
    defs/lambdas passed positionally to jax.jit(...)."""
    bodies: list[ast.AST] = []
    defs = _function_defs(pf)
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (
                    dotted_name(dec) in _JIT_NAMES
                    or (isinstance(dec, ast.Call) and _jit_call(dec))
                ):
                    bodies.append(node)
        if isinstance(node, ast.Call) and _jit_call(node):
            args = node.args
            # functools.partial(jax.jit, ...) has no fn argument yet
            if dotted_name(node.func) in ("functools.partial", "partial"):
                continue
            if not args:
                continue
            target = args[0]
            if isinstance(target, ast.Lambda):
                bodies.append(target)
            else:
                tname = dotted_name(target)
                if tname and "." not in tname:
                    bodies.extend(defs.get(tname, []))
    return bodies


# ---------------------------------------------------------------------------
# jit-side-effect
# ---------------------------------------------------------------------------

_EFFECT_CALLEES = {
    "print", "maybe_fail", "dump_flight_recorder",
}
_EFFECT_METHODS = {
    # telemetry hub surface: metric writes + events + spans
    "inc", "set", "observe", "event", "span",
}
_EFFECT_DOTTED_PREFIXES = ("telemetry", "chaos")


def _check_jit_side_effect(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        for body in _jitted_bodies(pf):
            for node in ast.walk(body):
                if node is body or not isinstance(node, ast.Call):
                    continue
                # nested defs inside the jitted body are still traced
                callee = dotted_name(node.func)
                attr = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute) else None
                )
                effect = None
                if callee in _EFFECT_CALLEES:
                    effect = f"{callee}()"
                elif attr in _EFFECT_CALLEES:
                    effect = f".{attr}()"
                elif attr in _EFFECT_METHODS:
                    # receiver may be a name chain (tel.event) or a
                    # chained call (tel.counter('x').inc())
                    inner = node.func.value
                    if isinstance(inner, ast.Call):
                        recv = dotted_name(inner.func) or ""
                    else:
                        recv = dotted_name(inner) or ""
                    if any(
                        part.startswith(_EFFECT_DOTTED_PREFIXES)
                        or part in ("tel", "hub")
                        for part in recv.split(".")
                    ):
                        effect = (
                            f"{recv}().{attr}()"
                            if isinstance(inner, ast.Call)
                            else f"{recv}.{attr}()"
                        )
                if effect:
                    yield Finding(
                        "jit-side-effect", pf.relpath, node.lineno,
                        f"Python side effect {effect} inside a jitted "
                        "function body: it runs once at trace time, not "
                        "per execution — move it to the call site, "
                        "outside the program",
                    )


# ---------------------------------------------------------------------------
# donated-buffer-reuse
# ---------------------------------------------------------------------------

def _donating_bindings(pf: PyFile) -> dict[str, tuple[int, ...]]:
    """name (var or self-attr) -> donated positions, for assignments
    like ``self._proj_jit = jax.jit(f, donate_argnums=(0, 1))``."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if not _jit_call(node.value):
            continue
        pos = _donated_positions(node.value)
        if not pos:
            continue
        name = dotted_name(node.targets[0])
        if name:
            out[name] = pos
    return out


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, ast.Tuple):
            for elt in t.elts:
                n = dotted_name(elt)
                if n:
                    out.add(n)
        else:
            n = dotted_name(t)
            if n:
                out.add(n)
    return out


def _check_donated_reuse(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        donors = _donating_bindings(pf)
        if not donors:
            continue
        for fn in ast.walk(pf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # statements of this function in source order (shallow walk
            # is enough: the donation discipline is per-scope)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name not in donors:
                    continue
                donated_args = {
                    dotted_name(node.args[i])
                    for i in donors[name] if i < len(node.args)
                }
                donated_args.discard(None)
                if not donated_args:
                    continue
                call_stmt = node
                for anc in pf.parent_chain(node):
                    if isinstance(anc, ast.stmt):
                        call_stmt = anc
                        break
                # names rebound by the very statement making the call
                # (``g = prog(g, x)``) are safe immediately
                rebound = _assigned_names(call_stmt)
                at_risk = donated_args - rebound
                if not at_risk:
                    continue
                for later in ast.walk(fn):
                    if (
                        isinstance(later, ast.Name)
                        and isinstance(later.ctx, ast.Load)
                        and later.id in at_risk
                        and later.lineno > call_stmt.lineno
                    ):
                        # a rebinding between call and use clears it
                        if _rebound_between(
                            fn, later.id, call_stmt.lineno, later.lineno
                        ):
                            continue
                        yield Finding(
                            "donated-buffer-reuse", pf.relpath,
                            later.lineno,
                            f"{later.id!r} was donated to "
                            f"{name}(donate_argnums=...) and is read "
                            "after the call: the buffer belongs to XLA "
                            "now (deleted or reused) — rebind the name "
                            "from the call's result or stop donating it",
                        )
                        at_risk.discard(later.id)


def _rebound_between(
    fn: ast.AST, name: str, after_line: int, before_line: int
) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and (
            after_line < node.lineno < before_line
        ):
            if name in _assigned_names(node):
                return True
    return False


# ---------------------------------------------------------------------------
# unseeded-rng
# ---------------------------------------------------------------------------

#: np.random constructors that are fine WHEN SEEDED.
_RNG_CONSTRUCTORS = {"default_rng", "RandomState", "Random"}
#: np.random attribute names that are not RNG draws at all.
_RNG_NEUTRAL = {
    "Generator", "SeedSequence", "PCG64", "Philox", "BitGenerator",
    "get_state", "set_state",
}
_NP_RANDOM_PREFIXES = ("np.random.", "numpy.random.")
_STDLIB_RANDOM_FNS = {
    "random.random", "random.uniform", "random.randint", "random.choice",
    "random.shuffle", "random.sample", "random.expovariate",
    "random.gauss", "random.normalvariate", "random.randrange",
    "random.seed",
}


def _check_unseeded_rng(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            tail = name.rsplit(".", 1)[-1]
            if name.startswith(_NP_RANDOM_PREFIXES):
                if tail in _RNG_NEUTRAL:
                    continue
                if tail in _RNG_CONSTRUCTORS:
                    if node.args or node.keywords:
                        continue  # seeded (or explicitly configured)
                    yield Finding(
                        "unseeded-rng", pf.relpath, node.lineno,
                        f"unseeded {name}(): randomness not determined "
                        "by a recorded seed — pass a seed so runs "
                        "replay (the chaos/tuning resume contracts "
                        "assume it)",
                    )
                    continue
                yield Finding(
                    "unseeded-rng", pf.relpath, node.lineno,
                    f"module-global numpy RNG {name}(): shared mutable "
                    "state across threads and call sites — use a "
                    "np.random.default_rng(seed) instance plumbed from "
                    "the caller",
                )
            elif name in _STDLIB_RANDOM_FNS:
                yield Finding(
                    "unseeded-rng", pf.relpath, node.lineno,
                    f"module-global stdlib RNG {name}(): shared mutable "
                    "state; use a seeded random.Random(seed) instance",
                )
            elif name == "random.Random" and not (
                node.args or node.keywords
            ):
                yield Finding(
                    "unseeded-rng", pf.relpath, node.lineno,
                    "unseeded random.Random(): randomness not "
                    "determined by a recorded seed — plumb a seeded or "
                    "injectable rng",
                )


RULES = [
    Rule(
        id="donated-buffer-reuse",
        family="jax",
        summary="no read of a buffer after it was donated to a "
                "jit(donate_argnums=...) call",
        explain=(
            "jax.jit(f, donate_argnums=...) transfers ownership of the "
            "named arguments' buffers to XLA: the program may write its "
            "outputs into them.  Reading the donated Python reference "
            "after the call is use-after-free — jax raises "
            "'buffer has been deleted' at best; under some backends it "
            "aliases silently.  The rule tracks assignments of donating "
            "programs (`self._p = jax.jit(f, donate_argnums=(0,))`), "
            "finds their call sites, and flags loads of donated "
            "argument names after the call unless the name was rebound "
            "(the `g = prog(g, x)` carry idiom optim/streaming.py "
            "documents).  Dynamic donation tables "
            "(`donate_argnums=self._donate[kind]`) are invisible to "
            "static analysis — those paths are covered by "
            "TestPipelineParity's donation-safety tests instead."
        ),
        fn=_check_donated_reuse,
    ),
    Rule(
        id="jit-side-effect",
        family="jax",
        summary="no Python side effects (telemetry, maybe_fail, print) "
                "inside jitted function bodies",
        explain=(
            "A jitted function body executes as Python exactly once per "
            "compilation (trace time).  A telemetry counter bumped "
            "there increments once and then flatlines; a chaos "
            "maybe_fail() site fires during tracing and never again — "
            "the fault schedule silently stops matching occurrence "
            "indices.  The rule finds jit-bound bodies (decorated defs, "
            "defs/lambdas passed to jax.jit) and flags calls to print, "
            "maybe_fail, dump_flight_recorder, and telemetry metric/"
            "event/span methods inside them.  Fix: hoist the effect to "
            "the call site (game/descent.py bumps its iteration "
            "histogram AROUND the program call, never inside)."
        ),
        fn=_check_jit_side_effect,
    ),
    Rule(
        id="unseeded-rng",
        family="jax",
        summary="no module-global or unseeded RNG in package code "
                "(determinism hazard)",
        explain=(
            "Bit-for-bit resume (chaos selfcheck) and journal replay "
            "(tuning) require every random draw to be derived from a "
            "recorded seed.  np.random.<fn>() draws from hidden global "
            "state shared across threads — two interleavings produce "
            "two histories.  The rule flags module-global numpy and "
            "stdlib random calls, and unseeded default_rng()/"
            "RandomState()/random.Random() constructions.  Intentional "
            "nondeterminism (watchdog/supervisor restart jitter, which "
            "is injectable for tests) carries a baseline entry saying "
            "so."
        ),
        fn=_check_unseeded_rng,
    ),
]
