"""CLI for the invariant checker.

    python -m photon_ml_tpu.analysis --check
    python -m photon_ml_tpu.analysis --check --root photon_ml_tpu/serving
    python -m photon_ml_tpu.analysis --update-baseline
    python -m photon_ml_tpu.analysis --list-rules
    python -m photon_ml_tpu.analysis --explain donated-buffer-reuse

Exit status: 0 when the tree is clean (modulo suppressions and the
committed baseline), 1 when there are actionable findings, parse
errors, or a broken baseline.  Stale baseline entries are reported on
stderr but do not fail the check — they mean a grandfathered defect was
fixed and the entry should be deleted (run --update-baseline).
"""

from __future__ import annotations

import argparse
import sys
import textwrap

from photon_ml_tpu.analysis import (
    ALL_RULES,
    RULES_BY_ID,
    Baseline,
    SourceTree,
    check,
    default_baseline_path,
    run_rules,
)


def _list_rules() -> int:
    width = max(len(r.id) for r in ALL_RULES)
    family = None
    for r in ALL_RULES:
        if r.family != family:
            family = r.family
            print(f"[{family}]")
        print(f"  {r.id:<{width}}  {r.summary}")
    print(
        "\nsuppress inline with '# photon: disable=<rule>' (or =all); "
        "see --explain <rule> for the full story"
    )
    return 0


def _explain(rule_id: str) -> int:
    rule = RULES_BY_ID.get(rule_id)
    if rule is None:
        print(
            f"unknown rule {rule_id!r}; known: "
            f"{', '.join(sorted(RULES_BY_ID))}",
            file=sys.stderr,
        )
        return 1
    print(f"{rule.id} [{rule.family}]")
    print(f"  {rule.summary}\n")
    print(textwrap.fill(rule.explain, width=76, initial_indent="  ",
                        subsequent_indent="  "))
    return 0


def _update_baseline(roots, baseline_path: str) -> int:
    tree = SourceTree(roots=roots)
    raw = run_rules(tree, ALL_RULES)
    by_rel = {f.relpath: f for f in tree.files}
    keep = [
        f for f in raw
        if not (
            by_rel.get(f.path) is not None
            and by_rel[f.path].is_suppressed(f.rule, f.line)
        )
    ]
    try:
        old = Baseline.load(baseline_path)
    except ValueError:
        # A baseline mid-edit (TODO justifications) still carries the
        # human-written ones forward.
        import json
        with open(baseline_path, encoding="utf-8") as f:
            old = Baseline.__new__(Baseline)
            old.entries = json.load(f).get("entries", [])
            old._keys = set()
    Baseline.write(baseline_path, keep, old)
    print(f"wrote {baseline_path} with {len(keep)} entries")
    print(
        "fill in any 'TODO' justifications before committing: --check "
        "refuses a baseline with placeholder or missing justifications"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.analysis",
        description="project-wide invariant checker (see docs/analysis.md)",
    )
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="run all rules; exit 1 on findings")
    mode.add_argument("--update-baseline", action="store_true",
                      help="rewrite the baseline from current findings")
    mode.add_argument("--list-rules", action="store_true",
                      help="list rule ids and one-line summaries")
    mode.add_argument("--explain", metavar="RULE",
                      help="print the full rationale for one rule")
    p.add_argument("--root", action="append", default=None,
                   help="scan root (repeatable; default: package + bench.py)")
    p.add_argument("--baseline", default=None,
                   help="baseline path (default: analysis/baseline.json)")
    args = p.parse_args(argv)

    if args.list_rules:
        return _list_rules()
    if args.explain:
        return _explain(args.explain)

    baseline_path = args.baseline or default_baseline_path()
    if args.update_baseline:
        return _update_baseline(args.root, baseline_path)

    try:
        report = check(roots=args.root, baseline_path=baseline_path)
    except ValueError as exc:  # malformed baseline
        print(f"analysis: {exc}", file=sys.stderr)
        return 1
    for err in report.parse_errors:
        print(err)
    for f in report.findings:
        print(f)
    for e in report.stale_baseline:
        print(
            f"stale baseline entry (fixed? delete it): "
            f"[{e['rule']}] {e['path']}: {e['message']}",
            file=sys.stderr,
        )
    status = "clean" if report.ok else "FAILED"
    print(
        f"analysis: {status} — {len(report.findings)} finding(s), "
        f"{report.suppressed} suppressed, {report.baselined} baselined, "
        f"{len(report.stale_baseline)} stale baseline entr(ies) over "
        f"{report.files} files / {report.rules} rules"
    )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
