"""Project-wide invariant checker: static rules + runtime sanitizers.

``python -m photon_ml_tpu.analysis --check`` runs every rule over the
package (exit 0 = clean); ``--list-rules`` / ``--explain RULE`` document
them; ``--update-baseline`` regenerates the grandfather list.  The
runtime half (lock-order tracking, thread- and process-leak sentinels)
lives in :mod:`photon_ml_tpu.analysis.sanitizers` and is imported
lazily — the static checker never imports jax or telemetry.

Rule families:

- concurrency (rules_concurrency.py): thread-lifecycle,
  process-lifecycle, lock-blocking-call, wall-clock-interval
- jax (rules_jax.py): donated-buffer-reuse, jit-side-effect,
  unseeded-rng
- registry (rules_registry.py): chaos-site-sync, metric-naming
"""

from __future__ import annotations

from photon_ml_tpu.analysis import (
    rules_concurrency,
    rules_jax,
    rules_registry,
)
from photon_ml_tpu.analysis.engine import (
    Baseline,
    CheckReport,
    Finding,
    Rule,
    SourceTree,
    default_baseline_path,
    default_roots,
    run_check,
    run_rules,
)

#: Every rule, in --list-rules order (family, then id).
ALL_RULES: list[Rule] = [
    *rules_concurrency.RULES,
    *rules_jax.RULES,
    *rules_registry.RULES,
]

RULES_BY_ID: dict[str, Rule] = {r.id: r for r in ALL_RULES}


def check(
    roots=None,
    repo_root=None,
    baseline_path=None,
    rules=None,
) -> CheckReport:
    """Run the full rule set (or ``rules``) and return a CheckReport."""
    return run_check(
        ALL_RULES if rules is None else rules,
        roots=roots,
        repo_root=repo_root,
        baseline_path=baseline_path,
    )


__all__ = [
    "ALL_RULES",
    "RULES_BY_ID",
    "Baseline",
    "CheckReport",
    "Finding",
    "Rule",
    "SourceTree",
    "check",
    "default_baseline_path",
    "default_roots",
    "run_check",
    "run_rules",
]
