"""Concurrency rules: thread lifecycle, lock hygiene, clock discipline.

PRs 5-9 grew ~30 threads and locks across prefetch, streaming, serving,
tuning, and the ops plane, policed only by convention.  These rules make
the conventions machine-checked:

- ``thread-lifecycle``: a non-daemon ``threading.Thread`` that is not
  joined on EVERY exit path (a ``finally``, or a separate lifecycle
  method like ``stop()``/``close()``) wedges interpreter shutdown the
  first time an exception lands between ``start()`` and ``join()``.
  The repo convention after the prefetch-leak incident (PR 6) is:
  every background thread is ``daemon=True`` AND joined by its owner.
- ``process-lifecycle``: the same discipline one isolation level up —
  every ``multiprocessing.Process`` / ``subprocess.Popen`` is
  join()ed/wait()ed on every exit path AND carries a terminate/kill
  escalation, because a leaked child outlives the interpreter and pins
  shared memory; a wedged one hangs shutdown behind an unbounded reap.
- ``lock-blocking-call``: a blocking call (sleep, network, thread join,
  device transfer, future result, fsync) while holding a
  ``threading.Lock`` turns a micro-critical-section into a convoy —
  and on the serving path, into tail latency.  chaos/core.py's
  sleep-outside-the-lock shape is the model.
- ``wall-clock-interval``: ``time.time()`` is wall clock — NTP steps
  it, VM migration steps it.  Every latency/interval measurement must
  use ``time.monotonic()``/``perf_counter()``; ``time.time()`` is only
  for wall-anchoring (epoch fields, ``*_wall`` keys) where the absolute
  date IS the datum.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from photon_ml_tpu.analysis.engine import (
    Finding,
    PyFile,
    Rule,
    SourceTree,
    dotted_name,
    kwarg,
)

# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

def _thread_target_name(pf: PyFile, call: ast.Call) -> Optional[str]:
    """The name the new Thread is bound to ('t', 'self._thread'), if the
    creation is a plain single-target assignment."""
    parent = pf.parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        return dotted_name(parent.targets[0])
    return None


def _is_daemon(call: ast.Call) -> bool:
    v = kwarg(call, "daemon")
    return isinstance(v, ast.Constant) and v.value is True


def _method_calls_on(pf: PyFile, name: str, method: str) -> list[ast.Call]:
    """Calls ``<name>.<method>(...)`` anywhere in the file."""
    out = []
    for node in ast.walk(pf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == method
            and dotted_name(node.func.value) == name
        ):
            out.append(node)
    return out


def _in_finally(pf: PyFile, node: ast.AST) -> bool:
    cur = node
    for anc in pf.parent_chain(node):
        if isinstance(anc, ast.Try):
            for stmt in anc.finalbody:
                if cur is stmt or any(
                    cur is d for d in ast.walk(stmt)
                ):
                    return True
        cur = anc
    return False


def _check_thread_lifecycle(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee not in ("threading.Thread", "Thread"):
                continue
            if _is_daemon(node):
                continue
            name = _thread_target_name(pf, node)
            if name is None:
                # Unbound creation (list comprehension, direct .start()):
                # nothing can ever join it by name.
                yield Finding(
                    "thread-lifecycle", pf.relpath, node.lineno,
                    "non-daemon Thread created without a binding that "
                    "could be joined; pass daemon=True (and join where "
                    "results are needed)",
                )
                continue
            joins = _method_calls_on(pf, name, "join")
            if not joins:
                yield Finding(
                    "thread-lifecycle", pf.relpath, node.lineno,
                    f"non-daemon Thread {name!r} is never joined in this "
                    "file; pass daemon=True or join it on every exit "
                    "path",
                )
                continue
            starts = _method_calls_on(pf, name, "start")
            start_fns = {pf.enclosing_function(c) for c in starts}
            for j in joins:
                if _in_finally(pf, j):
                    break  # exception-safe join exists
                if pf.enclosing_function(j) not in start_fns:
                    break  # lifecycle pattern: joined by stop()/close()
            else:
                yield Finding(
                    "thread-lifecycle", pf.relpath, node.lineno,
                    f"non-daemon Thread {name!r} is joined only on the "
                    "happy path: an exception between start() and join() "
                    "leaks it and wedges interpreter exit; join in a "
                    "finally: block or pass daemon=True",
                )


# ---------------------------------------------------------------------------
# process-lifecycle
# ---------------------------------------------------------------------------

#: process-constructor name -> the call that reaps it.  ``subprocess.run``
#: / ``call`` / ``check_output`` wait internally and are exempt.
_PROC_KINDS = {"Process": "join", "Popen": "wait"}


def _process_kind(callee: Optional[str]) -> Optional[str]:
    """'Process' for multiprocessing.Process / ctx.Process /
    mp.get_context(...).Process, 'Popen' for subprocess.Popen — matched
    on the final attribute so spawn-context construction counts too."""
    if not callee:
        return None
    base = callee.rsplit(".", 1)[-1]
    return base if base in _PROC_KINDS else None


def _check_process_lifecycle(tree: SourceTree) -> Iterable[Finding]:
    """A child process needs MORE than a thread: ``join``/``wait`` on
    every exit path (else zombies accumulate), AND a ``terminate()`` or
    ``kill()`` escalation reachable somewhere (else a wedged child hangs
    its owner's shutdown forever — a thread can at worst wedge exit, a
    process also pins shared memory and sockets past the interpreter)."""
    for pf in tree.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _process_kind(dotted_name(node.func))
            if kind is None:
                continue
            reap = _PROC_KINDS[kind]
            name = _thread_target_name(pf, node)
            if name is None:
                yield Finding(
                    "process-lifecycle", pf.relpath, node.lineno,
                    f"{kind} created without a binding that could be "
                    f"{reap}ed/terminated; bind it and reap it on every "
                    "exit path",
                )
                continue
            reaps = _method_calls_on(pf, name, reap)
            if not reaps:
                yield Finding(
                    "process-lifecycle", pf.relpath, node.lineno,
                    f"{kind} {name!r} is never {reap}ed in this file: "
                    "an unreaped child is a zombie and its exit status "
                    f"is lost; {reap} it on every exit path",
                )
                continue
            if not (
                _method_calls_on(pf, name, "terminate")
                or _method_calls_on(pf, name, "kill")
            ):
                yield Finding(
                    "process-lifecycle", pf.relpath, node.lineno,
                    f"{kind} {name!r} is {reap}ed but never "
                    "terminate()d/kill()ed: a wedged child makes the "
                    f"{reap} wait forever; escalate "
                    f"{reap}(timeout) -> terminate -> kill on shutdown",
                )
                continue
            # Exception safety, same discipline as thread-lifecycle: the
            # reap runs in a finally, or lives in a different method than
            # the one that launched the child (stop()/close() pattern).
            starts = _method_calls_on(pf, name, "start") or [node]
            start_fns = {pf.enclosing_function(c) for c in starts}
            for r in reaps:
                if _in_finally(pf, r):
                    break
                if pf.enclosing_function(r) not in start_fns:
                    break
            else:
                yield Finding(
                    "process-lifecycle", pf.relpath, node.lineno,
                    f"{kind} {name!r} is {reap}ed only on the happy "
                    "path: an exception after launch leaks the child "
                    f"(and whatever it maps); {reap} in a finally: "
                    "block or from a lifecycle stop()/close()",
                )


# ---------------------------------------------------------------------------
# lock-blocking-call
# ---------------------------------------------------------------------------

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

#: Callee patterns that block the calling thread for unbounded /
#: milliseconds-scale time.  Attribute tails match any receiver
#: (``x.block_until_ready``), dotted names match exactly.
_BLOCKING_DOTTED = {
    "time.sleep", "sleep",
    "urllib.request.urlopen", "urlopen",
    "socket.create_connection", "socket.getaddrinfo",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "jax.device_put", "os.fsync",
}
_BLOCKING_ATTRS = {
    "block_until_ready",  # device sync
    "result",  # concurrent.futures
    "recv", "accept", "connect", "urlopen",
    "fsync",
}
#: join() blocks too, but Condition/Barrier-free code here only joins
#: THREADS; flag it separately for a pointed message.
_JOIN_ATTR = "join"


def _lock_names(pf: PyFile) -> set[str]:
    """Names (vars and self-attrs) bound to lock objects in this file,
    including locks wrapped by ``sanitizers.tracked(...)``."""
    names: set[str] = set()
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        value = node.value
        # unwrap sanitizers.tracked(threading.Lock(), "witness")
        if (
            isinstance(value, ast.Call)
            and (dotted_name(value.func) or "").endswith("tracked")
            and value.args
        ):
            value = value.args[0]
        if (
            isinstance(value, ast.Call)
            and dotted_name(value.func) in _LOCK_FACTORIES
        ):
            target = dotted_name(node.targets[0])
            if target:
                names.add(target)
    return names


# ``.join()`` attribute calls that can never block: path joins and
# string joins on a literal separator.  Everything else named .join()
# under a lock is treated as a thread join.
_PATH_JOINS = {"os.path.join", "posixpath.join", "ntpath.join"}


def _is_thread_join(call: ast.Call, callee: Optional[str]) -> bool:
    if callee in _PATH_JOINS:
        return False
    if (
        isinstance(call.func, ast.Attribute)
        and isinstance(call.func.value, ast.Constant)
        and isinstance(call.func.value.value, str)
    ):
        return False
    return True


def _blocking_reason(callee: Optional[str], attr: Optional[str]
                     ) -> Optional[str]:
    if callee in _BLOCKING_DOTTED:
        return f"{callee}()"
    if attr in _BLOCKING_ATTRS:
        return f".{attr}()"
    return None


def _check_lock_blocking(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        locks = _lock_names(pf)
        if not locks:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.With):
                continue
            held = [
                dotted_name(item.context_expr)
                for item in node.items
                if dotted_name(item.context_expr) in locks
            ]
            if not held:
                continue
            for body_stmt in node.body:
                for sub in ast.walk(body_stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = dotted_name(sub.func)
                    attr = (
                        sub.func.attr
                        if isinstance(sub.func, ast.Attribute) else None
                    )
                    if attr == _JOIN_ATTR and _is_thread_join(
                        sub, callee
                    ):
                        yield Finding(
                            "lock-blocking-call", pf.relpath, sub.lineno,
                            f"thread join while holding lock "
                            f"{held[0]!r}: every other user of the lock "
                            "convoys behind the joined thread; join "
                            "outside the critical section",
                        )
                        continue
                    reason = _blocking_reason(callee, attr)
                    if reason:
                        yield Finding(
                            "lock-blocking-call", pf.relpath, sub.lineno,
                            f"blocking call {reason} while holding lock "
                            f"{held[0]!r}; move it outside the critical "
                            "section (chaos/core.py's sleep-after-"
                            "release is the model)",
                        )


# ---------------------------------------------------------------------------
# wall-clock-interval
# ---------------------------------------------------------------------------

_WALL_OK_TOKENS = ("wall", "epoch")


def _wall_anchored_context(pf: PyFile, call: ast.Call) -> bool:
    """True when the time.time() value is being used AS a wall-clock
    datum: assigned to / keyed under a name containing 'wall' or
    'epoch'.  Everything else (subtraction, comparison, latency math)
    must use a monotonic clock."""
    node: ast.AST = call
    for anc in pf.parent_chain(call):
        if isinstance(anc, ast.Dict):
            for k, v in zip(anc.keys, anc.values):
                if v is node and isinstance(k, ast.Constant) and any(
                    t in str(k.value).lower() for t in _WALL_OK_TOKENS
                ):
                    return True
            return False
        if isinstance(anc, ast.keyword):
            return anc.arg is not None and any(
                t in anc.arg.lower() for t in _WALL_OK_TOKENS
            )
        if isinstance(anc, (ast.Assign, ast.AnnAssign)):
            targets = (
                anc.targets if isinstance(anc, ast.Assign)
                else [anc.target]
            )
            return any(
                any(t in (dotted_name(tgt) or "").lower()
                    for t in _WALL_OK_TOKENS)
                for tgt in targets
            )
        if isinstance(anc, (ast.BinOp, ast.Compare)):
            return False  # arithmetic on wall clock = interval math
        if isinstance(anc, ast.stmt):
            return False
        node = anc
    return False


def _check_wall_clock(tree: SourceTree) -> Iterable[Finding]:
    for pf in tree.files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "time.time":
                continue
            if _wall_anchored_context(pf, node):
                continue
            yield Finding(
                "wall-clock-interval", pf.relpath, node.lineno,
                "time.time() outside a wall-anchoring context (no "
                "'wall'/'epoch' in the target name or dict key): "
                "latency and interval accounting must use "
                "time.monotonic()/perf_counter() — wall clock steps "
                "under NTP and corrupts the measurement",
            )


RULES = [
    Rule(
        id="thread-lifecycle",
        family="concurrency",
        summary="every threading.Thread is daemon=True or joined on "
                "every exit path (finally / lifecycle stop())",
        explain=(
            "A non-daemon thread that is never joined — or joined only "
            "on the happy path — blocks interpreter exit the first time "
            "an exception lands between start() and join(): CI wedges "
            "instead of failing, and the thread pins whatever buffers "
            "it holds (the PR-6 prefetch leak).  The rule accepts: "
            "daemon=True; a join inside a finally: block; or the "
            "lifecycle-object pattern where start() and join() live in "
            "different methods (MicroBatcher.start/stop).  Fix: pass "
            "daemon=True and keep the join for result correctness, "
            "moving it into a finally: when start and join share a "
            "function."
        ),
        fn=_check_thread_lifecycle,
    ),
    Rule(
        id="process-lifecycle",
        family="concurrency",
        summary="every multiprocessing.Process / subprocess.Popen is "
                "join()ed/wait()ed on every exit path AND has a "
                "terminate/kill escalation",
        explain=(
            "thread-lifecycle, one isolation level up — and stricter, "
            "because a leaked child process outlives the interpreter "
            "and pins shared-memory segments and sockets, and an "
            "unreaped one is a zombie.  The rule matches constructors "
            "by final attribute (multiprocessing.Process, ctx.Process "
            "from a spawn context, subprocess.Popen; subprocess.run/"
            "call/check_output wait internally and are exempt) and "
            "requires: a binding; a join() (Process) or wait() (Popen) "
            "somewhere in the file, exception-safe (in a finally:, or "
            "in a different method than the launch — the stop()/close() "
            "lifecycle split); and a terminate() or kill() call so a "
            "WEDGED child cannot hang shutdown behind an unbounded "
            "reap.  serving/procpool.py's stop() — shutdown frame, "
            "join(timeout), then terminate+join and kill+join in a "
            "finally: — is the model.  Runtime counterpart: "
            "sanitizers.ProcessLeakSentinel."
        ),
        fn=_check_process_lifecycle,
    ),
    Rule(
        id="lock-blocking-call",
        family="concurrency",
        summary="no blocking call (sleep/network/join/device/fsync/"
                "future-result) while holding a known lock",
        explain=(
            "The engine learns which names hold locks (assignments from "
            "threading.Lock/RLock/Condition, including "
            "sanitizers.tracked(...) wrappers) and flags blocking calls "
            "lexically inside `with <lock>:` bodies: time.sleep, "
            "urlopen/socket/subprocess, thread .join(), "
            "jax.device_put / .block_until_ready(), future .result(), "
            "os.fsync.  Holding a lock across any of these convoys "
            "every other user of the lock — on the serving path that is "
            "directly request tail latency; on the streamed path it "
            "stalls the pack/transfer overlap.  Fix: copy state under "
            "the lock, block outside it (chaos/core.py _hit sleeps "
            "after releasing; prefetch snapshots under live_lock and "
            "publishes outside).  Deliberate holds (a journal fsync "
            "that IS the critical section) carry a suppression or a "
            "baseline entry with the justification."
        ),
        fn=_check_lock_blocking,
    ),
    Rule(
        id="wall-clock-interval",
        family="concurrency",
        summary="time.time() only for wall-anchoring; intervals use "
                "monotonic()/perf_counter()",
        explain=(
            "time.time() is stepped by NTP and VM migration; a latency "
            "histogram fed from it can go negative or jump hours.  The "
            "telemetry contract (docs/telemetry.md) is: monotonic "
            "timestamps everywhere, wall clock ONLY to anchor a run's "
            "epoch (`_epoch_wall`, `t_wall`, `wall_epoch` fields) for "
            "cross-process trace merging.  The rule allows time.time() "
            "when the value lands under a name or dict key containing "
            "'wall' or 'epoch', and flags every other use — especially "
            "arithmetic (`time.time() - t0`), which is interval math on "
            "the wrong clock.  Fix: time.perf_counter() for intervals, "
            "or rename the anchor field to say wall/epoch."
        ),
        fn=_check_wall_clock,
    ),
]
