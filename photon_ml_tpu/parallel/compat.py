"""Version-compatibility shims for the jax APIs this codebase tracks.

The library is written against the current ``jax.shard_map`` surface
(``check_vma``); older jax releases ship the same functionality as
``jax.experimental.shard_map.shard_map`` with the flag spelled
``check_rep``.  Every library call site imports :func:`shard_map` from
here so the whole mesh layer (streamed DP, tensor parallel, distributed
GAME) runs unchanged on either API generation.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

else:  # pre-jax.shard_map releases: experimental module, check_rep flag
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
