"""Feature-dim (tensor) parallelism for wide fixed-effect GLMs.

The reference has no TP — its model is one weight vector small enough to
broadcast (SURVEY.md §2 parallelism table, TP row: "optional feature-dim
sharding for very wide models"; §5.7 scale axis (b): feature spaces up to
very wide sparse widths).  At 10⁸+ features, a replicated ``w`` (plus the
L-BFGS ``(m, d)`` history buffers — 10× ``w``!) no longer fits per-device
alongside the data, so here both are sharded over a second mesh axis:

- mesh: 2-D ``(data, feature)`` — rows sharded over ``data`` as in
  parallel/distributed.py, columns of X and entries of ``w`` sharded over
  ``feature``;
- each device holds ONE (row-block × column-slice) tile of X with local
  column ids, its slice of ``w``, and its slice of every history vector;
- margins: local tile matvec then ``psum`` over the FEATURE axis (each
  data-rank's row margins need every column's contribution);
- gradient: loss derivatives are replicated within a feature group (they
  depend only on margins), so the local ``rmatvec`` then ``psum`` over the
  DATA axis yields the gradient SLICE for the local columns — the gradient
  is born sharded exactly like ``w``, no all-gather anywhere;
- the whole L-BFGS loop runs on sharded state inside ``shard_map``: every
  w-space inner product / norm reduces over the feature axis
  (``optim.lbfgs`` ``w_axis``), so the iteration is an exact replica of the
  single-device one.

Per objective evaluation the wire cost is one (rows/dp)-length psum over
``feature`` + one fused scalar/slice psum over ``data`` — both ride ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from photon_ml_tpu.parallel.compat import shard_map
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.ops.sparse import DenseMatrix, SparseMatrix, from_coo
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, SolveResult, lbfgs_solve
from photon_ml_tpu.optim.owlqn import OWLQNConfig, owlqn_solve
from photon_ml_tpu.optim.tron import TRONConfig, tron_solve
from photon_ml_tpu.parallel.distributed import DATA_AXIS

Array = jax.Array

FEATURE_AXIS = "feature"


def dp_tp_mesh(
    dp: int, tp: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """A (data=dp, feature=tp) mesh.  Convention: the FEATURE axis is the
    minor (fastest-varying) one so a feature group's devices are ICI
    neighbors — the per-evaluation margin psum rides the shortest links."""
    devices = jax.devices() if devices is None else list(devices)
    if len(devices) < dp * tp:
        raise ValueError(f"need {dp * tp} devices, have {len(devices)}")
    return Mesh(
        np.asarray(devices[: dp * tp]).reshape(dp, tp),
        (DATA_AXIS, FEATURE_AXIS),
    )


def _ceil_to(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_glm_data_dp_tp(
    X_host,
    labels: np.ndarray,
    mesh: Mesh,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    dtype=jnp.float32,
):
    """Tile host data over the (data, feature) mesh.

    Rows pad (weight 0) to a multiple of dp; columns pad (all-zero) to a
    multiple of tp.  Returns ``(features, labels, weights, offsets, d)``
    where ``features`` arrays carry leading (dp, tp) tile axes, the row
    arrays carry a leading (dp,) axis (replicated over feature by their
    sharding), and ``d`` is the ORIGINAL feature count (strip padding from
    the solution with ``w[:d]``).
    """
    import scipy.sparse as sp

    dp, tp = (mesh.shape[DATA_AXIS], mesh.shape[FEATURE_AXIS])
    n, d = X_host.shape
    rows_per = _ceil_to(n, dp) // dp
    cols_per = _ceil_to(d, tp) // tp

    labels = np.asarray(labels, np.float32)
    weights = (
        np.ones(n, np.float32) if weights is None
        else np.asarray(weights, np.float32)
    )
    offsets = (
        np.zeros(n, np.float32) if offsets is None
        else np.asarray(offsets, np.float32)
    )
    pad = dp * rows_per - n
    labels = np.concatenate([labels, np.zeros(pad, np.float32)])
    weights = np.concatenate([weights, np.zeros(pad, np.float32)])
    offsets = np.concatenate([offsets, np.zeros(pad, np.float32)])

    if sp.issparse(X_host):
        csr = X_host.tocsr()
        csr.sum_duplicates()
        tiles = []
        budget = 1
        for i in range(dp):
            row_block = csr[min(i * rows_per, n): min((i + 1) * rows_per, n)]
            row_tiles = []
            for j in range(tp):
                tile = row_block[:, j * cols_per: min((j + 1) * cols_per, d)]
                coo = tile.tocoo()
                row_tiles.append((coo.row, coo.col, coo.data))
                budget = max(budget, coo.nnz)
            tiles.append(row_tiles)
        mats = [
            [
                from_coo(r, c, v, rows_per, cols_per, budget, dtype)
                for (r, c, v) in row_tiles
            ]
            for row_tiles in tiles
        ]
        features = SparseMatrix(
            row_ids=jnp.stack(
                [jnp.stack([m.row_ids for m in row]) for row in mats]
            ),
            col_ids=jnp.stack(
                [jnp.stack([m.col_ids for m in row]) for row in mats]
            ),
            values=jnp.stack(
                [jnp.stack([m.values for m in row]) for row in mats]
            ),
            n_rows=rows_per,
            n_cols=cols_per,
        )
    else:
        dense = np.asarray(X_host, np.float32)
        dense = np.pad(
            dense, ((0, dp * rows_per - n), (0, tp * cols_per - d))
        )
        features = DenseMatrix(
            jnp.asarray(
                dense.reshape(dp, rows_per, tp, cols_per).transpose(
                    0, 2, 1, 3
                ),
                dtype,
            )
        )

    feat_sharding = NamedSharding(mesh, P(DATA_AXIS, FEATURE_AXIS))
    row_sharding = NamedSharding(mesh, P(DATA_AXIS))
    features = jax.tree.map(
        lambda x: jax.device_put(x, feat_sharding), features
    )
    put_rows = lambda a: jax.device_put(
        jnp.asarray(a.reshape(dp, rows_per)), row_sharding
    )
    return (
        features,
        put_rows(labels),
        put_rows(weights),
        put_rows(offsets),
        d,
    )


# shard_map spec layout shared by every TP solver: the six data args
# (features tiles, three row arrays, the w0 shard, the traced scalar) and a
# replicated SolveResult with w/grad staying feature-sharded.
_TP_IN_SPECS = (
    P(DATA_AXIS, FEATURE_AXIS),
    P(DATA_AXIS),
    P(DATA_AXIS),
    P(DATA_AXIS),
    P(FEATURE_AXIS),
    P(),
)
_TP_OUT_SPECS = SolveResult(
    w=P(FEATURE_AXIS),
    value=P(),
    grad=P(FEATURE_AXIS),
    iterations=P(),
    converged=P(),
    values=P(),
    grad_norms=P(),
)


@functools.lru_cache(maxsize=None)
def _make_tp_solver(task: str, mesh: Mesh, config: LBFGSConfig):
    """ONE jitted shard_map program per (task, mesh, config) — reused across
    calls, so a λ sweep or repeated fits pay a single compile per data shape
    (``reg_weight`` and the data are traced arguments)."""
    loss = losses_lib.get(task)

    def spmd(feat, lab, wts, off, w0_local, lam):
        local = jax.tree.map(lambda x: x[0, 0], feat)
        vg = _smooth_vg(loss, local, lab[0], wts[0], off[0])
        return lbfgs_solve(
            lambda wl: vg(wl, lam), w0_local, config, w_axis=FEATURE_AXIS
        )

    return jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=_TP_IN_SPECS,
            out_specs=_TP_OUT_SPECS,
            check_vma=False,
        )
    )


def _smooth_vg(loss, local, lab, wts, off):
    """The sharded smooth GLM objective shared by every TP solver: margins
    psum over FEATURE, weighted loss + gradient psum over DATA, L2 term via
    a feature-axis psum'd dot.  Returns vg(wl, l2) -> (value, grad_slice)."""

    def vg(wl, l2):
        m = lax.psum(local.matvec(wl), FEATURE_AXIS) + off
        val = lax.psum(jnp.sum(wts * loss.value(m, lab)), DATA_AXIS)
        u = wts * loss.d1(m, lab)
        g = lax.psum(local.rmatvec(u), DATA_AXIS)
        val = val + 0.5 * l2 * lax.psum(jnp.vdot(wl, wl), FEATURE_AXIS)
        return val, g + l2 * wl

    return vg


def _padded_width(features, mesh) -> int:
    tp = mesh.shape[FEATURE_AXIS]
    if isinstance(features, SparseMatrix):
        return features.n_cols * tp  # n_cols is the per-tile width
    return features.data.shape[1] * features.data.shape[3]


@functools.lru_cache(maxsize=None)
def _make_tp_owlqn_solver(task: str, mesh: Mesh, config: OWLQNConfig):
    """ONE jitted shard_map OWL-QN program per (task, mesh, config) — the
    L1/elastic-net counterpart of :func:`_make_tp_solver`.  The smooth part
    (value/grad + L2) reduces exactly as in the L-BFGS solver; the L1 term,
    pseudo-gradient norms, and orthant machinery run on w shards with
    feature-axis psums (``owlqn_solve`` w_axis)."""
    loss = losses_lib.get(task)

    def spmd(feat, lab, wts, off, w0_local, l1, l2, mask_local):
        local = jax.tree.map(lambda x: x[0, 0], feat)
        vg = _smooth_vg(loss, local, lab[0], wts[0], off[0])
        return owlqn_solve(
            lambda wl: vg(wl, l2), w0_local, l1, config,
            l1_mask=mask_local, w_axis=FEATURE_AXIS,
        )

    return jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=_TP_IN_SPECS[:5] + (P(), P(), P(FEATURE_AXIS)),
            out_specs=_TP_OUT_SPECS,
            check_vma=False,
        )
    )


def tp_owlqn_solve(
    task: str,
    features,
    labels: Array,
    weights: Array,
    offsets: Array,
    mesh: Mesh,
    l1_weight: Array | float,
    l2_weight: Array | float = 0.0,
    w0: Optional[Array] = None,
    config: OWLQNConfig = OWLQNConfig(),
    l1_mask: Optional[Array] = None,
) -> SolveResult:
    """L1/elastic-net fit with rows sharded over DATA and features over
    FEATURE — very wide sparse models keep w, the L-BFGS history, AND the
    orthant state sharded.  ``l1_mask`` (global, column-padded width) exempts
    columns (e.g. the intercept) from the penalty."""
    d_padded = _padded_width(features, mesh)
    if w0 is None:
        w0 = jnp.zeros((d_padded,), jnp.float32)
    mask = (
        jnp.ones((d_padded,), jnp.float32) if l1_mask is None
        else jnp.asarray(l1_mask, jnp.float32)
    )
    fn = _make_tp_owlqn_solver(losses_lib.get(task).name, mesh, config)
    return fn(
        features, labels, weights, offsets, w0,
        jnp.asarray(l1_weight, jnp.float32),
        jnp.asarray(l2_weight, jnp.float32),
        mask,
    )


@functools.lru_cache(maxsize=None)
def _make_tp_tron_solver(task: str, mesh: Mesh, config: TRONConfig):
    """ONE jitted shard_map TRON program per (task, mesh, config): the
    trust-region Newton-CG outer/inner loops run on w shards with
    feature-axis psums (``tron_solve`` w_axis); each CG step's HVP is one
    (margin psum over FEATURE) + (gradient-side psum over DATA) pair — the
    reference's per-CG-step ``HessianVectorAggregator`` treeAggregate
    collapsed onto ICI."""
    loss = losses_lib.get(task)

    def spmd(feat, lab, wts, off, w0_local, lam):
        local = jax.tree.map(lambda x: x[0, 0], feat)
        lab_l, wts_l, off_l = lab[0], wts[0], off[0]
        vg = _smooth_vg(loss, local, lab_l, wts_l, off_l)

        def d2f(wl):
            m = lax.psum(local.matvec(wl), FEATURE_AXIS) + off_l
            return wts_l * loss.d2(m, lab_l)

        def hvp(wl, v, aux):
            dm = lax.psum(local.matvec(v), FEATURE_AXIS)
            return lax.psum(local.rmatvec(aux * dm), DATA_AXIS) + lam * v

        return tron_solve(
            lambda wl: vg(wl, lam), hvp, w0_local, config, d2_fn=d2f,
            w_axis=FEATURE_AXIS,
        )

    return jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=_TP_IN_SPECS,
            out_specs=_TP_OUT_SPECS,
            check_vma=False,
        )
    )


def tp_tron_solve(
    task: str,
    features,
    labels: Array,
    weights: Array,
    offsets: Array,
    mesh: Mesh,
    reg_weight: Array | float = 0.0,
    w0: Optional[Array] = None,
    config: TRONConfig = TRONConfig(),
) -> SolveResult:
    """Trust-region Newton fit with rows sharded over DATA and features
    over FEATURE (L2 only, like the single-device TRON)."""
    d_padded = _padded_width(features, mesh)
    if w0 is None:
        w0 = jnp.zeros((d_padded,), jnp.float32)
    fn = _make_tp_tron_solver(losses_lib.get(task).name, mesh, config)
    return fn(
        features, labels, weights, offsets, w0,
        jnp.asarray(reg_weight, jnp.float32),
    )


def tp_lbfgs_solve(
    task: str,
    features,
    labels: Array,
    weights: Array,
    offsets: Array,
    mesh: Mesh,
    reg_weight: Array | float = 0.0,
    w0: Optional[Array] = None,
    config: LBFGSConfig = LBFGSConfig(),
) -> SolveResult:
    """Fit an L2 GLM with rows sharded over DATA and features over FEATURE.

    ``features``/``labels``... come from :func:`shard_glm_data_dp_tp`.
    Returns a replicated :class:`SolveResult` whose ``w`` is the full
    (column-padded) coefficient vector — slice ``w[:d]``.  ``reg_weight``
    is a traced scalar and the compiled program is memoized per
    (task, mesh, config): λ sweeps reuse one compile.
    """
    d_padded = _padded_width(features, mesh)
    if w0 is None:
        w0 = jnp.zeros((d_padded,), jnp.float32)
    fn = _make_tp_solver(losses_lib.get(task).name, mesh, config)
    return fn(
        features, labels, weights, offsets, w0,
        jnp.asarray(reg_weight, jnp.float32),
    )
