from photon_ml_tpu.parallel.distributed import (  # noqa: F401
    DATA_AXIS,
    DistributedGlmData,
    data_mesh,
    distributed_solve,
    shard_glm_data,
)
