"""Multi-host (pod-scale) runtime glue.

The reference scales across machines through Spark's cluster manager;
here the equivalent is JAX's distributed runtime: every host runs the
SAME program, devices of all hosts join one global mesh, and XLA routes
collectives over ICI within a slice and DCN across slices (SURVEY.md §2
"Distributed communication backend", §5.8).  The compute code in
``parallel/`` and ``game/`` is already host-count-agnostic — this module
supplies the three pieces a pod job actually needs:

1. :func:`initialize` — bring up the JAX distributed runtime from
   explicit arguments or scheduler environment variables (GKE/Borg-style
   ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID``, with
   JAX's own auto-detection as the fallback);
2. :func:`global_data_mesh` — the all-hosts mesh (identical call on
   every host);
3. :func:`host_local_rows` + :func:`assemble_global` — split a global
   row space into this host's contiguous block, and assemble per-host
   arrays into one globally-sharded ``jax.Array`` without gathering
   everything onto one machine (each host feeds only its own shard —
   the analogue of executors reading their own HDFS splits).

Single-host degenerates cleanly: ``initialize`` is a no-op,
``host_local_rows`` returns the full range, ``assemble_global`` is a
``device_put`` — so the same driver script runs anywhere.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.parallel.distributed import DATA_AXIS, data_mesh

_ENV_COORD = ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")


def _env_first(names: Sequence[str]) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up the JAX distributed runtime; returns True if multi-host.

    Arguments fall back to environment variables
    (``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID``, or their
    ``JAX_``-prefixed forms); with ``PHOTON_MULTIHOST=1`` and no explicit
    config, JAX's own cluster auto-detection runs (it understands TPU pod
    metadata).  Without any of those this is a no-op returning False —
    safe to call unconditionally at driver start (auto-detect is opt-in
    because it can block waiting for peers).
    """
    from_args = any(
        v is not None for v in (coordinator_address, num_processes, process_id)
    )
    coordinator_address = coordinator_address or _env_first(_ENV_COORD)
    # JAX_-prefixed env vars are deliberate multi-host config and always
    # count (a partial set fails loudly below).  The UNPREFIXED
    # NUM_PROCESSES / PROCESS_ID names are common enough in unrelated
    # tooling (CI harnesses, process supervisors) that they only count
    # once a coordinator address or explicit argument shows intent.
    intent = coordinator_address is not None or from_args
    env_nproc = os.environ.get("JAX_NUM_PROCESSES") or (
        os.environ.get("NUM_PROCESSES") if intent else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID") or (
        os.environ.get("PROCESS_ID") if intent else None
    )
    num_processes = (
        num_processes if num_processes is not None
        else (int(env_nproc) if env_nproc else None)
    )
    process_id = (
        process_id if process_id is not None
        else (int(env_pid) if env_pid else None)
    )
    explicit = (coordinator_address, num_processes, process_id)
    if all(v is None for v in explicit):
        # No explicit config: JAX pod auto-detection only on explicit
        # opt-in (PHOTON_MULTIHOST=1) — auto-detect can BLOCK waiting for
        # peers, which must never happen to a single-host driver run.
        if os.environ.get("PHOTON_MULTIHOST") != "1":
            return False
        jax.distributed.initialize()
        return jax.process_count() > 1
    if any(v is None for v in explicit):
        # Partial config WITH a coordinator (or explicit arguments) is a
        # deployment bug (a scheduler template lost a variable) — fail
        # loudly rather than hang or silently run single-host.
        raise ValueError(
            "multi-host initialization needs ALL of coordinator_address, "
            "num_processes, process_id (or none of them); got "
            f"coordinator_address={coordinator_address!r}, "
            f"num_processes={num_processes!r}, process_id={process_id!r}"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def global_data_mesh() -> Mesh:
    """1-D mesh over the devices of ALL hosts (same call on every host).
    Shares :data:`DATA_AXIS` with ``parallel/distributed.py`` so arrays
    assembled here feed its ``shard_map`` programs directly."""
    return data_mesh()


def initialize_logged(logger=None) -> bool:
    """Driver preamble: :func:`initialize` + a one-line topology log."""
    multi = initialize()
    if multi and logger is not None:
        logger.info(
            "multi-host runtime: %d processes, %d devices",
            jax.process_count(), jax.device_count(),
        )
    return multi


def _process_row_bounds(
    n_global_rows: int, process_id: int, n_local_devices: int
) -> Tuple[int, int]:
    """[start, stop) owned by one process under a 1-D row sharding.

    Must mirror how XLA chunks an uneven dimension over devices:
    ceil-sized chunks per DEVICE (the last device's chunk may be short or
    empty), with each process owning its local devices' consecutive
    chunks — NOT an even per-process split, which would disagree with the
    sharding whenever rows don't divide the device count."""
    total = n_local_devices * jax.process_count()
    chunk = -(-n_global_rows // total)
    start = min(process_id * n_local_devices * chunk, n_global_rows)
    stop = min((process_id + 1) * n_local_devices * chunk, n_global_rows)
    return start, stop


def host_local_rows(n_global_rows: int) -> Tuple[int, int]:
    """This process's contiguous ``[start, stop)`` block of a global row
    space, matching the device-chunked layout :func:`assemble_global`
    uses."""
    return _process_row_bounds(
        n_global_rows, jax.process_index(), jax.local_device_count()
    )


def assemble_global(host_block: np.ndarray, n_global_rows: int,
                    mesh: Mesh) -> jax.Array:
    """One globally row-sharded ``jax.Array`` from per-host blocks.

    ``host_block`` is THIS host's rows (its :func:`host_local_rows`
    slice); no host ever materializes the global array.  Single-host:
    equivalent to a sharded ``device_put`` of the whole array.
    """
    start, stop = host_local_rows(n_global_rows)
    if host_block.shape[0] != stop - start:
        raise ValueError(
            f"host block has {host_block.shape[0]} rows; this process owns "
            f"[{start}, {stop}) of {n_global_rows}"
        )
    sharding = NamedSharding(
        mesh, P(DATA_AXIS, *([None] * (host_block.ndim - 1)))
    )
    global_shape = (n_global_rows,) + tuple(host_block.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, host_block, global_shape
    )
