"""Data-parallel training over a device mesh.

The analogue of the reference's Spark runtime layer (SURVEY.md §2
"Distributed communication backend", §3.1): rows live sharded across
executors, coefficients are broadcast each iteration, and gradients come
back through ``RDD.treeAggregate``.  Here:

- rows are sharded across devices of a ``jax.sharding.Mesh`` axis
  (``DATA_AXIS``) as equal-size row blocks, built once on the host and
  device_put once (the analogue of persisting the RDD);
- coefficients are *replicated* — no per-iteration broadcast exists because
  SPMD devices all hold w;
- each objective evaluation issues ONE fused ``lax.psum`` for (value, grad)
  over ICI — the ``treeAggregate`` replacement [CONFIRMED-BASELINE mapping];
- the ENTIRE optimizer loop runs inside ``shard_map``: every device executes
  the same while_loop and every convergence decision depends only on psum'd
  quantities, so control flow stays replicated with zero host round-trips
  per iteration (the reference pays a driver↔executor round trip per
  objective evaluation).

Scale-out note: the same code runs multi-host — devices of all hosts join
the mesh and XLA routes the psum over ICI within a slice and DCN across
slices; nothing here is host-count-aware.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from photon_ml_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.ops.sparse import DenseMatrix, SparseMatrix

Array = jax.Array

DATA_AXIS = "data"


def data_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices with axis ``DATA_AXIS``."""
    devices = jax.devices() if devices is None else list(devices)
    return Mesh(np.array(devices), (DATA_AXIS,))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["data"],
    meta_fields=["n_shards"],
)
@dataclasses.dataclass
class DistributedGlmData:
    """A GlmData whose arrays carry a leading shard axis of size n_shards.

    Built by :func:`shard_glm_data`; consumed inside ``shard_map`` where each
    device sees a leading axis of 1 — :meth:`local` squeezes it away and
    (for sparse features) re-materializes shard-local row ids.
    """

    data: GlmData  # every array: (n_shards, ...)
    n_shards: int

    def local(self) -> GlmData:
        return jax.tree.map(lambda x: x[0], self.data)


def _pad_rows_to(n_rows: int, n_shards: int) -> int:
    return ((n_rows + n_shards - 1) // n_shards) * n_shards


def shard_glm_data(
    data_host,
    labels,
    mesh: Optional[Mesh],
    weights=None,
    offsets=None,
    dtype=jnp.float32,
    n_shards: Optional[int] = None,
) -> DistributedGlmData:
    """Build row-block shards from host data and place them on the mesh.

    ``data_host`` is a numpy 2-D array or scipy sparse matrix.  Rows are
    padded (weight=0) to a multiple of the mesh size, split into contiguous
    blocks, and each block becomes a shard-local matrix with LOCAL row ids.
    Sparse blocks pad nnz to the max across shards so shapes are uniform.

    ``mesh=None`` builds LOGICAL shards: the same leading-shard-axis layout
    with ``n_shards`` row blocks, left on the default device — the
    single-device stand-in the host-kind solvers (solvers/admm.py,
    solvers/block_cd.py) vmap over when no mesh participates.
    """
    import scipy.sparse as sp

    from photon_ml_tpu.data.dataset import make_glm_data
    from photon_ml_tpu.ops.sparse import from_coo

    if mesh is not None:
        n_shards = mesh.devices.size
    elif n_shards is None or n_shards < 1:
        raise ValueError("shard_glm_data needs a mesh or n_shards >= 1")
    n = data_host.shape[0]
    d = data_host.shape[1]
    total = _pad_rows_to(n, n_shards)
    rows_per = total // n_shards

    labels = np.asarray(labels, np.float32)
    weights = np.ones(n, np.float32) if weights is None else np.asarray(weights, np.float32)
    offsets = np.zeros(n, np.float32) if offsets is None else np.asarray(offsets, np.float32)
    pad = total - n
    labels = np.concatenate([labels, np.zeros(pad, np.float32)])
    weights = np.concatenate([weights, np.zeros(pad, np.float32)])
    offsets = np.concatenate([offsets, np.zeros(pad, np.float32)])

    if sp.issparse(data_host):
        csr = data_host.tocsr()
        csr.sum_duplicates()
        # nnz budget: max across row blocks, rounded up for stable shapes.
        block_nnz = [
            csr.indptr[min((i + 1) * rows_per, n)] - csr.indptr[min(i * rows_per, n)]
            for i in range(n_shards)
        ]
        budget = max(1, max(block_nnz))
        shards = []
        for i in range(n_shards):
            lo, hi = min(i * rows_per, n), min((i + 1) * rows_per, n)
            block = csr[lo:hi]
            coo = block.tocoo()
            shards.append(
                from_coo(coo.row, coo.col, coo.data, rows_per, d, budget, dtype)
            )
        features = SparseMatrix(
            row_ids=jnp.stack([s.row_ids for s in shards]),
            col_ids=jnp.stack([s.col_ids for s in shards]),
            values=jnp.stack([s.values for s in shards]),
            n_rows=rows_per,
            n_cols=d,
        )
    else:
        dense = np.asarray(data_host, np.float32)
        dense = np.concatenate([dense, np.zeros((pad, d), np.float32)])
        features = DenseMatrix(jnp.asarray(dense.reshape(n_shards, rows_per, d), dtype))

    stacked = GlmData(
        features=features,
        labels=jnp.asarray(labels.reshape(n_shards, rows_per)),
        weights=jnp.asarray(weights.reshape(n_shards, rows_per)),
        offsets=jnp.asarray(offsets.reshape(n_shards, rows_per)),
    )
    if mesh is not None:
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        stacked = jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)
    return DistributedGlmData(data=stacked, n_shards=n_shards)


def run_grid_distributed(
    problem,
    dist_data: DistributedGlmData,
    mesh: Mesh,
    reg_weights,
    w0: Optional[Array] = None,
    l1_mask: Optional[Array] = None,
    warm_start: bool = True,
    solved: Optional[dict] = None,
    on_solved=None,
):
    """The λ-grid warm-start chain (optim.problem.grid_loop) on a
    row-sharded mesh: ONE jitted shard_map program serves every λ
    (reg_weight and the warm start are traced), each objective evaluation
    is one fused psum — the reference's per-λ ``treeAggregate`` loop
    collapsed onto ICI.  Coefficient variances, when configured, run as a
    second shard_map program (one psum'd squared-column reduction per λ).

    Host-kind solvers (``OptimizerConfig.solver`` naming admm/block_cd)
    cannot run inside the traced shard_map solve; they route to
    ``solvers.sharded.run_grid_sharded``, which drives the same grid_loop
    warm-start chain around the solver's own host outer loop."""
    import jax.numpy as jnp

    from photon_ml_tpu.solvers import registry as solver_registry
    from photon_ml_tpu.solvers import sharded as solvers_sharded

    cfg = problem.config
    defn = solver_registry.resolve(
        cfg.optimizer, l1_frac=cfg.regularization.l1_weight(1.0)
    )
    if defn.kind == "host":
        return solvers_sharded.run_grid_sharded(
            problem, dist_data, mesh, reg_weights, w0=w0, l1_mask=l1_mask,
            warm_start=warm_start, solved=solved, on_solved=on_solved,
        )

    d = dist_data.data.features.shape[-1]
    if w0 is None:
        w0 = jnp.zeros((d,), jnp.float32)
    mask = (
        jnp.ones((d,), jnp.float32) if l1_mask is None
        else jnp.asarray(l1_mask, jnp.float32)
    )

    def spmd(dd: DistributedGlmData, w_start: Array, lam: Array, m: Array):
        return problem.solve(
            dd.local(), lam, w_start, axis_name=DATA_AXIS, l1_mask=m
        )

    solve_sm = jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )

    def solve_fn(lam, w_prev):
        return solve_sm(
            dist_data, w_prev, jnp.asarray(lam, jnp.float32), mask
        )

    variance_fn = None
    if problem.config.compute_variances:
        def var_spmd(dd: DistributedGlmData, w: Array, lam: Array):
            return problem.coefficient_variances(
                w, dd.local(), lam, axis_name=DATA_AXIS
            )

        var_sm = jax.jit(
            shard_map(
                var_spmd,
                mesh=mesh,
                in_specs=(P(DATA_AXIS), P(), P()),
                out_specs=P(),
                check_vma=False,
            )
        )
        variance_fn = lambda w, lam: var_sm(
            dist_data, w, jnp.asarray(lam, jnp.float32)
        )

    return problem.grid_loop(
        solve_fn, reg_weights, w0, warm_start, solved, on_solved, variance_fn
    )


def distributed_solve(
    solve_fn: Callable[[GlmData, Array], object],
    dist_data: DistributedGlmData,
    w0: Array,
    mesh: Mesh,
):
    """Run ``solve_fn(local_data, w0) -> SolveResult`` SPMD over the mesh.

    ``solve_fn`` must reduce with ``axis_name=DATA_AXIS`` inside its
    objective (see GlmObjective's ``axis_name`` argument).  Results are
    replicated; the returned pytree is the single logical result.
    """

    def spmd(dd: DistributedGlmData, w0: Array):
        return solve_fn(dd.local(), w0)

    return jax.jit(
        shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P(),
            check_vma=False,
        )
    )(dist_data, w0)
