"""Hyperparameter search: random and Gaussian-process Bayesian optimization.

The analogue of the reference's ``...ml.hyperparameter`` package
(SURVEY.md §2, §3.5): ``RandomSearch`` and ``GaussianProcessSearch`` — a GP
surrogate with a Matérn-5/2 kernel and expected-improvement acquisition —
proposing points in a bounded box (the reference searches log-scaled
regularization weights the same way).  ``EvaluationFunction`` is just a
Python callable ``params -> metric`` here (the reference wraps
GameEstimator.fit; drivers pass exactly that).

Pure NumPy: the GP fits over tens of observed points, far below device
scale.  Minimization convention — callers whose metric is
larger-is-better pass ``maximize=True``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class SearchResult:
    best_params: np.ndarray
    best_value: float
    history: list  # (params, value) tuples in evaluation order


class RandomSearch:
    """Uniform sampling in the (optionally log-scaled) box."""

    def __init__(
        self,
        bounds: Sequence[tuple[float, float]],
        log_scale: bool | Sequence[bool] = False,
        seed: int = 0,
    ):
        self.bounds = [(float(lo), float(hi)) for lo, hi in bounds]
        d = len(self.bounds)
        self.log_scale = (
            [bool(log_scale)] * d if isinstance(log_scale, bool) else list(log_scale)
        )
        self.rng = np.random.default_rng(seed)

    def _sample(self, n: int) -> np.ndarray:
        d = len(self.bounds)
        out = np.empty((n, d))
        for j, (lo, hi) in enumerate(self.bounds):
            if self.log_scale[j]:
                out[:, j] = np.exp(
                    self.rng.uniform(np.log(lo), np.log(hi), size=n)
                )
            else:
                out[:, j] = self.rng.uniform(lo, hi, size=n)
        return out

    def find(
        self,
        evaluate: Callable[[np.ndarray], float],
        n_iterations: int,
        maximize: bool = False,
    ) -> SearchResult:
        history = []
        for x in self._sample(n_iterations):
            history.append((x, float(evaluate(x))))
        sign = -1.0 if maximize else 1.0
        best = min(history, key=lambda h: sign * h[1])
        return SearchResult(best[0], best[1], history)


def _matern52(X1: np.ndarray, X2: np.ndarray, length_scale: float) -> np.ndarray:
    """Matérn-5/2 kernel, the reference's GP covariance."""
    d = np.sqrt(
        np.maximum(
            np.sum(X1**2, 1)[:, None] + np.sum(X2**2, 1)[None, :]
            - 2.0 * X1 @ X2.T,
            0.0,
        )
    )
    s = np.sqrt(5.0) * d / length_scale
    return (1.0 + s + s**2 / 3.0) * np.exp(-s)


# Escalating diagonal jitter for a non-PD Gram matrix.  Repeated or
# near-repeated observed points (an ASHA sweep re-proposing a killed
# trial's region, a λ path with clustered weights) make the Matérn Gram
# numerically singular at tiny noise levels; each retry adds 100x more
# jitter before giving up.  The first rung (0.0) is the exact matrix.
_JITTER_LADDER = (0.0, 1e-10, 1e-8, 1e-6, 1e-4)


def _chol_with_jitter(K: np.ndarray) -> np.ndarray:
    for jitter in _JITTER_LADDER:
        try:
            if jitter:
                K = K.copy()
                K[np.diag_indices_from(K)] += jitter
            return np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            continue
    raise np.linalg.LinAlgError(
        "GP Gram matrix is not positive definite even with "
        f"{_JITTER_LADDER[-1]:g} diagonal jitter — observed points are "
        "degenerate (all identical?)"
    )


def _chol_lml(
    X: np.ndarray, y: np.ndarray, length_scale: float, noise: float
) -> tuple[np.ndarray, np.ndarray, float]:
    """Cholesky + α + log marginal likelihood for one (ℓ, σ²) setting."""
    K = _matern52(X, X, length_scale)
    K[np.diag_indices_from(K)] += noise
    L = _chol_with_jitter(K)
    alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
    lml = (
        -0.5 * float(y @ alpha)
        - float(np.sum(np.log(np.diagonal(L))))
        - 0.5 * len(y) * np.log(2.0 * np.pi)
    )
    return L, alpha, lml


def _deduplicate(
    X: np.ndarray, y: np.ndarray, tol: float = 1e-9
) -> tuple[np.ndarray, np.ndarray]:
    """Merge (near-)repeated rows of X, averaging their targets.

    Two evaluations of the same point (priors fed back in, a proposer
    re-asking a boundary point) put two identical rows in the Gram
    matrix — exactly rank-deficient before noise.  Points within ``tol``
    Euclidean distance (inputs are normalized to [0,1]^d) collapse to
    their first representative with the mean target; N is tens, so the
    O(N²) scan is free next to one objective evaluation."""
    keep: list[int] = []
    groups: list[list[int]] = []
    for i in range(len(X)):
        for gi, k in enumerate(keep):
            if np.sum((X[i] - X[k]) ** 2) <= tol * tol:
                groups[gi].append(i)
                break
        else:
            keep.append(i)
            groups.append([i])
    if len(keep) == len(X):
        return X, y
    return X[keep], np.array([float(np.mean(y[g])) for g in groups])


# Hyperparameter grids for type-II maximum likelihood: inputs are
# normalized to [0,1]^d, so ℓ spans "nearly white" to "nearly flat", and
# targets are standardized, so σ² is relative to unit variance.
_LS_GRID = np.geomspace(0.05, 2.0, 24)
_NOISE_GRID = np.array([1e-6, 1e-4, 1e-2])


class GaussianProcessModel:
    """GP posterior over normalized inputs (the reference's
    ``GaussianProcessModel``): zero mean, Matérn-5/2, observation noise.

    ``length_scale="fit"`` selects the kernel length scale (and the noise
    level) by maximizing the log marginal likelihood over a log-spaced
    grid at each :meth:`fit` — the reference refits its GP kernel the same
    way per search iteration.  The grid is exact enough in 1-D/3-point
    noise space and costs ~70 Cholesky factorizations of a ≤tens-point
    kernel, i.e. nothing next to one real evaluation of the objective."""

    def __init__(self, length_scale: float | str = 0.3, noise: float = 1e-6):
        if not (length_scale == "fit" or isinstance(length_scale, (int, float))):
            raise ValueError(
                f"length_scale must be a float or 'fit', got {length_scale!r}"
            )
        self.length_scale = length_scale
        self.noise = noise
        self._X: Optional[np.ndarray] = None

    def fit(self, X: np.ndarray, y: np.ndarray) -> "GaussianProcessModel":
        # De-duplicate BEFORE standardization: repeated rows make the
        # Gram matrix exactly singular, and the jitter ladder in
        # _chol_lml should be the fallback, not the steady state.
        self._X, y = _deduplicate(np.atleast_2d(X), np.asarray(y, float))
        self._y_mean = float(np.mean(y))
        self._y_std = float(np.std(y)) or 1.0
        self._y = (np.asarray(y, float) - self._y_mean) / self._y_std
        if self.length_scale == "fit":
            best = None
            for ls in _LS_GRID:
                for nz in _NOISE_GRID:
                    L, alpha, lml = _chol_lml(self._X, self._y, ls, nz)
                    if best is None or lml > best[0]:
                        best = (lml, ls, nz, L, alpha)
            _, self.fitted_length_scale, self.fitted_noise, self._L, \
                self._alpha = best
        else:
            self.fitted_length_scale = float(self.length_scale)
            self.fitted_noise = self.noise
            self._L, self._alpha, _ = _chol_lml(
                self._X, self._y, self.fitted_length_scale, self.fitted_noise
            )
        return self

    def predict(self, X: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and standard deviation at X."""
        X = np.atleast_2d(X)
        ls = self.fitted_length_scale
        Ks = _matern52(X, self._X, ls)
        mean = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.maximum(
            _matern52(X, X, ls).diagonal() - np.sum(v**2, 0),
            1e-12,
        )
        return (
            mean * self._y_std + self._y_mean,
            np.sqrt(var) * self._y_std,
        )


def expected_improvement(
    mean: np.ndarray, std: np.ndarray, best: float
) -> np.ndarray:
    """EI for MINIMIZATION: E[max(best - f, 0)]."""
    from scipy.stats import norm

    z = (best - mean) / std
    return (best - mean) * norm.cdf(z) + std * norm.pdf(z)


class GaussianProcessSearch(RandomSearch):
    """Reference: ``GaussianProcessSearch.findWithPriors`` — seed with a few
    random points, then repeatedly fit the GP and evaluate the EI-argmax of
    a candidate pool (SURVEY.md §3.5)."""

    def __init__(
        self,
        bounds: Sequence[tuple[float, float]],
        log_scale: bool | Sequence[bool] = False,
        seed: int = 0,
        n_seed_points: int = 3,
        n_candidates: int = 512,
        length_scale: float | str = "fit",
    ):
        """``length_scale="fit"`` (default) re-selects the kernel length
        scale and noise by marginal likelihood at every GP refit; pass a
        float to pin them (round-2 behavior was a pinned 0.3)."""
        super().__init__(bounds, log_scale, seed)
        self.n_seed_points = n_seed_points
        self.n_candidates = n_candidates
        self.length_scale = length_scale

    def _normalize(self, X: np.ndarray) -> np.ndarray:
        """Map the (possibly log-scaled) box to [0,1]^d for the GP."""
        out = np.empty_like(X, dtype=float)
        for j, (lo, hi) in enumerate(self.bounds):
            if self.log_scale[j]:
                out[:, j] = (np.log(X[:, j]) - np.log(lo)) / (
                    np.log(hi) - np.log(lo)
                )
            else:
                out[:, j] = (X[:, j] - lo) / (hi - lo)
        return out

    def find(
        self,
        evaluate: Callable[[np.ndarray], float],
        n_iterations: int,
        maximize: bool = False,
        priors: Optional[list] = None,
    ) -> SearchResult:
        """``priors`` seeds the GP with already-evaluated (params, value)
        pairs (the reference's findWithPriors — e.g. reuse the previous
        model-selection grid)."""
        sign = -1.0 if maximize else 1.0
        history: list = list(priors) if priors else []

        n_seed = max(0, min(self.n_seed_points - len(history), n_iterations))
        for x in self._sample(n_seed):
            history.append((x, float(evaluate(x))))

        remaining = n_iterations - n_seed
        for _ in range(remaining):
            X_obs = np.array([h[0] for h in history], float)
            y_obs = np.array([sign * h[1] for h in history], float)
            gp = GaussianProcessModel(self.length_scale).fit(
                self._normalize(X_obs), y_obs
            )
            candidates = self._sample(self.n_candidates)
            mean, std = gp.predict(self._normalize(candidates))
            ei = expected_improvement(mean, std, float(np.min(y_obs)))
            x_next = candidates[int(np.argmax(ei))]
            history.append((x_next, float(evaluate(x_next))))

        best = min(history, key=lambda h: sign * h[1])
        return SearchResult(np.asarray(best[0]), best[1], history)
