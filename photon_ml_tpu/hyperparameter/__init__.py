from photon_ml_tpu.hyperparameter.search import (  # noqa: F401
    GaussianProcessSearch,
    RandomSearch,
)
