"""Device-side metric computation.

The host evaluators (evaluation/evaluators.py) pull scores back and compute
in NumPy — fine for validation sets that fit on host, but a 1B-row weighted
AUC sort on host would dominate a validation pass at pod scale (VERDICT
round 1, weak #8).  These are the on-device counterparts:

- pointwise losses (logistic / poisson / squared / rmse): one fused
  weighted reduction, ``psum``-able over a mesh axis — usable INSIDE
  ``shard_map`` on row-sharded scores, so distributed validation costs one
  scalar all-reduce, exactly like a training objective evaluation;
- weighted AUC with tie handling: device ``argsort``-based, bit-matching
  the host evaluator (single-device; a distributed AUC needs a global sort,
  which the reference also does not attempt — its sharded AUC averages
  per-partition AUCs instead, our grouped-AUC analogue).

Parity with the host evaluators is tested to float tolerance in
tests/test_device_metrics.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


@partial(jax.jit, static_argnames=("kind", "axis_name"))
def device_pointwise_metric(
    scores: Array,
    labels: Array,
    weights: Optional[Array] = None,
    kind: str = "logistic_loss",
    axis_name: Optional[str] = None,
) -> Array:
    """Weighted mean pointwise metric on device.

    ``kind``: ``logistic_loss`` | ``poisson_loss`` | ``squared_loss`` |
    ``rmse``.  Zero-weight rows (padding) drop out.  With ``axis_name`` the
    numerator/denominator reduce over that mesh axis (call inside
    ``shard_map`` on row shards).
    """
    scores = scores.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    w = jnp.ones_like(scores) if weights is None else weights.astype(
        jnp.float32
    )
    if kind == "logistic_loss":
        per_row = jnp.logaddexp(0.0, scores) - labels * scores
    elif kind == "poisson_loss":
        per_row = jnp.exp(scores) - labels * scores
    elif kind in ("squared_loss", "rmse"):
        r = scores - labels
        per_row = (0.5 if kind == "squared_loss" else 1.0) * r * r
    else:
        raise ValueError(f"unknown device metric kind {kind!r}")
    num = jnp.sum(w * per_row)
    den = jnp.sum(w)
    if axis_name is not None:
        num, den = lax.psum((num, den), axis_name)
    if kind == "squared_loss":
        return num  # the reference's squared loss is a SUM, not a mean
    out = num / den
    return jnp.sqrt(out) if kind == "rmse" else out


@jax.jit
def device_auc(
    scores: Array, labels: Array, weights: Optional[Array] = None
) -> Array:
    """Weighted AUC with tie averaging on device (single-device sort).

    Same math as the host evaluator: for each tie group, pairs against
    strictly-lower negatives count 1, within-group pairs count ½.
    Zero-weight rows are excluded.  Returns NaN when a class is missing.
    """
    scores = scores.astype(jnp.float64 if jax.config.jax_enable_x64
                           else jnp.float32)
    labels = labels.astype(scores.dtype)
    w = jnp.ones_like(scores) if weights is None else weights.astype(
        scores.dtype
    )
    w = jnp.where(w > 0, w, 0.0)

    order = jnp.argsort(scores, stable=True)
    s = scores[order]
    y = labels[order]
    ws = w[order]
    wp = ws * y
    wn = ws * (1.0 - y)

    pos_w = jnp.sum(wp)
    neg_w = jnp.sum(wn)

    cum_neg = jnp.concatenate([jnp.zeros((1,), wn.dtype), jnp.cumsum(wn)])
    boundaries = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]]
    )
    group_id = jnp.cumsum(boundaries) - 1  # (n,) tie-group index

    # Per-group sums via segment_sum over tie groups (n groups <= n).
    n = s.shape[0]
    group_neg = jax.ops.segment_sum(wn, group_id, num_segments=n)
    # Index of each group's first element → neg weight strictly below it.
    first_idx = jax.ops.segment_min(
        jnp.arange(n), group_id, num_segments=n
    )
    neg_below_group = cum_neg[jnp.where(first_idx > n, 0, first_idx)]
    contrib = wp * (
        neg_below_group[group_id] + 0.5 * group_neg[group_id]
    )
    auc = jnp.sum(contrib) / (pos_w * neg_w)
    return jnp.where(
        jnp.logical_or(pos_w == 0, neg_w == 0), jnp.nan, auc
    )
