"""Device-side metric computation.

The host evaluators (evaluation/evaluators.py) pull scores back and compute
in NumPy — fine for validation sets that fit on host, but a 1B-row weighted
AUC sort on host would dominate a validation pass at pod scale (VERDICT
round 1, weak #8).  These are the on-device counterparts:

- pointwise losses (logistic / poisson / squared / rmse): one fused
  weighted reduction, ``psum``-able over a mesh axis — usable INSIDE
  ``shard_map`` on row-sharded scores, so distributed validation costs one
  scalar all-reduce, exactly like a training objective evaluation;
- weighted AUC with tie handling: device ``argsort``-based, bit-matching
  the host evaluator (single-device; a distributed AUC needs a global sort,
  which the reference also does not attempt — its sharded AUC averages
  per-partition AUCs instead, our grouped-AUC analogue).

Parity with the host evaluators is tested to float tolerance in
tests/test_device_metrics.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array


def _weighted_per_row(scores, labels, weights, kind):
    """Shared per-row loss dispatch for the whole-array metric and the
    streaming partial (one implementation, or streamed-vs-resident metric
    parity drifts on the next numeric fix).  Host evaluators MASK rows
    with w <= 0 before computing; the device analogue zeroes their weight
    AND their per-row term — ``0 * inf`` from an overflowing masked row
    (poisson exp at large margins) must not poison the sum."""
    scores = scores.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    w = jnp.ones_like(scores) if weights is None else weights.astype(
        jnp.float32
    )
    w = jnp.where(w > 0, w, 0.0)
    if kind == "logistic_loss":
        per_row = jnp.logaddexp(0.0, scores) - labels * scores
    elif kind == "poisson_loss":
        per_row = jnp.exp(scores) - labels * scores
    elif kind in ("squared_loss", "rmse"):
        r = scores - labels
        per_row = (0.5 if kind == "squared_loss" else 1.0) * r * r
    else:
        raise ValueError(f"unknown device metric kind {kind!r}")
    return jnp.where(w > 0, w * per_row, 0.0), w


@partial(jax.jit, static_argnames=("kind", "axis_name"))
def device_pointwise_metric(
    scores: Array,
    labels: Array,
    weights: Optional[Array] = None,
    kind: str = "logistic_loss",
    axis_name: Optional[str] = None,
) -> Array:
    """Weighted mean pointwise metric on device.

    ``kind``: ``logistic_loss`` | ``poisson_loss`` | ``squared_loss`` |
    ``rmse``.  Zero-weight rows (padding) drop out.  With ``axis_name`` the
    numerator/denominator reduce over that mesh axis (call inside
    ``shard_map`` on row shards).
    """
    wpr, w = _weighted_per_row(scores, labels, weights, kind)
    num = jnp.sum(wpr)
    den = jnp.sum(w)
    if axis_name is not None:
        num, den = lax.psum((num, den), axis_name)
    if kind == "squared_loss":
        return num  # the reference's squared loss is a SUM, not a mean
    out = num / den
    return jnp.sqrt(out) if kind == "rmse" else out


def device_evaluator_fn(evaluator):
    """Map a HOST evaluator instance to its device counterpart —
    ``callable(scores, labels, weights) → scalar Array`` — or None when no
    device implementation exists (grouped/per-query evaluators,
    precision@k: these need host-side grouping or top-k joins).  The
    estimator / drivers use this to keep validation on device and pull
    back only scalars (VERDICT r4 missing #4).

    GROUPING IS THE CALLER'S GATE: these run the GLOBAL metric; a suite
    with a ``group_column`` (per-query AUC semantics) must stay on the
    host path."""
    name = type(evaluator).__name__
    if name == "AreaUnderROCCurveEvaluator":
        return lambda s, y, w: device_auc(s, y, w)
    kind = pointwise_kind_for(evaluator)
    if kind is None:
        return None
    return lambda s, y, w: device_pointwise_metric(s, y, w, kind=kind)


#: Streaming accumulation for pointwise device metrics: (num, den) pairs
#: add across blocks/chunks, so an out-of-core scoring pass needs no
#: O(n_rows) column retention for the metric — only two scalars.
@partial(jax.jit, static_argnames=("kind",))
def device_pointwise_partial(
    scores: Array,
    labels: Array,
    weights: Optional[Array] = None,
    kind: str = "logistic_loss",
) -> tuple[Array, Array]:
    """One block's (weighted-sum, weight-sum) contribution for ``kind``
    (``finish_pointwise_partial`` turns the running totals into the
    metric).  Same per-row math as ``device_pointwise_metric`` — shared
    via ``_weighted_per_row``."""
    wpr, w = _weighted_per_row(scores, labels, weights, kind)
    return jnp.sum(wpr), jnp.sum(w)


def finish_pointwise_partial(num: float, den: float, kind: str) -> float:
    if kind == "squared_loss":
        return float(num)
    if den == 0:  # zero rows / all-masked: the host path's NaN, not a crash
        return float("nan")
    out = num / den
    return float(np.sqrt(out)) if kind == "rmse" else float(out)


def pointwise_kind_for(evaluator) -> Optional[str]:
    """The streaming-accumulable kind for a host evaluator, or None (AUC
    needs a global sort; precision@k needs per-group top-k)."""
    return {
        "RMSEEvaluator": "rmse",
        "SquaredLossEvaluator": "squared_loss",
        "LogisticLossEvaluator": "logistic_loss",
        "PoissonLossEvaluator": "poisson_loss",
    }.get(type(evaluator).__name__)


@jax.jit
def device_auc(
    scores: Array, labels: Array, weights: Optional[Array] = None
) -> Array:
    """Weighted AUC with tie averaging on device (single-device sort).

    Same math as the host evaluator: for each tie group, pairs against
    strictly-lower negatives count 1, within-group pairs count ½.
    Zero-weight rows are excluded.  Returns NaN when a class is missing.
    """
    scores = scores.astype(jnp.float64 if jax.config.jax_enable_x64
                           else jnp.float32)
    labels = labels.astype(scores.dtype)
    w = jnp.ones_like(scores) if weights is None else weights.astype(
        scores.dtype
    )
    w = jnp.where(w > 0, w, 0.0)

    order = jnp.argsort(scores, stable=True)
    s = scores[order]
    y = labels[order]
    ws = w[order]
    wp = ws * y
    wn = ws * (1.0 - y)

    pos_w = jnp.sum(wp)
    neg_w = jnp.sum(wn)

    cum_neg = jnp.concatenate([jnp.zeros((1,), wn.dtype), jnp.cumsum(wn)])
    boundaries = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]]
    )
    group_id = jnp.cumsum(boundaries) - 1  # (n,) tie-group index

    # Per-group sums via segment_sum over tie groups (n groups <= n).
    n = s.shape[0]
    group_neg = jax.ops.segment_sum(wn, group_id, num_segments=n)
    # Index of each group's first element → neg weight strictly below it.
    first_idx = jax.ops.segment_min(
        jnp.arange(n), group_id, num_segments=n
    )
    neg_below_group = cum_neg[jnp.where(first_idx > n, 0, first_idx)]
    contrib = wp * (
        neg_below_group[group_id] + 0.5 * group_neg[group_id]
    )
    auc = jnp.sum(contrib) / (pos_w * neg_w)
    return jnp.where(
        jnp.logical_or(pos_w == 0, neg_w == 0), jnp.nan, auc
    )
