from photon_ml_tpu.evaluation.evaluators import (  # noqa: F401
    AreaUnderROCCurveEvaluator,
    Evaluator,
    EvaluatorType,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    PrecisionAtKEvaluator,
    RMSEEvaluator,
    SquaredLossEvaluator,
    get_evaluator,
)
