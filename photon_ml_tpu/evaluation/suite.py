"""Evaluation suites: many metrics per validation pass, one for selection.

The analogue of the reference's ``EvaluationSuite`` / ``MultiEvaluator``
(SURVEY.md §2, Evaluation): the reference's drivers take a LIST of evaluator
specs, evaluate all of them per coordinate-descent iteration and per
config-grid point, and select the best model by the FIRST evaluator in the
list.  Here a suite is an ordered name→``Evaluator`` mapping with a
designated primary metric that drives model selection.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    default_evaluator_for_task,
    get_evaluator,
)


@dataclasses.dataclass(frozen=True)
class EvaluationSuite:
    """Ordered collection of named evaluators; ``primary`` drives selection
    (the reference selects by the first configured evaluator)."""

    evaluators: tuple  # tuple[(name, Evaluator), ...] — ordered
    primary: str
    #: id column whose values group rows for per-group evaluators (the
    #: reference's per-query AUC / precision@k "sharded" evaluators); None
    #: evaluates globally.
    group_column: Optional[str] = None

    def __post_init__(self):
        names = [n for n, _ in self.evaluators]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate evaluator names: {names}")
        if self.primary not in names:
            raise ValueError(
                f"primary {self.primary!r} not among evaluators {names}"
            )

    @classmethod
    def from_specs(
        cls,
        specs: Sequence[Union[str, Evaluator]],
        primary: Optional[str] = None,
        group_column: Optional[str] = None,
    ) -> "EvaluationSuite":
        """Build from spec strings (``"auc"``, ``"precision@5"``, ...) or
        ``Evaluator`` instances; primary defaults to the first, as the
        reference's driver does with its evaluator list."""
        pairs = []
        for spec in specs:
            if isinstance(spec, Evaluator):
                pairs.append((type(spec).__name__, spec))
            else:
                pairs.append((str(spec).strip().lower(), get_evaluator(spec)))
        if not pairs:
            raise ValueError("EvaluationSuite requires at least one evaluator")
        return cls(
            evaluators=tuple(pairs),
            primary=primary if primary is not None else pairs[0][0],
            group_column=group_column,
        )

    @classmethod
    def for_task(cls, task: str) -> "EvaluationSuite":
        ev = default_evaluator_for_task(task)
        return cls(evaluators=((type(ev).__name__, ev),), primary=type(ev).__name__)

    @property
    def primary_evaluator(self) -> Evaluator:
        return dict(self.evaluators)[self.primary]

    def evaluate(
        self,
        scores: np.ndarray,
        labels: np.ndarray,
        weights: Optional[np.ndarray] = None,
        group_ids: Optional[np.ndarray] = None,
    ) -> dict:
        """name → metric value, every evaluator on one score pass."""
        with telemetry_mod.current().span(
            "evaluation",
            evaluators=[n for n, _ in self.evaluators],
            grouped=group_ids is not None,
            rows=len(scores),
        ):
            return {
                name: ev.evaluate(scores, labels, weights, group_ids)
                for name, ev in self.evaluators
            }

    def evaluate_primary(
        self,
        scores: np.ndarray,
        labels: np.ndarray,
        weights: Optional[np.ndarray] = None,
        group_ids: Optional[np.ndarray] = None,
    ) -> tuple[float, dict]:
        """(primary metric, full name→value dict) from one score pass.

        The tuning orchestrator's per-rung reporting contract
        (tuning/executor.py): ASHA promotes/kills on the PRIMARY metric
        while the journal's rung reports carry the whole suite, so a
        finished search can be audited on every configured metric, not
        just the one that drove the decisions."""
        values = self.evaluate(scores, labels, weights, group_ids)
        return values[self.primary], values

    def evaluate_device(
        self,
        scores,
        labels,
        weights=None,
        materialize: bool = True,
    ) -> dict:
        """name → metric value with the computation ON DEVICE: scores /
        labels / weights are (possibly sharded) device arrays, and only
        the metric SCALARS cross back to host — the validation-pass
        contract at 1B rows (the reference computes metrics where the
        data lives, Spark-side; SURVEY.md §2 Evaluation row).

        Evaluators with no device implementation (precision@k) fall back
        to the host path with ONE array pullback, shared across all of
        them.  Grouped suites (``group_column`` set) must use
        :meth:`evaluate` — per-group metrics are host-side.

        ``materialize=False`` leaves device-computed metrics as 0-d
        device arrays (no readback here at all, unless a host-fallback
        evaluator forces its pullback) — callers that batch readbacks,
        like the CD loop's history flush, pull them later in one sync.
        """
        if self.group_column is not None:
            raise ValueError(
                "evaluate_device computes GLOBAL metrics; this suite has "
                f"group_column={self.group_column!r} — use evaluate()"
            )
        from photon_ml_tpu.evaluation.device import device_evaluator_fn

        # Span covers DISPATCH wall when materialize=False (device
        # metrics flush later in the CD batched readback — forcing a
        # sync here for timing would defeat that design).
        with telemetry_mod.current().span(
            "evaluation",
            evaluators=[n for n, _ in self.evaluators],
            device=True,
            materialize=materialize,
        ):
            out = {}
            host_pull = None
            for name, ev in self.evaluators:
                fn = device_evaluator_fn(ev)
                if fn is not None:
                    m = fn(scores, labels, weights)
                    out[name] = float(m) if materialize else m
                    continue
                if host_pull is None:
                    host_pull = (
                        np.asarray(scores),
                        np.asarray(labels),
                        None if weights is None else np.asarray(weights),
                    )
                out[name] = ev.evaluate(*host_pull)
            return out

    def better_than(self, a: Optional[float], b: Optional[float]) -> bool:
        """Compare two PRIMARY metric values; None/NaN always loses."""
        if a is None or np.isnan(a):
            return False
        if b is None or np.isnan(b):
            return True
        return self.primary_evaluator.better_than(a, b)
