"""Validation metrics.

The analogue of the reference's ``...ml.evaluation`` package —
``Evaluator`` / ``EvaluatorType`` with AUC, RMSE, logistic loss, Poisson
loss, squared loss, and grouped (sharded) variants such as per-query AUC and
precision@k (SURVEY.md §2, Evaluation).  Evaluators drive model selection
across the regularization grid, so each knows its improvement direction
(``better_than``), exactly as the reference's do.

Host-side NumPy: the reference evaluates via Spark jobs on the cluster;
here scores come back from the device once per validation pass and the
metric itself is cheap.  Rows with ``weight == 0`` (padding) are excluded
everywhere.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import numpy as np


class EvaluatorType(enum.Enum):
    AUC = "auc"
    RMSE = "rmse"
    LOGISTIC_LOSS = "logistic_loss"
    POISSON_LOSS = "poisson_loss"
    SQUARED_LOSS = "squared_loss"
    PRECISION_AT_K = "precision_at_k"


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """Base evaluator; subclasses implement :meth:`_compute` on cleaned
    (nonzero-weight) arrays of scores / labels / weights."""

    #: larger-is-better metrics flip the comparison (reference:
    #: ``Evaluator.betterThan``).
    larger_is_better: bool = dataclasses.field(default=False, init=False)

    def evaluate(
        self,
        scores: np.ndarray,
        labels: np.ndarray,
        weights: Optional[np.ndarray] = None,
        group_ids: Optional[np.ndarray] = None,
    ) -> float:
        scores = np.asarray(scores, np.float64)
        labels = np.asarray(labels, np.float64)
        w = (
            np.ones_like(scores)
            if weights is None
            else np.asarray(weights, np.float64)
        )
        mask = w > 0
        g = None if group_ids is None else np.asarray(group_ids)[mask]
        return float(self._compute(scores[mask], labels[mask], w[mask], g))

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.larger_is_better else a < b

    def _compute(self, scores, labels, weights, group_ids) -> float:
        raise NotImplementedError


def _auc(scores, labels, weights) -> float:
    """Weighted AUC with tie averaging (trapezoidal ROC)."""
    pos_w = np.sum(weights * labels)
    neg_w = np.sum(weights * (1.0 - labels))
    if pos_w == 0 or neg_w == 0:
        return float("nan")
    order = np.argsort(scores, kind="stable")
    s, y, w = scores[order], labels[order], weights[order]
    wp = w * y
    wn = w * (1.0 - y)
    # For each tie group: pairs against strictly-lower negatives count 1,
    # within-group pairs count 1/2.
    cum_neg = np.concatenate([[0.0], np.cumsum(wn)])
    boundaries = np.concatenate([[True], s[1:] != s[:-1]])
    group_id = np.cumsum(boundaries) - 1
    group_start = np.flatnonzero(boundaries)
    neg_below = cum_neg[group_start][group_id]  # neg weight strictly below
    group_neg = np.add.reduceat(wn, group_start)[group_id]
    contrib = wp * (neg_below + 0.5 * group_neg)
    return float(np.sum(contrib) / (pos_w * neg_w))


def _grouped_auc_mean(scores, labels, weights, group_ids) -> float:
    """Unweighted mean of per-group weighted AUCs, fully vectorized.

    One lexsort by (group, score) plus segment reductions replaces the
    per-group Python loop — at 10⁵ per-query groups (MovieLens-scale) the
    loop costs minutes, this costs one sort.  Math per group is identical
    to :func:`_auc` (tie averaging included); groups lacking both classes
    are skipped, as the reference does."""
    if len(scores) == 0:  # all rows masked (e.g. zero weights)
        return float("nan")
    _, gidx = np.unique(group_ids, return_inverse=True)
    order = np.lexsort((scores, gidx))  # group-major, score ascending
    g = gidx[order]
    s = scores[order]
    y = labels[order]
    w = weights[order]
    wp = w * y
    wn = w * (1.0 - y)

    gb = np.concatenate([[True], g[1:] != g[:-1]])      # group starts
    g_start = np.flatnonzero(gb)
    cum_wn = np.concatenate([[0.0], np.cumsum(wn)])     # before each row
    base_wn = cum_wn[g_start]                           # at group start
    row_group = np.cumsum(gb) - 1                       # dense group seq

    # Tie groups are (group, score) runs; negatives strictly below a tie
    # group are group-LOCAL: global prefix minus the group's base.
    tb = np.concatenate([[True], (g[1:] != g[:-1]) | (s[1:] != s[:-1])])
    t_start = np.flatnonzero(tb)
    t_id = np.cumsum(tb) - 1
    neg_below = cum_wn[t_start][t_id] - base_wn[row_group]
    tie_neg = np.add.reduceat(wn, t_start)[t_id]
    contrib = wp * (neg_below + 0.5 * tie_neg)

    contrib_g = np.add.reduceat(contrib, g_start)
    pos_g = np.add.reduceat(wp, g_start)
    neg_g = np.add.reduceat(wn, g_start)
    valid = (pos_g > 0) & (neg_g > 0)
    if not np.any(valid):
        return float("nan")
    return float(np.mean(contrib_g[valid] / (pos_g[valid] * neg_g[valid])))


@dataclasses.dataclass(frozen=True)
class AreaUnderROCCurveEvaluator(Evaluator):
    """AUC; with ``group_ids`` given, the unweighted mean of per-group AUCs
    (the reference's sharded/per-query ``MultiAUC``).  Groups lacking both
    classes are skipped, as the reference does."""

    larger_is_better: bool = dataclasses.field(default=True, init=False)

    def _compute(self, scores, labels, weights, group_ids) -> float:
        if group_ids is None:
            return _auc(scores, labels, weights)
        return _grouped_auc_mean(scores, labels, weights, group_ids)


@dataclasses.dataclass(frozen=True)
class RMSEEvaluator(Evaluator):
    def _compute(self, scores, labels, weights, group_ids) -> float:
        r = scores - labels
        return float(np.sqrt(np.sum(weights * r * r) / np.sum(weights)))


@dataclasses.dataclass(frozen=True)
class SquaredLossEvaluator(Evaluator):
    def _compute(self, scores, labels, weights, group_ids) -> float:
        r = scores - labels
        return float(np.sum(weights * 0.5 * r * r))


@dataclasses.dataclass(frozen=True)
class LogisticLossEvaluator(Evaluator):
    """Mean weighted negative log-likelihood of {0,1} labels given margins."""

    def _compute(self, scores, labels, weights, group_ids) -> float:
        loss = np.logaddexp(0.0, scores) - labels * scores
        return float(np.sum(weights * loss) / np.sum(weights))


@dataclasses.dataclass(frozen=True)
class PoissonLossEvaluator(Evaluator):
    """Mean weighted Poisson NLL (up to the label-only constant) of margins."""

    def _compute(self, scores, labels, weights, group_ids) -> float:
        loss = np.exp(scores) - labels * scores
        return float(np.sum(weights * loss) / np.sum(weights))


@dataclasses.dataclass(frozen=True)
class PrecisionAtKEvaluator(Evaluator):
    """Precision@k within each group, averaged over groups (the reference's
    per-query precision@1/3/5/10 evaluators require a group id column)."""

    k: int = 1
    larger_is_better: bool = dataclasses.field(default=True, init=False)

    def _compute(self, scores, labels, weights, group_ids) -> float:
        if group_ids is None:
            raise ValueError("precision@k requires group_ids (per-query metric)")
        # Vectorized over groups: one lexsort by (group, score desc) and
        # segment reductions (the per-group argsort loop costs minutes at
        # 10⁵ per-query groups).  lexsort is stable, so ties keep original
        # order exactly like the per-group stable argsort did.
        if len(scores) == 0:  # all rows masked (e.g. zero weights)
            return float("nan")
        _, gidx = np.unique(group_ids, return_inverse=True)
        order = np.lexsort((-scores, gidx))
        g = gidx[order]
        y = labels[order]
        gb = np.concatenate([[True], g[1:] != g[:-1]])
        g_start = np.flatnonzero(gb)
        row_group = np.cumsum(gb) - 1
        pos_in_group = np.arange(len(g)) - g_start[row_group]
        sizes = np.diff(np.append(g_start, len(g)))
        k_eff = np.minimum(self.k, sizes)
        in_top = pos_in_group < self.k
        hits_g = np.add.reduceat(
            np.where(in_top, (y > 0).astype(np.float64), 0.0), g_start
        )
        return float(np.mean(hits_g / k_eff))


def get_evaluator(spec: str) -> Evaluator:
    """Parse an evaluator spec string as the reference's CLI does:
    ``AUC``, ``RMSE``, ``LOGISTIC_LOSS``, ``POISSON_LOSS``, ``SQUARED_LOSS``,
    or ``PRECISION@k`` (e.g. ``precision@5``)."""
    key = spec.strip().lower()
    if key.startswith("precision@"):
        return PrecisionAtKEvaluator(k=int(key.split("@", 1)[1]))
    table = {
        "auc": AreaUnderROCCurveEvaluator,
        "rmse": RMSEEvaluator,
        "logistic_loss": LogisticLossEvaluator,
        "logisticloss": LogisticLossEvaluator,
        "poisson_loss": PoissonLossEvaluator,
        "poissonloss": PoissonLossEvaluator,
        "squared_loss": SquaredLossEvaluator,
        "squaredloss": SquaredLossEvaluator,
    }
    if key not in table:
        raise KeyError(f"unknown evaluator {spec!r}; available: {sorted(table)}")
    return table[key]()


def default_evaluator_for_task(task: str) -> Evaluator:
    """Task-type default metric, as the reference's drivers choose."""
    return {
        "logistic": AreaUnderROCCurveEvaluator(),
        "squared": RMSEEvaluator(),
        "poisson": PoissonLossEvaluator(),
        "smoothed_hinge": AreaUnderROCCurveEvaluator(),
    }[task]
