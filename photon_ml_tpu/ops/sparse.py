"""Feature-matrix representations: dense and sparse, TPU-first.

The reference keeps examples as Breeze ``SparseVector``s inside RDD
partitions and runs BLAS dot/axpy per row inside its aggregators
(SURVEY.md §2, "Gradient/HVP aggregators").  TPUs want the opposite layout:
one large, statically-shaped, padded structure per shard that XLA can tile
onto the MXU / VPU.  Two interchangeable representations:

- ``DenseMatrix``: a plain ``(n_rows, n_cols)`` array; margins are a single
  matmul on the MXU.  Right for narrow feature spaces (a1a has 123 features)
  and for the padded per-entity blocks of random-effect solves.

- ``SparseMatrix``: flat COO with a static nnz budget (padding entries carry
  ``value = 0`` and point at row 0 / col 0, so they contribute nothing).
  ``matvec`` is gather + ``segment_sum`` over row ids; ``rmatvec`` (the Xᵀu
  needed for gradients) is gather + ``segment_sum`` over column ids.  Row ids
  are kept sorted so ``indices_are_sorted`` lets XLA lower the row reduction
  efficiently.

Both are registered as pytrees, so they can live inside ``jit``/``shard_map``
programs and be device-put once and reused across optimizer iterations
(the analogue of the reference persisting its RDDs).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["data"],
    meta_fields=[],
)
@dataclasses.dataclass
class DenseMatrix:
    """Dense feature matrix of shape (n_rows, n_cols)."""

    data: Array

    @property
    def shape(self) -> tuple[int, int]:
        return self.data.shape

    @property
    def n_rows(self) -> int:
        return self.data.shape[0]

    @property
    def n_cols(self) -> int:
        return self.data.shape[1]

    def matvec(self, w: Array) -> Array:
        """X @ w → (n_rows,) margins."""
        return self.data @ w

    def rmatvec(self, u: Array) -> Array:
        """Xᵀ @ u → (n_cols,) — the gradient-side reduction."""
        return self.data.T @ u

    def row_sq_matvec(self, v: Array) -> Array:
        """(X ⊙ X) @ v — used for diagonal-Hessian preconditioners."""
        return (self.data * self.data) @ v

    def sq_rmatvec(self, u: Array) -> Array:
        """(X ⊙ X)ᵀ @ u — per-feature squared reductions (Hessian diagonal
        ``diag(XᵀDX) = (X⊙X)ᵀ d``, second moments for summary stats)."""
        return (self.data * self.data).T @ u

    def col_nnz(self, row_mask: Array | None = None) -> Array:
        """Per-feature nonzero counts (summary stats).  ``row_mask`` excludes
        padding / zero-weight rows."""
        nz = self.data != 0
        if row_mask is not None:
            nz = jnp.logical_and(nz, row_mask[:, None])
        return jnp.sum(nz, axis=0)

    def col_min_max(self, row_mask: Array | None = None) -> tuple[Array, Array]:
        """Per-feature (min, max); rows excluded by ``row_mask`` (padding,
        zero-weight) contribute nothing."""
        if row_mask is None:
            return jnp.min(self.data, axis=0), jnp.max(self.data, axis=0)
        m = row_mask[:, None]
        mins = jnp.min(jnp.where(m, self.data, jnp.inf), axis=0)
        maxs = jnp.max(jnp.where(m, self.data, -jnp.inf), axis=0)
        return mins, maxs


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["row_ids", "col_ids", "values"],
    meta_fields=["n_rows", "n_cols"],
)
@dataclasses.dataclass
class SparseMatrix:
    """Flat COO sparse matrix with a static (padded) nnz budget.

    Invariants: ``row_ids`` sorted ascending; padding entries have
    ``values == 0`` (their row/col ids are arbitrary but in-range).
    """

    row_ids: Array  # (nnz,) int32, sorted
    col_ids: Array  # (nnz,) int32
    values: Array  # (nnz,) float
    n_rows: int
    n_cols: int

    @property
    def shape(self) -> tuple[int, int]:
        return (self.n_rows, self.n_cols)

    @property
    def nnz(self) -> int:
        return self.values.shape[0]

    def matvec(self, w: Array) -> Array:
        contrib = self.values * jnp.take(w, self.col_ids)
        return jax.ops.segment_sum(
            contrib, self.row_ids, num_segments=self.n_rows, indices_are_sorted=True
        )

    def rmatvec(self, u: Array) -> Array:
        contrib = self.values * jnp.take(u, self.row_ids)
        return jax.ops.segment_sum(contrib, self.col_ids, num_segments=self.n_cols)

    def row_sq_matvec(self, v: Array) -> Array:
        contrib = self.values * self.values * jnp.take(v, self.col_ids)
        return jax.ops.segment_sum(
            contrib, self.row_ids, num_segments=self.n_rows, indices_are_sorted=True
        )

    def sq_rmatvec(self, u: Array) -> Array:
        """(X ⊙ X)ᵀ @ u — per-feature squared reductions."""
        contrib = self.values * self.values * jnp.take(u, self.row_ids)
        return jax.ops.segment_sum(contrib, self.col_ids, num_segments=self.n_cols)

    def _live_entries(self, row_mask: Array | None) -> Array:
        """Entries that represent a real stored value: nonzero (padding
        entries carry value 0) and, with ``row_mask``, in a live row."""
        live = self.values != 0
        if row_mask is not None:
            live = jnp.logical_and(live, jnp.take(row_mask, self.row_ids))
        return live

    def col_nnz(self, row_mask: Array | None = None) -> Array:
        """Per-feature nonzero counts.  ``row_mask`` excludes padding /
        zero-weight rows."""
        return jax.ops.segment_sum(
            self._live_entries(row_mask).astype(jnp.int32),
            self.col_ids,
            num_segments=self.n_cols,
        )

    def col_min_max(self, row_mask: Array | None = None) -> tuple[Array, Array]:
        """Per-feature (min, max) over stored entries of live rows, folded
        with the implicit zeros of unstored entries (a column with fewer
        stored values than live rows necessarily contains a zero)."""
        live = self._live_entries(row_mask)
        nnz = jax.ops.segment_sum(
            live.astype(jnp.int32), self.col_ids, num_segments=self.n_cols
        )
        n_live_rows = (
            self.n_rows
            if row_mask is None
            else jnp.sum(row_mask.astype(jnp.int32))
        )
        has_zero = nnz < n_live_rows
        # Non-live entries are neutralized to ±inf so they can't pollute the
        # column they point at; the has_zero fold restores the 0 that
        # zero-valued entries represent (and repairs empty segments).
        vals_min = jnp.where(live, self.values, jnp.inf)
        vals_max = jnp.where(live, self.values, -jnp.inf)
        mins = jax.ops.segment_min(vals_min, self.col_ids, num_segments=self.n_cols)
        maxs = jax.ops.segment_max(vals_max, self.col_ids, num_segments=self.n_cols)
        mins = jnp.where(has_zero, jnp.minimum(mins, 0.0), mins)
        maxs = jnp.where(has_zero, jnp.maximum(maxs, 0.0), maxs)
        return mins, maxs

    def to_dense(self) -> DenseMatrix:
        dense = jnp.zeros(self.shape, dtype=self.values.dtype)
        dense = dense.at[self.row_ids, self.col_ids].add(self.values)
        return DenseMatrix(dense)


FeatureMatrix = Union[DenseMatrix, SparseMatrix]


def from_scipy_csr(csr, pad_nnz: int | None = None, dtype=jnp.float32) -> SparseMatrix:
    """Build a SparseMatrix from a scipy CSR matrix, padding nnz to a static budget."""
    csr = csr.tocsr()
    csr.sum_duplicates()
    coo = csr.tocoo()
    return from_coo(
        coo.row, coo.col, coo.data, csr.shape[0], csr.shape[1], pad_nnz, dtype
    )


def canonicalize_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    pad_nnz: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side COO canonicalization shared by the device and Pallas
    builders: dedup duplicate (row, col) entries by summing, sort by row,
    pad nnz to the requested budget.  Returns numpy (rows i32, cols i32,
    vals)."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    # Canonicalize: duplicate coordinates must be summed, or row_sq_matvec
    # (which squares per-entry values) diverges from the dense equivalent.
    # One stable (radix) argsort of the combined key orders by (row, col);
    # the np.unique(return_inverse) + scatter-add formulation this
    # replaces cost ~2x at 33M entries, paid even with zero duplicates.
    keys = rows.astype(np.int64) * np.int64(n_cols) + cols.astype(np.int64)
    order = np.argsort(keys, kind="stable")
    keys = keys[order]
    rows = rows[order].astype(np.int32)
    cols = cols[order].astype(np.int32)
    vals = vals[order]
    if keys.size > 1 and bool(np.any(keys[1:] == keys[:-1])):
        change = np.empty(keys.size, dtype=bool)
        change[0] = True
        np.not_equal(keys[1:], keys[:-1], out=change[1:])
        starts = np.flatnonzero(change)
        vals = np.add.reduceat(vals, starts)
        rows = rows[starts]
        cols = cols[starts]
    budget = pad_nnz if pad_nnz is not None else rows.shape[0]
    return pad_coo_triples(rows, cols, vals, budget)


def pad_coo_triples(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, budget: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad already-canonical (row-sorted) COO triples to a static nnz
    budget.  THE padding invariant, shared by every builder (device COO,
    Pallas spill, streaming chunk stores): pad entries carry value 0 and
    the LAST row id, so the sorted-rows invariant holds and the entries
    are numerically inert."""
    nnz = rows.shape[0]
    if budget < nnz:
        raise ValueError(f"pad_nnz={budget} < actual nnz={nnz}")
    pad = budget - nnz
    if pad:
        pad_row = rows[-1] if nnz else 0
        rows = np.concatenate([rows, np.full(pad, pad_row, np.int32)])
        cols = np.concatenate([cols, np.zeros(pad, np.int32)])
        vals = np.concatenate([vals, np.zeros(pad, vals.dtype)])
    return rows, cols, vals


def from_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    pad_nnz: int | None = None,
    dtype=jnp.float32,
) -> SparseMatrix:
    """Build a SparseMatrix from host COO triples (dedups duplicate (row, col)
    entries by summing, sorts by row, pads nnz)."""
    rows, cols, vals = canonicalize_coo(
        rows, cols, vals, n_rows, n_cols, pad_nnz
    )
    return SparseMatrix(
        row_ids=jnp.asarray(rows),
        col_ids=jnp.asarray(cols),
        values=jnp.asarray(vals, dtype=dtype),
        n_rows=int(n_rows),
        n_cols=int(n_cols),
    )
