"""Pointwise GLM loss functions.

The analogue of the reference's ``com.linkedin.photon.ml.function`` pointwise
losses (``LogisticLossFunction``, ``SquaredLossFunction``,
``PoissonLossFunction``, ``SmoothedHingeLossFunction`` — SURVEY.md §2): each
loss exposes the per-example value and its first and second derivatives with
respect to the *margin* ``m = <w, x> + offset``.

Why margin derivatives rather than raw autodiff on the objective: the full
gradient and Hessian-vector product of a GLM objective factor as

    grad   = Xᵀ (weight ⊙ d1(m, y))
    H @ v  = Xᵀ (weight ⊙ d2(m, y) ⊙ (X @ v))

so with d1/d2 available the hot loop is two (sparse) matvecs — exactly the
structure the reference's ``ValueAndGradientAggregator`` /
``HessianVectorAggregator`` exploit per-partition, and the structure XLA
fuses best on TPU (elementwise ops fused into the matmul epilogue).  Closed
forms also avoid materializing autodiff residuals for billions of rows.

All functions are pure, elementwise, and safe under ``jit`` / ``vmap`` /
``grad``.  Labels follow the reference's conventions: ``{0, 1}`` for logistic
and smoothed hinge (hinge converts internally to ±1), nonnegative counts for
Poisson, reals for squared loss.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss ℓ(m, y) with derivatives taken w.r.t. the margin m.

    Attributes:
      name: stable identifier used by configs and model metadata.
      value: ℓ(m, y) per example.
      d1: ∂ℓ/∂m per example.
      d2: ∂²ℓ/∂m² per example (nonnegative for convex losses).
      mean_fn: the inverse-link / mean function used at scoring time
        (e.g. sigmoid for logistic, exp for Poisson, identity for linear).
    """

    name: str
    value: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    mean_fn: Callable[[Array], Array]

    def value_d1(self, margin: Array, label: Array) -> tuple[Array, Array]:
        return self.value(margin, label), self.d1(margin, label)


# --------------------------------------------------------------------------
# Logistic loss (binary labels in {0, 1}).
#   ℓ(m, y) = softplus(m) - y·m        (= -log p(y|m), numerically stable)
#   ∂ℓ/∂m   = σ(m) - y
#   ∂²ℓ/∂m² = σ(m)(1 - σ(m))
# --------------------------------------------------------------------------

def _logistic_value(margin: Array, label: Array) -> Array:
    return jax.nn.softplus(margin) - label * margin


def _logistic_d1(margin: Array, label: Array) -> Array:
    return jax.nn.sigmoid(margin) - label


def _logistic_d2(margin: Array, label: Array) -> Array:
    p = jax.nn.sigmoid(margin)
    return p * (1.0 - p)


logistic = PointwiseLoss(
    name="logistic",
    value=_logistic_value,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean_fn=jax.nn.sigmoid,
)


# --------------------------------------------------------------------------
# Squared loss (linear regression).
#   ℓ(m, y) = ½(m - y)²
# --------------------------------------------------------------------------

def _squared_value(margin: Array, label: Array) -> Array:
    r = margin - label
    return 0.5 * r * r


def _squared_d1(margin: Array, label: Array) -> Array:
    return margin - label


def _squared_d2(margin: Array, label: Array) -> Array:
    return jnp.ones_like(margin)


squared = PointwiseLoss(
    name="squared",
    value=_squared_value,
    d1=_squared_d1,
    d2=_squared_d2,
    mean_fn=lambda m: m,
)


# --------------------------------------------------------------------------
# Poisson loss (count labels y ≥ 0, log link).
#   ℓ(m, y) = exp(m) - y·m            (negative log-likelihood up to const)
# --------------------------------------------------------------------------

def _poisson_value(margin: Array, label: Array) -> Array:
    return jnp.exp(margin) - label * margin


def _poisson_d1(margin: Array, label: Array) -> Array:
    return jnp.exp(margin) - label


def _poisson_d2(margin: Array, label: Array) -> Array:
    return jnp.exp(margin)


poisson = PointwiseLoss(
    name="poisson",
    value=_poisson_value,
    d1=_poisson_d1,
    d2=_poisson_d2,
    mean_fn=jnp.exp,
)


# --------------------------------------------------------------------------
# Smoothed hinge loss (binary labels in {0, 1}, converted to ±1).
# Piecewise-quadratic smoothing of the hinge (Rennie's smooth hinge), as in
# the reference's SmoothedHingeLossFunction:
#   with z = ŷ·m, ŷ ∈ {-1, +1}:
#     ℓ = ½ - z        if z ≤ 0
#     ℓ = ½(1 - z)²    if 0 < z < 1
#     ℓ = 0            if z ≥ 1
# C¹ everywhere; ∂²ℓ/∂m² is the indicator of the quadratic region (the
# generalized Hessian used by the reference's TwiceDiff variant).
# --------------------------------------------------------------------------

def _hinge_sign(label: Array) -> Array:
    return 2.0 * label - 1.0


def _smoothed_hinge_value(margin: Array, label: Array) -> Array:
    z = _hinge_sign(label) * margin
    return jnp.where(z <= 0.0, 0.5 - z, jnp.where(z < 1.0, 0.5 * (1.0 - z) ** 2, 0.0))


def _smoothed_hinge_d1(margin: Array, label: Array) -> Array:
    s = _hinge_sign(label)
    z = s * margin
    dz = jnp.where(z <= 0.0, -1.0, jnp.where(z < 1.0, z - 1.0, 0.0))
    return s * dz


def _smoothed_hinge_d2(margin: Array, label: Array) -> Array:
    z = _hinge_sign(label) * margin
    return jnp.where((z > 0.0) & (z < 1.0), 1.0, 0.0)


smoothed_hinge = PointwiseLoss(
    name="smoothed_hinge",
    value=_smoothed_hinge_value,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    mean_fn=lambda m: m,
)


_REGISTRY: dict[str, PointwiseLoss] = {
    loss.name: loss for loss in (logistic, squared, poisson, smoothed_hinge)
}

# Task-type aliases mirroring the reference's TaskType enum
# (LOGISTIC_REGRESSION, LINEAR_REGRESSION, POISSON_REGRESSION,
#  SMOOTHED_HINGE_LOSS_LINEAR_SVM).
_ALIASES = {
    "logistic_regression": "logistic",
    "linear_regression": "squared",
    "linear": "squared",
    "poisson_regression": "poisson",
    "smoothed_hinge_loss_linear_svm": "smoothed_hinge",
    "hinge": "smoothed_hinge",
}


def get(name: str) -> PointwiseLoss:
    """Look up a loss by name or task-type alias (case-insensitive)."""
    key = name.lower()
    key = _ALIASES.get(key, key)
    if key not in _REGISTRY:
        raise KeyError(
            f"unknown loss {name!r}; available: {sorted(_REGISTRY)} "
            f"(aliases: {sorted(_ALIASES)})"
        )
    return _REGISTRY[key]
