from photon_ml_tpu.ops import losses  # noqa: F401
from photon_ml_tpu.ops import sparse  # noqa: F401
