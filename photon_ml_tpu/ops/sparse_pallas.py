"""TPU-native sparse feature matrix: tiled Pallas kernels for the GLM hot loop.

This is the framework's BLAS-layer replacement (SURVEY.md §2: "the
performance-critical kernels to write are Pallas/XLA kernels (sparse matvec,
segment reductions)") — the analogue of the reference's netlib/Breeze BLAS
under its ``ValueAndGradientAggregator`` hot loop.

Why not XLA gather/scatter: on TPU, ``jnp.take`` on a 33M-element index set
runs at ~0.1 G elem/s (measured on v5e — effectively a scalar loop), and
``segment_sum`` lowers to scatter, which is as bad.  The whole 1B-row epoch
metric dies there.  Mosaic's only fast data-movement primitive is
``tpu.dynamic_gather`` on a single 128-lane vreg: each sublane of an
``(A, 128)`` operand is an independent 128-wide lookup table.

The kernel design exploits exactly that:

- The matrix is cut into ``TILE_R x TILE_C = 2048 x 2048`` tiles; each tile's
  entries are placed, ON HOST at build time, into a dense slot grid
  ``(A, 128)`` where

  * ``lane  = row % 128``                      (matvec orientation "F")
  * ``sublane group = (col % 2048) // 128``    — the entry's 128-wide
    column *window*, so every sublane needs ONE 128-wide slice of ``w``
    as its gather table;
  * ``depth`` slots absorb collisions; overflow spills to a tiny COO tail.

- matvec per tile: ONE ``dynamic_gather`` of the whole ``(A, 128)`` block
  against per-sublane tables built with ``pltpu.repeat`` from the 16 column
  windows, then a 16-step masked sweep accumulates rows into the
  ``(16, 128)`` margin block (``rhi = (row % 2048) // 128`` selects the
  output sublane).  No scatter anywhere.

- rmatvec (the gradient side, Xᵀu) is the SAME kernel with roles mirrored
  (orientation "B": lane = col % 128, tables = 128-wide windows of ``u``,
  sweep over column-his).  Both directions therefore run at the same rate —
  the property Spark's treeAggregate had for free and TPUs do not.

Measured on one v5e chip (1M rows x 8192 features, 32 nnz/row): ~40x the
pure-XLA COO path for the fused objective; see bench.py / ops/README.md.

Precision: everything is f32 on the VPU — bit-comparable to the COO path
(only summation ORDER differs).  No bf16 shortcuts in the value path.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.sparse import (
    DenseMatrix,
    SparseMatrix,
    canonicalize_coo,
    from_coo,
)

Array = jax.Array

# Tile edge: experimentally tunable (PHOTON_PALLAS_TILE); the per-tile
# output sweep costs WINS = TILE/128 masked passes over the slot grid, so
# smaller tiles trade DMA granularity for sweep work.  2048 measured best
# on v5e for the bench workload; see ops/README.md.
TILE_R = int(os.environ.get("PHOTON_PALLAS_TILE", "2048"))
if TILE_R < 128 or TILE_R % 128 or TILE_R > 32768:
    # Upper bound: the packed per-slot code ohi*128 + lo spans [0, TILE_R)
    # and must fit int16.
    raise ValueError(
        f"PHOTON_PALLAS_TILE must be a multiple of 128 in [128, 32768] "
        f"(packed int16 slot codes), got {TILE_R}"
    )
TILE_C = TILE_R
WIN = 128           # window width = lanes per vreg
WINS = TILE_R // WIN  # windows per tile side
# Per-grid-step DMA budget for the tile kernel (bytes); 4 MiB measured best
# on v5e (2/8/16 MiB all slower — see ops/README.md).
DMA_BUDGET = int(os.environ.get("PHOTON_PALLAS_BUDGET", 4 << 20))
if DMA_BUDGET <= 0:
    raise ValueError(
        f"PHOTON_PALLAS_BUDGET must be a positive byte count, got "
        f"{DMA_BUDGET}"
    )


def _interpret() -> bool:
    """Run kernels in interpreter mode (CPU tests set this env var)."""
    return os.environ.get("PHOTON_PALLAS_INTERPRET", "") == "1"


def pallas_available() -> bool:
    """True when the Pallas sparse path can run here (TPU, or interpret)."""
    return jax.default_backend() == "tpu" or _interpret()


# ---------------------------------------------------------------------------
# Host-side layout build
# ---------------------------------------------------------------------------


def _build_orientation(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    nbr: int,
    nbc: int,
    depth_cap: int,
    spill_cost_ratio: float = 1024.0,
):
    """Place entries into the (tile, sublane, lane) slot grid.

    Orientation F (matvec): ``rows`` are the lane/output side, ``cols`` the
    gather side.  Call with rows/cols swapped (and nbr/nbc swapped) for
    orientation B.  Returns (lo, val, ohi, spill_mask, depth).

    lo   (NT, A, 128) int32 — gather-side low 7 bits (index into the table)
    val  (NT, A, 128) f32   — entry values (0 in empty slots)
    ohi  (NT, A, 128) int32 — output window id within the tile, in [0, 16)

    Depth selection is COST-based, not worst-cell-based: each depth level
    costs one full (tiles × WINS × 128) kernel sweep, while each spilled
    entry costs ~``spill_cost_ratio`` slot-equivalents on the XLA
    gather/segment_sum path (measured ~1000x per entry on v5e: ~60 ns
    per spilled entry vs ~0.06 ns per kernel slot).  The
    chosen depth minimizes the modeled total, so a lone overloaded cell
    spills instead of inflating every tile to the cap, while near-full
    occupancy keeps everything tiled (spilling 0.5% to shave a few depth
    levels is a measured net LOSS).  ``spill_cost_ratio=inf`` forces full
    coverage (used for the post-spill rebuild).
    """
    tr = rows // TILE_R
    tc = cols // TILE_C
    tile = tr * nbc + tc
    lane = rows % WIN
    gwin = (cols % TILE_C) // WIN       # gather window within tile [0,16)
    glo = cols % WIN                    # index into that window's table
    ohi = (rows % TILE_R) // WIN        # output window within tile [0,16)

    # Depth position within each (tile, gather-window, lane) cell.  One
    # combined int64 sort key (≈2-3x faster than a 3-key lexsort at 33M
    # entries); tile/gwin/lane recover from the key by div/mod.
    key = (tile * np.int64(WINS) + gwin) * np.int64(WIN) + lane
    order = np.argsort(key)
    cell = key[order]
    t_s = cell // (WINS * WIN)
    g_s = (cell // WIN) % WINS
    l_s = cell % WIN
    if len(cell) == 0:  # all-zero / empty matrix: one empty depth level
        return (
            np.zeros((nbr, nbc, WINS, WIN), np.int16),
            np.zeros((nbr, nbc, WINS, WIN), np.float32),
            np.empty(0, np.intp),
            1,
        )
    # run-length position within equal consecutive cells
    change = np.empty(len(cell), dtype=bool)
    change[0] = True
    np.not_equal(cell[1:], cell[:-1], out=change[1:])
    run_starts = np.flatnonzero(change)
    run_ids = np.cumsum(change) - 1
    depth_pos = np.arange(len(cell)) - run_starts[run_ids]

    # Cost model over candidate depths d (covering depth_pos < d):
    #   cost(d) = d · (tiles · WINS · WIN)  +  spill_cost_ratio · spilled(d)
    hist = np.bincount(depth_pos)
    cum = np.cumsum(hist)
    spilled_at = len(depth_pos) - cum  # spilled(d) for d = 1..len(hist)
    if np.isinf(spill_cost_ratio):
        needed = len(hist)
    else:
        level_cost = float(nbr * nbc * WINS * WIN)
        # Any nonzero spill also pays a FIXED cost (the XLA scatter's
        # latency floor, measured ~milliseconds — worth ~16 depth levels):
        # spilling a handful of entries to shave one or two levels always
        # loses; spilling to avoid a 100-deep pathological cell wins.
        cost = (
            np.arange(1, len(hist) + 1, dtype=np.float64) * level_cost
            + spill_cost_ratio * spilled_at
            + 16.0 * level_cost * (spilled_at > 0)
        )
        needed = int(np.argmin(cost)) + 1
    depth = min(max(needed, 1), depth_cap)
    keep = depth_pos < depth

    nt = nbr * nbc
    a = WINS * depth
    # Packed per-slot code: ohi*128 + lo (11 bits) -> int16 halves the DMA
    # for index data relative to two int32 planes.
    code = np.zeros((nt, a, WIN), np.int16)
    val = np.zeros((nt, a, WIN), np.float32)

    # sublane = depth * WINS + gwin  (tile-repeat table order: the in-kernel
    # pltpu.repeat produces tables [w0..w15, w0..w15, ...])
    sub = depth_pos[keep] * WINS + g_s[keep]
    kt = t_s[keep]
    kl = l_s[keep]
    code[kt, sub, kl] = (ohi[order][keep] * WIN + glo[order][keep]).astype(
        np.int16)
    val[kt, sub, kl] = vals[order][keep]

    spill_idx = order[~keep]            # indices into original entry arrays
    return (code.reshape(nbr, nbc, a, WIN), val.reshape(nbr, nbc, a, WIN),
            spill_idx, depth)


# ---------------------------------------------------------------------------
# The tile kernel (shared by both directions)
# ---------------------------------------------------------------------------


def _tile_kernel(code_ref, val_ref, tab_ref, out_ref, *, depth, square,
                 batch, chunk):
    """A (batch x chunk) rectangle of tiles per grid step.

    Batching many tiles per step keeps DMAs large (MBs, not hundreds of KB)
    so the stream stays bandwidth-bound instead of per-step-overhead-bound
    (measured: 2048 one-tile steps cost ~5 us each — more than the data).

    code: (batch, chunk, A, 128) int16 packed (ohi*128 + lo)
    val:  (batch, chunk, A, 128) f32
    tab:  (chunk, WINS, 128) gather-side vector windows for this chunk
    out:  (batch, WINS, 128), accumulated across the chunked grid dim
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    def tile_body(t, _):
        b = t // chunk
        j = t % chunk
        code = code_ref[b, j].astype(jnp.int32)
        lo = code & (WIN - 1)
        ohi = code >> 7
        tables = pltpu.repeat(tab_ref[j], depth, axis=0)      # (A, 128)
        g = jnp.take_along_axis(tables, lo, axis=1)           # (A, 128)
        v = val_ref[b, j]
        if square:
            contrib = v * v * g
        else:
            contrib = v * g

        def h_body(h, _):
            part = jnp.sum(jnp.where(ohi == h, contrib, 0.0), axis=0)
            out_ref[b, pl.ds(h, 1), :] += part.reshape(1, WIN)
            return 0

        jax.lax.fori_loop(0, WINS, h_body, 0)
        return 0

    jax.lax.fori_loop(0, batch * chunk, tile_body, 0)


def _pick_rect(nbo: int, nbg: int, a: int,
               budget: int = None) -> tuple[int, int]:
    """(batch, chunk) tiles per grid step fitting ~``budget`` input bytes."""
    if budget is None:
        budget = DMA_BUDGET
    per_tile = a * WIN * 6  # int16 code + f32 val
    cap = max(1, budget // per_tile)

    def largest_divisor_leq(n, m):
        d = min(n, m)
        while n % d:
            d -= 1
        return d

    chunk = largest_divisor_leq(nbg, cap)
    batch = largest_divisor_leq(nbo, max(1, cap // chunk))
    return batch, chunk


@functools.partial(jax.jit, static_argnames=("depth", "nbo", "nbg", "square"))
def _tiled_apply(code, val, vec_padded, *, depth, nbo, nbg, square):
    """out[i] = sum over entries (i, j, v) of v * vec[j] (+ optional v²).

    ``code``/``val``: (nbo, nbg, A, 128); ``vec_padded``: (nbg * TILE_C,).
    Returns (nbo * TILE_R,) output.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    a = WINS * depth
    batch, chunk = _pick_rect(nbo, nbg, a)
    tab = vec_padded.reshape(nbg, WINS, WIN)
    kernel = functools.partial(_tile_kernel, depth=depth, square=square,
                               batch=batch, chunk=chunk)
    out = pl.pallas_call(
        kernel,
        grid=(nbo // batch, nbg // chunk),
        out_shape=jax.ShapeDtypeStruct((nbo, WINS, WIN), jnp.float32),
        in_specs=[
            pl.BlockSpec((batch, chunk, a, WIN), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((batch, chunk, a, WIN), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, WINS, WIN), lambda i, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((batch, WINS, WIN), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(code, val, tab)
    # out[i, h, l] = output element i*TILE_R + h*128 + l
    return out.reshape(nbo * TILE_R)


# ---------------------------------------------------------------------------
# Public matrix type
# ---------------------------------------------------------------------------


class HostCoo:
    """Host-side canonical COO triples for COLD paths (stats, min/max,
    densify) — one-shot per job, so they run in numpy on the host instead of
    keeping a full device COO copy alive (at 33M nnz that copy cost ~670 MB
    of HBM and ~14 s of transfer over this transport for ops the hot loop
    never touches).

    Lives in a pytree META field, never traced, never transferred.
    Equality/hash use the (n_rows, n_cols, nnz) shape class — NOT content —
    so rebuilding a same-shaped matrix (tuning / down-sampling loops) keeps
    hitting existing jit caches exactly as the all-int metadata did.  Two
    consequences, both documented invariants:

    - cold ops must be called EAGERLY (outside jit), as the drivers do —
      under tracing their results would be baked as constants keyed by the
      shape class, which is wrong across different matrices (the main
      consumer, stats.summarize, passes a row_mask whose np.asarray raises
      on tracers, failing loudly);
    - a jit cache entry for a given shape class keeps that first holder's
      host arrays alive until the compiled function is dropped (bounded by
      distinct shape classes, not by rebuild count).
    """

    __slots__ = ("rows", "cols", "vals", "n_rows", "n_cols")

    def __eq__(self, other):
        return (
            isinstance(other, HostCoo)
            and self.n_rows == other.n_rows
            and self.n_cols == other.n_cols
            and self.nnz == other.nnz
        )

    def __hash__(self):
        return hash((self.n_rows, self.n_cols, self.nnz))

    def __init__(self, rows, cols, vals, n_rows, n_cols):
        self.rows = rows
        self.cols = cols
        self.vals = vals
        self.n_rows = n_rows
        self.n_cols = n_cols

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def _live(self, row_mask):
        live = self.vals != 0
        if row_mask is not None:
            live &= np.asarray(row_mask)[self.rows]
        return live

    def col_nnz(self, row_mask=None):
        live = self._live(row_mask)
        return jnp.asarray(
            np.bincount(
                self.cols[live], minlength=self.n_cols
            ).astype(np.int32)
        )

    def col_min_max(self, row_mask=None):
        """Per-feature (min, max) over stored entries of live rows, folded
        with the implicit zeros of unstored entries — same semantics as
        SparseMatrix.col_min_max."""
        live = self._live(row_mask)
        c = self.cols[live]
        v = self.vals[live]
        mins = np.full(self.n_cols, np.inf, np.float32)
        maxs = np.full(self.n_cols, -np.inf, np.float32)
        np.minimum.at(mins, c, v)
        np.maximum.at(maxs, c, v)
        nnz = np.bincount(c, minlength=self.n_cols)
        n_live_rows = (
            self.n_rows if row_mask is None
            else int(np.sum(np.asarray(row_mask)))
        )
        has_zero = nnz < n_live_rows
        mins = np.where(has_zero, np.minimum(mins, 0.0), mins)
        maxs = np.where(has_zero, np.maximum(maxs, 0.0), maxs)
        return jnp.asarray(mins), jnp.asarray(maxs)

    def to_dense(self):
        dense = np.zeros((self.n_rows, self.n_cols), np.float32)
        np.add.at(dense, (self.rows, self.cols), self.vals)
        return DenseMatrix(jnp.asarray(dense))


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "f_code", "f_val",
        "b_code", "b_val",
        "spill",
        "dense_cols", "dense_col_ids",
        "dense_rows", "dense_row_ids",
    ],
    meta_fields=[
        "host_coo",
        "n_rows", "n_cols", "nbr", "nbc", "depth_f", "depth_b",
        "has_dense_cols", "has_dense_rows",
    ],
)
@dataclasses.dataclass
class PallasSparseMatrix:
    """Sparse feature matrix backed by the tiled Pallas layout.

    Drop-in for :class:`photon_ml_tpu.ops.sparse.SparseMatrix` in the GLM
    hot loop (matvec / rmatvec / squared variants).  Three complementary
    storage classes, split at build time:

    - **tiled slot grids** — the bulk of the entries, Pallas-kernel fast;
    - **dense stripes** — ultra-dense columns/rows (an explicit bias column,
      a few very popular features) extracted into small dense blocks that
      ride plain MXU matmuls: they would otherwise overload their slot
      cells and drag the whole layout's depth up;
    - **compact spill** — the residual overflow past the cost-model depth,
      a COO matrix holding ONLY the spilled entries (cost scales with
      spill size, not total nnz).

    Statistics and other cold paths run host-side over ``host_coo`` (the
    canonical triples; a META field — see its docstring for the eager-only
    contract).
    """

    # orientation F (matvec): lane = row%128, tables = w windows
    f_code: Array
    f_val: Array
    # orientation B (rmatvec): lane = col%128, tables = u windows
    b_code: Array
    b_val: Array
    # compact spill matrix (hot-path overflow past the chosen depth)
    spill: "SpillData"
    # ultra-dense stripes (minor dim = the long axis, so XLA's physical
    # tiling pads 8 sublanes, not 128 lanes per stripe; placeholder arrays
    # when absent — see has_* flags)
    dense_cols: Array      # (kc, n_rows) f32 — TRANSPOSED stripe storage
    dense_col_ids: Array   # (kc,) int32 — global column of each stripe
    dense_rows: Array      # (kr, n_cols) f32
    dense_row_ids: Array   # (kr,) int32 — global row of each stripe
    host_coo: HostCoo      # META: host triples for cold paths (never traced)
    n_rows: int
    n_cols: int
    nbr: int
    nbc: int
    depth_f: int
    depth_b: int
    has_dense_cols: bool
    has_dense_rows: bool

    # -- shape protocol ----------------------------------------------------
    @property
    def shape(self) -> tuple[int, int]:
        return (self.n_rows, self.n_cols)

    @property
    def nnz(self) -> int:
        return self.host_coo.nnz

    def _pad_cols(self, w: Array) -> Array:
        target = self.nbc * TILE_C
        return jnp.pad(w, (0, target - self.n_cols))

    def _pad_rows(self, u: Array) -> Array:
        target = self.nbr * TILE_R
        return jnp.pad(u, (0, target - self.n_rows))

    # -- hot paths ---------------------------------------------------------
    def matvec(self, w: Array) -> Array:
        out = _tiled_apply(
            self.f_code, self.f_val, self._pad_cols(w),
            depth=self.depth_f, nbo=self.nbr, nbg=self.nbc, square=False,
        )[: self.n_rows]
        out = out + self.spill.matvec(w)
        if self.has_dense_cols:
            out = out + jnp.einsum(
                "k,kn->n", w[self.dense_col_ids], self.dense_cols)
        if self.has_dense_rows:
            out = out.at[self.dense_row_ids].add(self.dense_rows @ w)
        return out

    def rmatvec(self, u: Array) -> Array:
        out = _tiled_apply(
            self.b_code, self.b_val, self._pad_rows(u),
            depth=self.depth_b, nbo=self.nbc, nbg=self.nbr, square=False,
        )[: self.n_cols]
        out = out + self.spill.rmatvec(u)
        if self.has_dense_cols:
            out = out.at[self.dense_col_ids].add(self.dense_cols @ u)
        if self.has_dense_rows:
            out = out + jnp.einsum(
                "k,kn->n", u[self.dense_row_ids], self.dense_rows)
        return out

    def row_sq_matvec(self, v: Array) -> Array:
        out = _tiled_apply(
            self.f_code, self.f_val, self._pad_cols(v),
            depth=self.depth_f, nbo=self.nbr, nbg=self.nbc, square=True,
        )[: self.n_rows]
        out = out + self.spill.row_sq_matvec(v)
        if self.has_dense_cols:
            out = out + jnp.einsum(
                "k,kn->n", v[self.dense_col_ids],
                self.dense_cols * self.dense_cols)
        if self.has_dense_rows:
            out = out.at[self.dense_row_ids].add(
                (self.dense_rows * self.dense_rows) @ v)
        return out

    def sq_rmatvec(self, u: Array) -> Array:
        out = _tiled_apply(
            self.b_code, self.b_val, self._pad_rows(u),
            depth=self.depth_b, nbo=self.nbc, nbg=self.nbr, square=True,
        )[: self.n_cols]
        out = out + self.spill.sq_rmatvec(u)
        if self.has_dense_cols:
            out = out.at[self.dense_col_ids].add(
                (self.dense_cols * self.dense_cols) @ u)
        if self.has_dense_rows:
            out = out + jnp.einsum(
                "k,kn->n", u[self.dense_row_ids],
                self.dense_rows * self.dense_rows)
        return out

    # -- cold paths: host-side over the canonical triples ------------------
    def col_nnz(self, row_mask=None) -> Array:
        return self.host_coo.col_nnz(row_mask)

    def col_min_max(self, row_mask=None):
        return self.host_coo.col_min_max(row_mask)

    def to_dense(self):
        return self.host_coo.to_dense()


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["spill_coo"],
    meta_fields=["has_spill"],
)
@dataclasses.dataclass
class SpillData:
    """COMPACT spill matrix for hot-path depth overflow.

    ``spill_coo`` holds ONLY the depth-overflow entries, so the XLA
    gather/segment_sum cost of a spill scales with the spilled minority,
    never with the total nnz.  When nothing spilled (the common case) the
    whole XLA branch is skipped at trace time via the static ``has_spill``
    flag (``spill_coo`` is then an empty 1-entry placeholder).
    """

    spill_coo: SparseMatrix  # spilled entries only
    has_spill: bool

    def matvec(self, w):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.matvec(w)

    def rmatvec(self, u):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.rmatvec(u)

    def row_sq_matvec(self, v):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.row_sq_matvec(v)

    def sq_rmatvec(self, u):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.sq_rmatvec(u)


def _extract_dense(counts, threshold, max_stripes):
    """Pick up to ``max_stripes`` indices whose entry count ≥ threshold,
    densest first."""
    cand = np.flatnonzero(counts >= threshold)
    if cand.size > max_stripes:
        cand = cand[np.argsort(-counts[cand], kind="stable")[:max_stripes]]
        cand = np.sort(cand)
    return cand.astype(np.int64)


def build_pallas_matrix(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    depth_cap: int = 128,
    pad_nnz: Optional[int] = None,
    dtype=jnp.float32,
    dense_frac: float = 1.0 / 32.0,
    max_dense: int = 8,
) -> PallasSparseMatrix:
    """Build the tiled layout from host COO triples.

    Storage-class split (see :class:`PallasSparseMatrix`):

    1. columns with ≥ ``max(256, n_rows·dense_frac)`` entries (then rows
       with ≥ ``max(256, n_cols·dense_frac)``, from what remains) become
       dense MXU stripes, at most ``max_dense`` each — an explicit bias
       column would otherwise drive every tile's slot depth to the cap;
    2. the rest lands in the tiled slot grids, at the cost-model depth
       (see ``_build_orientation``; ≤ ``depth_cap``);
    3. the residual overflow becomes a COMPACT spill COO (cost ∝ spill).
    """
    # Canonicalize ON HOST (dedup + sort + nnz-budget pad/validation) —
    # the old path built a full device COO first and read it straight
    # back, paying two transfers of the entire entry set for nothing.
    # Padding entries carry value 0, so the tiled build excludes them via
    # the live filter below; P.nnz still reports the padded budget.
    r_all, c_all, v_all = canonicalize_coo(
        rows, cols, vals, n_rows, n_cols, pad_nnz
    )
    host_coo = HostCoo(r_all, c_all, v_all, int(n_rows), int(n_cols))
    # Zero-valued entries contribute nothing; excluding them keeps explicit
    # zeros from faking a dense cell.
    live = np.flatnonzero(v_all != 0)
    r, c, v = r_all[live], c_all[live], v_all[live]

    # --- dense stripe extraction (columns first, rows from the rest) ------
    dense_col_ids = _extract_dense(
        np.bincount(c, minlength=n_cols),
        max(256, int(n_rows * dense_frac)), max_dense,
    )
    in_dc = (
        np.isin(c, dense_col_ids) if dense_col_ids.size else
        np.zeros(len(c), bool)
    )
    # Zero-SIZE placeholder when absent (never read; has_dense_cols gates).
    dense_cols = np.zeros((len(dense_col_ids), n_rows), np.float32)
    if dense_col_ids.size:
        pos = np.searchsorted(dense_col_ids, c[in_dc])
        dense_cols[pos, r[in_dc]] = v[in_dc]
        r, c, v = r[~in_dc], c[~in_dc], v[~in_dc]

    dense_row_ids = _extract_dense(
        np.bincount(r, minlength=n_rows),
        max(256, int(n_cols * dense_frac)), max_dense,
    )
    in_dr = (
        np.isin(r, dense_row_ids) if dense_row_ids.size else
        np.zeros(len(r), bool)
    )
    dense_rows = np.zeros((len(dense_row_ids), n_cols), np.float32)
    if dense_row_ids.size:
        pos = np.searchsorted(dense_row_ids, r[in_dr])
        dense_rows[pos, c[in_dr]] = v[in_dr]
        r, c, v = r[~in_dr], c[~in_dr], v[~in_dr]

    nbr = max(1, -(-n_rows // TILE_R))
    nbc = max(1, -(-n_cols // TILE_C))

    f_code, f_val, f_spill, depth_f = _build_orientation(
        r, c, v, nbr, nbc, depth_cap)
    b_code, b_val, b_spill, depth_b = _build_orientation(
        c, r, v, nbc, nbr, depth_cap)

    # Entries spilled from EITHER orientation go through the COO path for
    # BOTH directions (keeps matvec and rmatvec consistent with one X).
    spilled = np.union1d(f_spill, b_spill)
    if spilled.size:
        spill_coo = from_coo(
            r[spilled], c[spilled], v[spilled], n_rows, n_cols, dtype=dtype,
        )
        # Rebuild both orientations without the spilled entries so neither
        # tiled layout double-counts them (host-side, one extra pass).
        keep = np.ones(r.shape[0], bool)
        keep[spilled] = False
        f_code, f_val, fs2, depth_f = _build_orientation(
            r[keep], c[keep], v[keep], nbr, nbc, depth_cap,
            spill_cost_ratio=np.inf)
        b_code, b_val, bs2, depth_b = _build_orientation(
            c[keep], r[keep], v[keep], nbc, nbr, depth_cap,
            spill_cost_ratio=np.inf)
        assert fs2.size == 0 and bs2.size == 0, "re-spill after rebuild"
    else:
        spill_coo = from_coo(
            np.zeros(1, np.int64), np.zeros(1, np.int64),
            np.zeros(1, np.float32), n_rows, n_cols, dtype=dtype,
        )

    return PallasSparseMatrix(
        f_code=jnp.asarray(f_code), f_val=jnp.asarray(f_val),
        b_code=jnp.asarray(b_code), b_val=jnp.asarray(b_val),
        spill=SpillData(
            spill_coo=spill_coo, has_spill=bool(spilled.size),
        ),
        dense_cols=jnp.asarray(dense_cols),
        dense_col_ids=jnp.asarray(dense_col_ids, jnp.int32),
        dense_rows=jnp.asarray(dense_rows),
        dense_row_ids=jnp.asarray(dense_row_ids, jnp.int32),
        host_coo=host_coo,
        n_rows=int(n_rows), n_cols=int(n_cols),
        nbr=nbr, nbc=nbc, depth_f=depth_f, depth_b=depth_b,
        has_dense_cols=bool(dense_col_ids.size),
        has_dense_rows=bool(dense_row_ids.size),
    )


def from_scipy_csr_pallas(csr, depth_cap: int = 128, pad_nnz: Optional[int] = None,
                          dtype=jnp.float32) -> PallasSparseMatrix:
    csr = csr.tocsr()
    csr.sum_duplicates()
    coo = csr.tocoo()
    return build_pallas_matrix(
        coo.row.astype(np.int64), coo.col.astype(np.int64), coo.data,
        csr.shape[0], csr.shape[1], depth_cap=depth_cap, pad_nnz=pad_nnz,
        dtype=dtype)
