"""TPU-native sparse feature matrix: tiled Pallas kernels for the GLM hot loop.

This is the framework's BLAS-layer replacement (SURVEY.md §2: "the
performance-critical kernels to write are Pallas/XLA kernels (sparse matvec,
segment reductions)") — the analogue of the reference's netlib/Breeze BLAS
under its ``ValueAndGradientAggregator`` hot loop.

Why not XLA gather/scatter: on TPU, ``jnp.take`` on a 33M-element index set
runs at ~0.1 G elem/s (measured on v5e — effectively a scalar loop), and
``segment_sum`` lowers to scatter, which is as bad.  The whole 1B-row epoch
metric dies there.  Mosaic's only fast data-movement primitive is
``tpu.dynamic_gather`` on a single 128-lane vreg: each sublane of an
``(A, 128)`` operand is an independent 128-wide lookup table.

The kernel design exploits exactly that:

- The matrix is cut into ``TILE_R x TILE_C = 2048 x 2048`` tiles; each tile's
  entries are placed, ON HOST at build time, into a window-PACKED slot grid
  ``(A, 128)`` where

  * ``lane  = row % 128``                      (matvec orientation "F")
  * an entry's *window* ``(col % 2048) // 128`` decides which sublanes can
    hold it: each (tile, window) owns a contiguous run of
    ``min(max-lane-load, depth)`` sublanes (bin-packed per tile), and every
    sublane needs ONE 128-wide slice of ``w`` as its gather table;
  * extra sublanes per window absorb (window, lane) collisions; overflow
    past the cost-model depth spills to a tiny COO tail.

  Packing beats the older uniform ``depth × WINS`` grid ~1.4x on slot
  padding: A = Σ over windows of that window's own worst lane, instead of
  ``WINS ×`` the worst cell anywhere in the matrix.

- matvec per tile: per-sublane gather tables are built from each sublane's
  packed window id — by default ONE one-hot matmul on the MXU
  (f32-HIGHEST, guarded per chunk tile: any non-finite vector window
  falls back to the exact 16-step masked-SELECT sweep so inf/nan stay
  localized; measured 1.41x the select sweep on v5e — the table sweep
  was the round-3 compute floor) — then ONE ``dynamic_gather`` of the
  whole ``(A, 128)`` block, then a 16-step masked sweep accumulates rows
  into the ``(16, 128)`` margin block (``ohi = (row % 2048) // 128``,
  packed per slot, selects the output sublane).  No scatter anywhere.

- rmatvec (the gradient side, Xᵀu) is the SAME kernel with roles mirrored
  (orientation "B": lane = col % 128, tables = 128-wide windows of ``u``,
  sweep over column-his).  Both directions therefore run at the same rate —
  the property Spark's treeAggregate had for free and TPUs do not.

Measured on one v5e chip (1M rows x 8192 features, 32 nnz/row): ~40x the
pure-XLA COO path for the fused objective; see bench.py / ops/README.md.

Precision: everything is f32 — bit-comparable to the COO path (only
summation ORDER differs).  Table construction is pure selection (no
arithmetic).  No bf16 shortcuts in the value path.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.sparse import (
    DenseMatrix,
    SparseMatrix,
    canonicalize_coo,
    from_coo,
)

Array = jax.Array

# Tile edge: experimentally tunable (PHOTON_PALLAS_TILE); the per-tile
# output sweep costs WINS = TILE/128 masked passes over the slot grid, so
# smaller tiles trade DMA granularity for sweep work.  2048 measured best
# on v5e for the bench workload; see ops/README.md.
TILE_R = int(os.environ.get("PHOTON_PALLAS_TILE", "2048"))
if TILE_R < 128 or TILE_R % 128 or TILE_R > 32768:
    # The packed slot code (win | ohi | lo) switches to int32 automatically
    # past TILE 2048 (CODE_DTYPE below); 32768 is a sanity bound.
    raise ValueError(
        f"PHOTON_PALLAS_TILE must be a multiple of 128 in [128, 32768], "
        f"got {TILE_R}"
    )
TILE_C = TILE_R
WIN = 128           # window width = lanes per vreg
WINS = TILE_R // WIN  # windows per tile side
# Packed per-slot code layout: | win | ohi | lo |, low bits first.
#   lo  (7 bits)      — gather index into the sublane's 128-wide table
#   ohi (OBITS bits)  — output window within the tile
#   win (OBITS bits)  — the SUBLANE's gather window (same value in all 128
#                       slots of a sublane; the kernel reads lane 0)
# int16 when it fits (TILE ≤ 2048 — halves index DMA), else int32.
OBITS = max(1, (WINS - 1).bit_length())
WIN_SHIFT = 7 + OBITS
_CODE_BITS = 7 + 2 * OBITS
CODE_DTYPE = np.int16 if _CODE_BITS <= 15 else np.int32
CODE_BYTES = 2 if _CODE_BITS <= 15 else 4
# Empty slots carry the code dtype's SIGN bit (win bits preserved — the
# kernel still reads lane 0's window id through CODE_MASK).  This lets the
# unit-value layout drop the f32 val stream entirely: validity is
# ``code >= 0``, cutting slot DMA 6 → 2 bytes on binary feature matrices
# (the reference's canonical case — a1a features, one-hot GAME features).
CODE_MASK = (1 << _CODE_BITS) - 1
EMPTY_MARK = np.iinfo(CODE_DTYPE).min
# Sublane-count granularity: the int16 slot arrays tile as (16, 128) on TPU,
# so A is padded to a multiple of 16 (8 would re-pad internally).
SUBPAD = 16
# Per-grid-step DMA budget for the tile kernel (bytes); 4 MiB measured best
# on v5e (2/8/16 MiB all slower — see ops/README.md).
DMA_BUDGET = int(os.environ.get("PHOTON_PALLAS_BUDGET", 4 << 20))
if DMA_BUDGET <= 0:
    raise ValueError(
        f"PHOTON_PALLAS_BUDGET must be a positive byte count, got "
        f"{DMA_BUDGET}"
    )


def _cptr(arr: np.ndarray, ct):
    """ctypes pointer to a contiguous numpy array (native build glue)."""
    import ctypes

    return arr.ctypes.data_as(ctypes.POINTER(ct))


def _extract_fields(r32: np.ndarray, c32: np.ndarray, nbc: int):
    """(tile, gwin, lane) in int32, with shifts/masks where the tile edge
    is a power of two (the default) — numpy's int64 floor-division is
    scalar (~0.5 s per pass at 33M entries).  Shared by the layout build
    and the permutation predictor."""
    if TILE_R & (TILE_R - 1) == 0:
        tshift = TILE_R.bit_length() - 1
        tr = r32 >> tshift
        tc = c32 >> tshift
        gwin = (c32 >> 7) & (WINS - 1)
    else:
        tr = (r32 // TILE_R).astype(np.int32)
        tc = (c32 // TILE_C).astype(np.int32)
        gwin = ((c32 % TILE_C) // WIN).astype(np.int32)
    tile = tr * np.int32(nbc) + tc
    lane = r32 & np.int32(WIN - 1)
    return tile, gwin, lane


def _interpret() -> bool:
    """Run kernels in interpreter mode (CPU tests set this env var)."""
    return os.environ.get("PHOTON_PALLAS_INTERPRET", "") == "1"


# Gather-side table build: one-hot matmul on the MXU (all-finite fast
# path, guarded per chunk tile) vs the 16-pass masked-select sweep.
# Opt-out knob: the select sweep was the round-3 compute floor; set to
# "0" if a TPU generation regresses on the tiny matmul.  Read ONCE at
# import (the kernel bakes the choice at trace time) — A/B in separate
# processes, exactly like PHOTON_PALLAS_TILE.
_MXU_GATHER = os.environ.get("PHOTON_PALLAS_MXU_GATHER", "1") == "1"


def pallas_available() -> bool:
    """True when the Pallas sparse path can run here (TPU, or interpret)."""
    return jax.default_backend() == "tpu" or _interpret()


# ---------------------------------------------------------------------------
# Host-side layout build
# ---------------------------------------------------------------------------


def _build_orientation(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    nbr: int,
    nbc: int,
    depth_cap: int,
    spill_cost_ratio: float = 1024.0,
):
    """Place entries into the window-PACKED (tile, sublane, lane) slot grid.

    Orientation F (matvec): ``rows`` are the lane/output side, ``cols`` the
    gather side.  Call with rows/cols swapped (and nbr/nbc swapped) for
    orientation B.  Returns (code, val, spill_idx, a, depth) where

    code (NBR, NBC, A, 128) — packed ``win<<WIN_SHIFT | ohi<<7 | lo``:
         ``lo`` indexes the sublane's 128-wide gather table, ``ohi`` is the
         output window, ``win`` the SUBLANE's gather window (present in
         every slot, empty or not — the kernel reads lane 0's copy)
    val  (NBR, NBC, A, 128) f32 — entry values (0 in empty slots)

    Packing: each (tile, window) pair owns a CONTIGUOUS run of
    ``need = min(max-lane-load, depth)`` sublanes, bin-packed per tile, so
    A = max over tiles of Σ_w need — instead of the old uniform
    ``WINS × global-max-depth`` grid.  On Poisson-spread data this cuts slot
    padding ~1.5×: the old grid paid ``WINS ×`` the WORST cell anywhere,
    the packed layout pays each window's own worst lane, summed.

    Depth (the per-cell slot cap) is still COST-based: covering one more
    collision level costs real slots only where windows actually need it
    (Σ over windows of the increment to ``min(M, d)``, maxed over tiles),
    while each spilled entry costs ~``spill_cost_ratio`` slot-equivalents
    on the XLA gather/segment_sum path (measured ~1000x per entry on v5e),
    plus a FIXED penalty for any nonzero spill (the XLA scatter's latency
    floor, worth ~16 uniform depth levels).  ``spill_cost_ratio=inf``
    forces full coverage (used for the post-spill rebuild).
    """
    nt = nbr * nbc

    if len(rows) == 0:  # all-zero / empty matrix: one empty sublane group
        return (
            np.full((nbr, nbc, SUBPAD, WIN), EMPTY_MARK, CODE_DTYPE),
            np.zeros((nbr, nbc, SUBPAD, WIN), np.float32),
            np.empty(0, np.intp),
            SUBPAD,
            1,
        )

    # Sort + per-cell depth positions + per-(tile, window) max lane loads:
    # the NATIVE path (native/layout_sort.cpp — stable radix argsort with
    # numpy's exact tie order, one sequential scan) when the library is
    # available and the entry count is worth the ctypes round trip; the
    # numpy formulation below otherwise.  Outputs are BIT-IDENTICAL
    # (parity-tested), so everything downstream is shared.
    rows64 = cols64 = None
    lib = None
    if len(rows) >= (1 << 18):
        from photon_ml_tpu.native import load_layout_sorter

        lib = load_layout_sorter()
    if lib is not None:
        import ctypes

        rows64 = np.ascontiguousarray(rows, np.int64)
        cols64 = np.ascontiguousarray(cols, np.int64)
        nnz = len(rows64)
        order = np.empty(nnz, np.int32)
        depth_pos = np.empty(nnz, np.int32)
        M = np.zeros(nt * WINS, np.int64)

        rc = lib.pl_sort_orientation(
            _cptr(rows64, ctypes.c_int64), _cptr(cols64, ctypes.c_int64),
            nnz, nbc, TILE_R, nt,
            _cptr(order, ctypes.c_int32), _cptr(depth_pos, ctypes.c_int32),
            _cptr(M, ctypes.c_int64),
        )
        if rc != 0:  # nnz beyond int32 indexing: numpy handles it
            lib = None
    if lib is None:
        r32 = rows.astype(np.int32, copy=False)
        c32 = cols.astype(np.int32, copy=False)
        tile, gwin, lane = _extract_fields(r32, c32, nbc)

        # One combined sort key (≈2-3x faster than a 3-key lexsort at 33M
        # entries), in int32 when it fits; kind="stable" selects numpy's
        # radix sort for integer keys (~2x quicksort at this size).
        kmax = nt * WINS * WIN
        kdtype = np.int32 if kmax < 2**31 else np.int64
        key = (
            (tile.astype(kdtype) * WINS + gwin) * WIN + lane
        )
        order = np.argsort(key, kind="stable")
        cell = key[order]
        # run-length position within equal consecutive cells
        change = np.empty(len(cell), dtype=bool)
        change[0] = True
        np.not_equal(cell[1:], cell[:-1], out=change[1:])
        run_starts = np.flatnonzero(change)
        run_ids = np.cumsum(change) - 1
        depth_pos = np.arange(len(cell)) - run_starts[run_ids]

        # Per-(tile, window) max lane load M — the sublanes window w needs
        # at depth cap d is min(M[t, w], d) (max of min = min of max per
        # lane).  cell ids are sorted, so grouped reduceat beats the
        # ufunc.at path (~10x at 33M entries).
        counts = np.diff(np.append(run_starts, len(cell)))
        cell_tw = (cell[run_starts] // WIN).astype(np.int64)
        tw_change = np.empty(len(cell_tw), dtype=bool)
        tw_change[0] = True
        np.not_equal(cell_tw[1:], cell_tw[:-1], out=tw_change[1:])
        tw_starts = np.flatnonzero(tw_change)
        M = np.zeros(nt * WINS, np.int64)
        M[cell_tw[tw_starts]] = np.maximum.reduceat(counts, tw_starts)
    M = M.reshape(nt, WINS)

    hist = np.bincount(depth_pos)
    cum = np.cumsum(hist)
    spilled_at = len(depth_pos) - cum  # spilled(d) for d = 1..len(hist)
    if np.isinf(spill_cost_ratio):
        depth = len(hist)
    else:
        max_d = min(len(hist), depth_cap)
        # cost(d) = slots(d) + ratio·spilled(d) + fixed·(spilled(d) > 0)
        a_at = np.array(
            [np.minimum(M, d).sum(axis=1).max() for d in range(1, max_d + 1)],
            np.float64,
        )
        cost = (
            a_at * float(nt * WIN)
            + spill_cost_ratio * spilled_at[:max_d]
            + 16.0 * float(nt * WINS * WIN) * (spilled_at[:max_d] > 0)
        )
        depth = int(np.argmin(cost)) + 1
    depth = min(max(depth, 1), depth_cap)
    keep = depth_pos < depth

    # Bin-pack: window w of tile t owns sublanes [base[t,w], base[t,w]+need).
    need = np.minimum(M, depth)             # (nt, WINS)
    base = np.cumsum(need, axis=1) - need   # exclusive per-tile cumsum
    a_t = need.sum(axis=1)
    a = max(SUBPAD, int(-(-a_t.max() // SUBPAD) * SUBPAD))

    # Every slot of a sublane carries the sublane's window id in its high
    # bits (so empty slots still tell the kernel which table to build).
    winid = np.zeros((nt, a), CODE_DTYPE)
    total = int(a_t.sum())
    tile_of = np.repeat(np.arange(nt), a_t)
    pos = np.arange(total) - np.repeat(np.cumsum(a_t) - a_t, a_t)
    winid[tile_of, pos] = np.repeat(
        np.tile(np.arange(WINS, dtype=CODE_DTYPE), nt), need.ravel()
    )
    code = np.empty((nt, a, WIN), CODE_DTYPE)
    # Empty slots: window id in the high FIELD bits + the EMPTY sign bit.
    code[:] = (
        (winid << np.array(WIN_SHIFT, CODE_DTYPE))
        | np.array(EMPTY_MARK, CODE_DTYPE)
    )[:, :, None]
    val = np.zeros((nt, a, WIN), np.float32)

    if lib is not None:
        import ctypes

        vals32 = np.ascontiguousarray(vals, np.float32)
        base32 = np.ascontiguousarray(base, np.int32)
        n_spill_expected = int(len(rows64) - hist[:depth].sum())
        spill_idx = np.empty(max(n_spill_expected, 1), np.int64)

        n_sp = lib.pl_scatter(
            _cptr(rows64, ctypes.c_int64), _cptr(cols64, ctypes.c_int64),
            _cptr(vals32, ctypes.c_float),
            _cptr(order, ctypes.c_int32), _cptr(depth_pos, ctypes.c_int32),
            _cptr(base32, ctypes.c_int32),
            len(rows64), nbc, TILE_R, depth, a, WIN_SHIFT, CODE_BYTES,
            code.ctypes.data_as(ctypes.c_void_p),
            _cptr(val, ctypes.c_float),
            _cptr(spill_idx, ctypes.c_int64),
        )
        assert n_sp == n_spill_expected, (n_sp, n_spill_expected)
        spill_idx = spill_idx[:n_sp]
        return (
            code.reshape(nbr, nbc, a, WIN), val.reshape(nbr, nbc, a, WIN),
            spill_idx, a, depth,
        )

    # Decompose sorted keys with shifts (WIN is always 2^7; WINS is a
    # power of two for power-of-two tile edges), and gather per-entry
    # payloads through ONE index array instead of gather-then-mask — the
    # div/mod + double-gather formulation cost ~18 s at 33M entries.
    if TILE_R & (TILE_R - 1) == 0:
        ohi = (r32 >> 7) & (WINS - 1)
    else:
        ohi = ((r32 % TILE_R) // WIN).astype(np.int32)
    glo = c32 & np.int32(WIN - 1)
    if WINS & (WINS - 1) == 0:
        wshift = WINS.bit_length() - 1
        t_s = cell >> np.array(7 + wshift, cell.dtype)
        g_s = (cell >> np.array(7, cell.dtype)) & np.array(
            WINS - 1, cell.dtype
        )
    else:
        t_s = cell // (WINS * WIN)
        g_s = (cell // WIN) % WINS
    l_s = cell & np.array(WIN - 1, cell.dtype)
    kidx = order[keep]                  # original indices of kept entries
    kt = t_s[keep]
    kl = l_s[keep]
    kg = g_s[keep]
    sub = base[kt, kg] + depth_pos[keep]
    # Filled slots: full positive code (sign bit clear).  The window id of
    # slot (kt, sub) is kg by construction (sub lies in window g's run).
    flat = (kt.astype(np.int64) * a + sub) * WIN + kl
    code.reshape(-1)[flat] = (
        (kg.astype(np.int32) << WIN_SHIFT)
        | (ohi[kidx].astype(np.int32) << 7)
        | glo[kidx]
    ).astype(CODE_DTYPE)
    val.reshape(-1)[flat] = vals[kidx]

    spill_idx = order[~keep]            # indices into original entry arrays
    return (code.reshape(nbr, nbc, a, WIN), val.reshape(nbr, nbc, a, WIN),
            spill_idx, a, depth)


# ---------------------------------------------------------------------------
# The tile kernel (shared by both directions)
# ---------------------------------------------------------------------------


def _tile_kernel(*refs, square, batch, chunk, unit):
    """A (batch x chunk) rectangle of tiles per grid step.

    Batching many tiles per step keeps DMAs large (MBs, not hundreds of KB)
    so the stream stays bandwidth-bound instead of per-step-overhead-bound
    (measured: 2048 one-tile steps cost ~5 us each — more than the data).

    code: (batch, chunk, A, 128) packed (win<<WIN_SHIFT | ohi<<7 | lo);
          empty slots carry EMPTY_MARK's sign bit (win bits preserved)
    val:  (batch, chunk, A, 128) f32 — ABSENT in ``unit`` mode: binary
          matrices (every tiled value 1.0) stream codes only, 3x less
          DMA on a bandwidth-bound kernel; validity is ``code >= 0``
    tab:  (chunk, WINS, 128) gather-side vector windows for this chunk
    out:  (batch, WINS, 128), accumulated across the chunked grid dim

    Gather tables are built per tile from each sublane's packed window
    id — by default a one-hot f32 matmul on the MXU, guarded per chunk
    tile: a bare matmul would leak a non-finite vector entry into every
    sublane's table via 0·inf = NaN, so tiles whose table windows carry
    inf/nan take the exact masked-SELECT sweep instead (see the in-body
    comment and test_nonfinite_vector_entries_stay_localized).
    """
    from jax.experimental import pallas as pl

    if unit:
        code_ref, tab_ref, out_ref = refs
        val_ref = None
    else:
        code_ref, val_ref, tab_ref, out_ref = refs

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    def chunk_tile_body(j, _):
        # Hoisted per CHUNK tile: the gather windows and their finiteness
        # predicate are invariant across the batch dimension — slicing and
        # reducing them once per (j) instead of per (b, j) saves
        # batch-1 redundant (WINS, 128) passes.
        if _MXU_GATHER:
            tab_j = tab_ref[pl.ds(j, 1), :, :][0]             # (WINS, 128)
            tab_finite = jnp.all(jnp.isfinite(tab_j))
        _batch_tiles(j, tab_j if _MXU_GATHER else None,
                     tab_finite if _MXU_GATHER else None)
        return 0

    def _batch_tiles(j, tab_j, tab_finite):
        jax.lax.fori_loop(
            0, batch, lambda b, _: tile_body(b, j, tab_j, tab_finite), 0
        )

    def tile_body(b, j, tab_j, tab_finite):
        code = code_ref[b, j].astype(jnp.int32)
        # Field bits through CODE_MASK: empty slots are sign-marked, and
        # int16→int32 sign extension would otherwise corrupt the window
        # id read from a lane-0-empty sublane.
        fields = code & CODE_MASK
        lo = fields & (WIN - 1)
        ohi = (fields >> 7) & ((1 << OBITS) - 1)
        win = fields[:, 0:1] >> WIN_SHIFT                     # (A, 1)
        a = code.shape[0]

        # Per-sublane tables: WINS masked selects (exact; a non-finite
        # vector entry stays localized to sublanes whose window actually
        # holds it — a bare one-hot matmul would leak it everywhere via
        # 0*inf=NaN).  With PHOTON_PALLAS_MXU_GATHER the common all-
        # finite case rides ONE (A,WINS)x(WINS,128) one-hot matmul on
        # the MXU instead of the 16-pass select sweep; a per-chunk-tile
        # finiteness reduce guards the exact select path for vectors
        # carrying inf/nan, so the localization contract is unchanged.
        def select_tables(_):
            def w_body(wi, acc):
                row = tab_ref[j, pl.ds(wi, 1), :]             # (1, 128)
                return jnp.where(
                    win == wi, jnp.broadcast_to(row, (a, WIN)), acc
                )

            return jax.lax.fori_loop(
                0, WINS, w_body, jnp.zeros((a, WIN), jnp.float32)
            )                                                 # (A, 128)

        if _MXU_GATHER:
            def mxu_tables(_):
                onehot = (
                    win == jax.lax.broadcasted_iota(
                        jnp.int32, (a, WINS), 1
                    )
                ).astype(jnp.float32)
                # HIGHEST: default matmul precision feeds the MXU bf16
                # inputs, and bf16(table) != f32 table — the one-hot
                # product must return window entries exactly (the value
                # path is f32 end-to-end; sole exception: -0.0 gathers
                # as +0.0, numerically inert in the product-sum).
                return jax.lax.dot_general(
                    onehot, tab_j,
                    (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32,
                )

            tables = jax.lax.cond(
                tab_finite, mxu_tables, select_tables, 0
            )
        else:
            tables = select_tables(0)
        g = jnp.take_along_axis(tables, lo, axis=1)           # (A, 128)
        if unit:
            # Unit values: v = v² = 1 for every real slot; empty slots
            # (sign bit set) must contribute EXACT zero even when their
            # placeholder gather hits a non-finite vector entry.
            contrib = jnp.where(code >= 0, g, 0.0)
        else:
            v = val_ref[b, j]
            if square:
                contrib = v * v * g
            else:
                contrib = v * g
            # Empty slots (v == 0; zero-valued entries are excluded at
            # build time) must contribute EXACT zero even when their
            # placeholder gather (lo = 0) hits a non-finite vector entry
            # — 0 * inf = NaN would otherwise leak into output window 0
            # of unrelated rows.
            contrib = jnp.where(v != 0.0, contrib, 0.0)

        def h_body(h, _):
            part = jnp.sum(jnp.where(ohi == h, contrib, 0.0), axis=0)
            out_ref[b, pl.ds(h, 1), :] += part.reshape(1, WIN)
            return 0

        jax.lax.fori_loop(0, WINS, h_body, 0)
        return 0

    # j-outer / b-inner: per-(b, h) accumulation order over j is unchanged
    # vs the old flat (b-major) loop, so outputs stay bit-identical.
    jax.lax.fori_loop(0, chunk, chunk_tile_body, 0)


def _pick_rect(nbo: int, nbg: int, a: int,
               budget: int = None, unit: bool = False) -> tuple[int, int]:
    """(batch, chunk) tiles per grid step fitting ~``budget`` input bytes."""
    if budget is None:
        budget = DMA_BUDGET
    # packed code (+ f32 val unless the unit-value layout dropped it)
    per_tile = a * WIN * (CODE_BYTES + (0 if unit else 4))
    cap = max(1, budget // per_tile)

    def largest_divisor_leq(n, m):
        d = min(n, m)
        while n % d:
            d -= 1
        return d

    chunk = largest_divisor_leq(nbg, cap)
    batch = largest_divisor_leq(nbo, max(1, cap // chunk))
    return batch, chunk


@functools.partial(jax.jit, static_argnames=("nbo", "nbg", "square", "unit"))
def _tiled_apply(code, val, vec_padded, *, nbo, nbg, square, unit=False):
    """out[i] = sum over entries (i, j, v) of v * vec[j] (+ optional v²).

    ``code``/``val``: (nbo, nbg, A, 128); ``vec_padded``: (nbg * TILE_C,).
    Returns (nbo * TILE_R,) output.  The packed sublane count A comes from
    the array shape (jit already specializes on it).  ``unit``: the
    binary-matrix layout — ``val`` is ignored (pass the placeholder) and
    only codes stream through the kernel.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed pltpu.TPUCompilerParams → pltpu.CompilerParams; accept
    # both so the kernels (and interpret-mode CPU tests) run on either
    # side of the rename.
    compiler_params_cls = getattr(
        pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
    )

    a = code.shape[2]
    batch, chunk = _pick_rect(nbo, nbg, a, unit=unit)
    tab = vec_padded.reshape(nbg, WINS, WIN)
    kernel = functools.partial(_tile_kernel, square=square,
                               batch=batch, chunk=chunk, unit=unit)
    slot_spec = pl.BlockSpec(
        (batch, chunk, a, WIN), lambda i, j: (i, j, 0, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [slot_spec]
    operands = [code]
    if not unit:
        in_specs.append(slot_spec)
        operands.append(val)
    in_specs.append(
        pl.BlockSpec((chunk, WINS, WIN), lambda i, j: (j, 0, 0),
                     memory_space=pltpu.VMEM)
    )
    operands.append(tab)
    out = pl.pallas_call(
        kernel,
        grid=(nbo // batch, nbg // chunk),
        out_shape=jax.ShapeDtypeStruct((nbo, WINS, WIN), jnp.float32),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((batch, WINS, WIN), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=compiler_params_cls(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*operands)
    # out[i, h, l] = output element i*TILE_R + h*128 + l
    return out.reshape(nbo * TILE_R)


# ---------------------------------------------------------------------------
# Public matrix type
# ---------------------------------------------------------------------------


class HostCoo:
    """Host-side canonical COO triples for COLD paths (stats, min/max,
    densify) — one-shot per job, so they run in numpy on the host instead of
    keeping a full device COO copy alive (at 33M nnz that copy cost ~670 MB
    of HBM and ~14 s of transfer over this transport for ops the hot loop
    never touches).

    Lives in a pytree META field, never traced, never transferred.
    Equality/hash use the (n_rows, n_cols, nnz) shape class — NOT content —
    so rebuilding a same-shaped matrix (tuning / down-sampling loops) keeps
    hitting existing jit caches exactly as the all-int metadata did.  Two
    consequences, both documented invariants:

    - cold ops must be called EAGERLY (outside jit), as the drivers do —
      under tracing their results would be baked as constants keyed by the
      shape class, which is wrong across different matrices (the main
      consumer, stats.summarize, passes a row_mask whose np.asarray raises
      on tracers, failing loudly);
    - a jit cache entry for a given shape class keeps that first holder's
      host arrays alive until the compiled function is dropped (bounded by
      distinct shape classes, not by rebuild count).
    """

    __slots__ = ("rows", "cols", "vals", "n_rows", "n_cols")

    def __eq__(self, other):
        return (
            isinstance(other, HostCoo)
            and self.n_rows == other.n_rows
            and self.n_cols == other.n_cols
            and self.nnz == other.nnz
        )

    def __hash__(self):
        return hash((self.n_rows, self.n_cols, self.nnz))

    def __init__(self, rows, cols, vals, n_rows, n_cols):
        self.rows = rows
        self.cols = cols
        self.vals = vals
        self.n_rows = n_rows
        self.n_cols = n_cols

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def _live(self, row_mask):
        live = self.vals != 0
        if row_mask is not None:
            live &= np.asarray(row_mask)[self.rows]
        return live

    def col_nnz(self, row_mask=None):
        live = self._live(row_mask)
        return jnp.asarray(
            np.bincount(
                self.cols[live], minlength=self.n_cols
            ).astype(np.int32)
        )

    def col_min_max(self, row_mask=None):
        """Per-feature (min, max) over stored entries of live rows, folded
        with the implicit zeros of unstored entries — same semantics as
        SparseMatrix.col_min_max."""
        live = self._live(row_mask)
        c = self.cols[live]
        v = self.vals[live]
        mins = np.full(self.n_cols, np.inf, np.float32)
        maxs = np.full(self.n_cols, -np.inf, np.float32)
        np.minimum.at(mins, c, v)
        np.maximum.at(maxs, c, v)
        nnz = np.bincount(c, minlength=self.n_cols)
        n_live_rows = (
            self.n_rows if row_mask is None
            else int(np.sum(np.asarray(row_mask)))
        )
        has_zero = nnz < n_live_rows
        mins = np.where(has_zero, np.minimum(mins, 0.0), mins)
        maxs = np.where(has_zero, np.maximum(maxs, 0.0), maxs)
        return jnp.asarray(mins), jnp.asarray(maxs)

    def to_dense(self):
        dense = np.zeros((self.n_rows, self.n_cols), np.float32)
        np.add.at(dense, (self.rows, self.cols), self.vals)
        return DenseMatrix(jnp.asarray(dense))


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "f_code", "f_val",
        "b_code", "b_val",
        "spill",
        "dense_cols", "dense_col_ids",
        "dense_rows", "dense_row_ids",
        "col_perm_fwd", "col_perm_inv",
    ],
    meta_fields=[
        "host_coo",
        "n_rows", "n_cols", "nbr", "nbc", "a_f", "a_b", "depth_f", "depth_b",
        "has_dense_cols", "has_dense_rows", "has_col_perm", "unit_vals",
    ],
)
@dataclasses.dataclass
class PallasSparseMatrix:
    """Sparse feature matrix backed by the tiled Pallas layout.

    Drop-in for :class:`photon_ml_tpu.ops.sparse.SparseMatrix` in the GLM
    hot loop (matvec / rmatvec / squared variants).  Three complementary
    storage classes, split at build time:

    - **tiled slot grids** — the bulk of the entries, Pallas-kernel fast;
    - **dense stripes** — ultra-dense columns/rows (an explicit bias column,
      a few very popular features) extracted into small dense blocks that
      ride plain MXU matmuls: they would otherwise overload their slot
      cells and drag the whole layout's depth up;
    - **compact spill** — the residual overflow past the cost-model depth,
      a COO matrix holding ONLY the spilled entries (cost scales with
      spill size, not total nnz).

    Statistics and other cold paths run host-side over ``host_coo`` (the
    canonical triples; a META field — see its docstring for the eager-only
    contract).
    """

    # orientation F (matvec): lane = row%128, tables = w windows
    f_code: Array
    f_val: Array
    # orientation B (rmatvec): lane = col%128, tables = u windows
    b_code: Array
    b_val: Array
    # compact spill matrix (hot-path overflow past the chosen depth)
    spill: "SpillData"
    # ultra-dense stripes (minor dim = the long axis, so XLA's physical
    # tiling pads 8 sublanes, not 128 lanes per stripe; placeholder arrays
    # when absent — see has_* flags)
    dense_cols: Array      # (kc, n_rows) f32 — TRANSPOSED stripe storage
    dense_col_ids: Array   # (kc,) int32 — global column of each stripe
    dense_rows: Array      # (kr, n_cols) f32
    dense_row_ids: Array   # (kr,) int32 — global row of each stripe
    # Column permutation (clustered-data balance; identity when absent —
    # placeholders gated by has_col_perm):
    col_perm_fwd: Array    # (n_cols,) int32 — old col → tiled position
    col_perm_inv: Array    # (nbc*TILE_C,) int32 — tiled position → old col
    #                        (n_cols = "reads the appended zero slot")
    host_coo: HostCoo      # META: host triples for cold paths (never traced)
    n_rows: int
    n_cols: int
    nbr: int
    nbc: int
    a_f: int               # packed sublane count per tile, orientation F
    a_b: int               # packed sublane count per tile, orientation B
    depth_f: int           # per-cell collision cap chosen by the cost model
    depth_b: int
    has_dense_cols: bool
    has_dense_rows: bool
    has_col_perm: bool
    # Binary-matrix fast path: every TILED value is 1.0, so the f32 val
    # arrays are 1-element placeholders and the kernels stream codes only
    # (3x less slot DMA); validity rides the codes' EMPTY sign bit.
    # Dense stripes and the spill keep true values either way.
    unit_vals: bool = False

    # -- shape protocol ----------------------------------------------------
    @property
    def shape(self) -> tuple[int, int]:
        return (self.n_rows, self.n_cols)

    @property
    def nnz(self) -> int:
        return self.host_coo.nnz

    def _pad_cols(self, w: Array) -> Array:
        """Column-side vector in TILED position space: zero-pad, or (with
        a column permutation) a d-sized gather through the inverse map."""
        target = self.nbc * TILE_C
        if not self.has_col_perm:
            return jnp.pad(w, (0, target - self.n_cols))
        wp = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        return jnp.take(wp, self.col_perm_inv, axis=0)

    def _uncols(self, out_full: Array) -> Array:
        """Column-space tiled output back to original column order."""
        if not self.has_col_perm:
            return out_full[: self.n_cols]
        return jnp.take(out_full, self.col_perm_fwd, axis=0)

    def _pad_rows(self, u: Array) -> Array:
        target = self.nbr * TILE_R
        return jnp.pad(u, (0, target - self.n_rows))

    # -- hot paths ---------------------------------------------------------
    def matvec(self, w: Array) -> Array:
        out = _tiled_apply(
            self.f_code, self.f_val, self._pad_cols(w),
            nbo=self.nbr, nbg=self.nbc, square=False, unit=self.unit_vals,
        )[: self.n_rows]
        out = out + self.spill.matvec(w)
        if self.has_dense_cols:
            out = out + jnp.einsum(
                "k,kn->n", w[self.dense_col_ids], self.dense_cols)
        if self.has_dense_rows:
            out = out.at[self.dense_row_ids].add(self.dense_rows @ w)
        return out

    def rmatvec(self, u: Array) -> Array:
        out = self._uncols(_tiled_apply(
            self.b_code, self.b_val, self._pad_rows(u),
            nbo=self.nbc, nbg=self.nbr, square=False, unit=self.unit_vals,
        ))
        out = out + self.spill.rmatvec(u)
        if self.has_dense_cols:
            out = out.at[self.dense_col_ids].add(self.dense_cols @ u)
        if self.has_dense_rows:
            out = out + jnp.einsum(
                "k,kn->n", u[self.dense_row_ids], self.dense_rows)
        return out

    def row_sq_matvec(self, v: Array) -> Array:
        out = _tiled_apply(
            self.f_code, self.f_val, self._pad_cols(v),
            nbo=self.nbr, nbg=self.nbc, square=True, unit=self.unit_vals,
        )[: self.n_rows]
        out = out + self.spill.row_sq_matvec(v)
        if self.has_dense_cols:
            out = out + jnp.einsum(
                "k,kn->n", v[self.dense_col_ids],
                self.dense_cols * self.dense_cols)
        if self.has_dense_rows:
            out = out.at[self.dense_row_ids].add(
                (self.dense_rows * self.dense_rows) @ v)
        return out

    def sq_rmatvec(self, u: Array) -> Array:
        out = self._uncols(_tiled_apply(
            self.b_code, self.b_val, self._pad_rows(u),
            nbo=self.nbc, nbg=self.nbr, square=True, unit=self.unit_vals,
        ))
        out = out + self.spill.sq_rmatvec(u)
        if self.has_dense_cols:
            out = out.at[self.dense_col_ids].add(
                (self.dense_cols * self.dense_cols) @ u)
        if self.has_dense_rows:
            out = out + jnp.einsum(
                "k,kn->n", u[self.dense_row_ids],
                self.dense_rows * self.dense_rows)
        return out

    # -- cold paths: host-side over the canonical triples ------------------
    def col_nnz(self, row_mask=None) -> Array:
        return self.host_coo.col_nnz(row_mask)

    def col_min_max(self, row_mask=None):
        return self.host_coo.col_min_max(row_mask)

    def to_dense(self):
        return self.host_coo.to_dense()


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["spill_coo"],
    meta_fields=["has_spill"],
)
@dataclasses.dataclass
class SpillData:
    """COMPACT spill matrix for hot-path depth overflow.

    ``spill_coo`` holds ONLY the depth-overflow entries, so the XLA
    gather/segment_sum cost of a spill scales with the spilled minority,
    never with the total nnz.  When nothing spilled (the common case) the
    whole XLA branch is skipped at trace time via the static ``has_spill``
    flag (``spill_coo`` is then an empty 1-entry placeholder).
    """

    spill_coo: SparseMatrix  # spilled entries only
    has_spill: bool

    def matvec(self, w):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.matvec(w)

    def rmatvec(self, u):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.rmatvec(u)

    def row_sq_matvec(self, v):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.row_sq_matvec(v)

    def sq_rmatvec(self, u):
        if not self.has_spill:
            return jnp.zeros((), jnp.float32)
        return self.spill_coo.sq_rmatvec(u)


def _predict_a(rows, cols, nbr, nbc):
    """Predicted packed sublane count (max over tiles of Σ_w max-lane-load)
    for orientation F of the given entry set.  Counts only PRESENT cells
    (sort + reduceat) — a dense bincount over every possible cell is
    O(tiles · TILE · 128) host memory and OOMs at millions of tiles.
    Used to choose between identity and permuted column layouts."""
    t, w, l = _extract_fields(
        rows.astype(np.int32, copy=False),
        cols.astype(np.int32, copy=False), nbc,
    )
    kdtype = np.int32 if nbr * nbc * WINS * WIN < 2**31 else np.int64
    key = np.sort(
        (t.astype(kdtype) * WINS + w) * WIN + l, kind="stable"
    )
    change = np.empty(len(key), dtype=bool)
    change[0] = True
    np.not_equal(key[1:], key[:-1], out=change[1:])
    starts = np.flatnonzero(change)
    counts = np.diff(np.append(starts, len(key)))
    tw = key[starts] // WIN
    tw_change = np.empty(len(tw), dtype=bool)
    tw_change[0] = True
    np.not_equal(tw[1:], tw[:-1], out=tw_change[1:])
    tw_starts = np.flatnonzero(tw_change)
    m = np.maximum.reduceat(counts, tw_starts)     # max lane load per (t,w)
    a_t = np.bincount(
        tw[tw_starts] // WINS, weights=m, minlength=nbr * nbc
    )
    return int(a_t.max())


def _balance_col_perm(cols, n_cols, nbc):
    """Frequency round-robin column relabeling: rank columns by entry count
    (descending) and stripe them across ALL column windows of all tiles,
    rotating the within-window offset so orientation B's lanes (col % 128)
    spread too.  Returns ``m`` (old col → new col, len n_cols), a bijection
    into [0, nbc*TILE_C).

    Clustered real-world data (ids sorted by popularity, feature shards
    grouped by type) concentrates hot columns in a few windows; each
    window pays its own worst lane in the packed layout, so spreading the
    mass is a direct A reduction.  Uniform data is unaffected — the
    builder compares predicted A and keeps the identity when it wins.
    """
    counts = np.bincount(cols, minlength=n_cols)
    ranks = np.argsort(-counts, kind="stable")
    n_win_total = nbc * WINS
    r = np.arange(n_cols, dtype=np.int64)
    w = r % n_win_total            # window round-robin (F-side balance)
    k = r // n_win_total           # round within the window
    # Lane (= new_col % 128, orientation B's lane) must ALSO spread: within
    # one column-tile, round k of window w gets lane (w % WINS) + WINS·σ
    # via a transposed-grid bijection σ of the rounds, so the first WIN hot
    # ranks of every tile land on WIN DISTINCT lanes (a plain (k + w) % WIN
    # rotation made hot ranks from consecutive rounds collide on the same
    # (col-tile, lane), blowing up orientation B's packing).
    if WIN % WINS == 0:
        q = WIN // WINS
        # k = q·a + b → lane = w_in + WINS·b + a (mod WIN): bijective in k
        # for fixed w, and the first q rounds of a tile's WINS windows
        # cover all WIN lanes exactly once.
        lane = (w % WINS + WINS * (k % q) + k // q) % WIN
    else:
        # Non-power-of-two tiles (WINS ∤ WIN): the grid transpose is not a
        # bijection, so fall back to the trivially bijective per-window
        # round order (weaker B-lane spreading, never wrong).
        lane = k
    new = w * WIN + lane
    m = np.empty(n_cols, np.int64)
    m[ranks] = new
    assert len(np.unique(new)) == n_cols, "column relabeling not bijective"
    return m


def _extract_dense(counts, threshold, max_stripes, long_axis,
                   budget_bytes):
    """Pick up to ``max_stripes`` indices whose entry count ≥ threshold,
    densest first, additionally capped so the stripes' dense storage
    (``long_axis × 4`` bytes each) stays within ``budget_bytes`` — at
    10⁸-row matrices each column stripe costs ~400 MB, so the count cap
    alone would blow HBM."""
    mem_cap = int(budget_bytes // max(long_axis * 4, 1))
    max_stripes = min(max_stripes, mem_cap)
    if max_stripes <= 0:
        return np.empty(0, np.int64)
    cand = np.flatnonzero(counts >= threshold)
    if cand.size > max_stripes:
        cand = cand[np.argsort(-counts[cand], kind="stable")[:max_stripes]]
        cand = np.sort(cand)
    return cand.astype(np.int64)


def build_pallas_matrix(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    n_cols: int,
    depth_cap: int = 128,
    pad_nnz: Optional[int] = None,
    dtype=jnp.float32,
    dense_frac: float = 1.0 / 32.0,
    max_dense: int = 64,
    dense_budget_bytes: int = 512 << 20,
    col_permutation: bool = True,
    unit_values: bool | str = "auto",
) -> PallasSparseMatrix:
    """Build the tiled layout from host COO triples.

    Storage-class split (see :class:`PallasSparseMatrix`):

    1. columns with ≥ ``max(256, n_rows·dense_frac)`` entries (then rows
       with ≥ ``max(256, n_cols·dense_frac)``, from what remains) become
       dense MXU stripes, at most ``max_dense`` each and within
       ``dense_budget_bytes`` of dense storage per side — a bias column
       or popularity-head feature would otherwise drive its tiles' slot
       packing toward the cap (measured on zipf data: stripes 8 → 64 cut
       rmatvec 1.73×, the B orientation pays ~16× a hot column's max
       lane load otherwise);
    2. the rest lands in the tiled slot grids, at the cost-model depth
       (see ``_build_orientation``; ≤ ``depth_cap``);
    3. the residual overflow becomes a COMPACT spill COO (cost ∝ spill).
    """
    # Canonicalize ON HOST (dedup + sort + nnz-budget pad/validation) —
    # the old path built a full device COO first and read it straight
    # back, paying two transfers of the entire entry set for nothing.
    # Padding entries carry value 0, so the tiled build excludes them via
    # the live filter below; P.nnz still reports the padded budget.
    r_all, c_all, v_all = canonicalize_coo(
        rows, cols, vals, n_rows, n_cols, pad_nnz
    )
    host_coo = HostCoo(r_all, c_all, v_all, int(n_rows), int(n_cols))
    # Zero-valued entries contribute nothing; excluding them keeps explicit
    # zeros from faking a dense cell.
    live = np.flatnonzero(v_all != 0)
    r, c, v = r_all[live], c_all[live], v_all[live]

    # --- dense stripe extraction (columns first, rows from the rest) ------
    dense_col_ids = _extract_dense(
        np.bincount(c, minlength=n_cols),
        max(256, int(n_rows * dense_frac)), max_dense,
        n_rows, dense_budget_bytes,
    )
    in_dc = (
        np.isin(c, dense_col_ids) if dense_col_ids.size else
        np.zeros(len(c), bool)
    )
    # Zero-SIZE placeholder when absent (never read; has_dense_cols gates).
    dense_cols = np.zeros((len(dense_col_ids), n_rows), np.float32)
    if dense_col_ids.size:
        pos = np.searchsorted(dense_col_ids, c[in_dc])
        dense_cols[pos, r[in_dc]] = v[in_dc]
        r, c, v = r[~in_dc], c[~in_dc], v[~in_dc]

    dense_row_ids = _extract_dense(
        np.bincount(r, minlength=n_rows),
        max(256, int(n_cols * dense_frac)), max_dense,
        n_cols, dense_budget_bytes,
    )
    in_dr = (
        np.isin(r, dense_row_ids) if dense_row_ids.size else
        np.zeros(len(r), bool)
    )
    dense_rows = np.zeros((len(dense_row_ids), n_cols), np.float32)
    if dense_row_ids.size:
        pos = np.searchsorted(dense_row_ids, r[in_dr])
        dense_rows[pos, c[in_dr]] = v[in_dr]
        r, c, v = r[~in_dr], c[~in_dr], v[~in_dr]

    nbr = max(1, -(-n_rows // TILE_R))
    nbc = max(1, -(-n_cols // TILE_C))

    # --- optional column permutation (clustered-data balance) -------------
    # Relabel columns frequency-round-robin across windows when that
    # predicts fewer packed sublanes (summed over both orientations).
    # Spill/dense/cold paths keep ORIGINAL column ids; only the tiled
    # layouts see permuted ones, at the cost of one d-sized gather of the
    # input vector (matvec side) / output vector (rmatvec side).
    col_perm = None
    c_tiled = c
    if col_permutation and r.size and n_cols > WIN:
        m = _balance_col_perm(c, n_cols, nbc)
        c_perm = m[c]
        a_id = (_predict_a(r, c, nbr, nbc)
                + _predict_a(c, r, nbc, nbr))
        a_pm = (_predict_a(r, c_perm, nbr, nbc)
                + _predict_a(c_perm, r, nbc, nbr))
        # Engage only when the predicted slot-BYTE saving clearly exceeds
        # the gather traffic the permutation adds (a d-sized take of w per
        # matvec + an unpermute take per rmatvec).  The 8x margin covers
        # jnp.take's per-byte inefficiency vs pure streaming for
        # moderate-sized gathers; marginal predicted wins stay identity.
        saving_bytes = (a_id - a_pm) * (nbr * nbc) * WIN * (CODE_BYTES + 4)
        gather_bytes = 2 * (nbc * TILE_C) * 4
        if a_pm < a_id and saving_bytes >= 8 * gather_bytes:
            col_perm = m
            c_tiled = c_perm

    f_code, f_val, f_spill, a_f, depth_f = _build_orientation(
        r, c_tiled, v, nbr, nbc, depth_cap)
    b_code, b_val, b_spill, a_b, depth_b = _build_orientation(
        c_tiled, r, v, nbc, nbr, depth_cap)

    # Entries spilled from EITHER orientation go through the COO path for
    # BOTH directions (keeps matvec and rmatvec consistent with one X).
    spilled = np.union1d(f_spill, b_spill)
    if spilled.size:
        spill_coo = from_coo(
            r[spilled], c[spilled], v[spilled], n_rows, n_cols, dtype=dtype,
        )
        # Rebuild both orientations without the spilled entries so neither
        # tiled layout double-counts them (host-side, one extra pass).
        keep = np.ones(r.shape[0], bool)
        keep[spilled] = False
        f_code, f_val, fs2, a_f, depth_f = _build_orientation(
            r[keep], c_tiled[keep], v[keep], nbr, nbc, depth_cap,
            spill_cost_ratio=np.inf)
        b_code, b_val, bs2, a_b, depth_b = _build_orientation(
            c_tiled[keep], r[keep], v[keep], nbc, nbr, depth_cap,
            spill_cost_ratio=np.inf)
        assert fs2.size == 0 and bs2.size == 0, "re-spill after rebuild"
    else:
        spill_coo = from_coo(
            np.zeros(1, np.int64), np.zeros(1, np.int64),
            np.zeros(1, np.float32), n_rows, n_cols, dtype=dtype,
        )

    if col_perm is not None:
        inv = np.full(nbc * TILE_C, n_cols, np.int64)  # default: zero slot
        inv[col_perm] = np.arange(n_cols)
        perm_fwd = jnp.asarray(col_perm, jnp.int32)
        perm_inv = jnp.asarray(inv, jnp.int32)
    else:
        perm_fwd = jnp.zeros((1,), jnp.int32)
        perm_inv = jnp.zeros((1,), jnp.int32)

    # Binary-matrix fast path: when every TILED value is 1.0 (dense
    # stripes and spill keep their true values), drop the f32 val stream —
    # the kernels then move 2 bytes/slot instead of 6 ("auto"; False
    # forces the valued layout, e.g. for A/B measurement).
    tiled_vals = v[keep] if spilled.size else v
    unit = (
        unit_values == "auto"
        and (tiled_vals.size == 0 or bool(np.all(tiled_vals == 1.0)))
    ) or unit_values is True
    if unit_values is True and tiled_vals.size and not np.all(
        tiled_vals == 1.0
    ):
        raise ValueError("unit_values=True but tiled values are not all 1.0")
    if unit:
        f_val = np.zeros((1,), np.float32)
        b_val = np.zeros((1,), np.float32)

    return PallasSparseMatrix(
        f_code=jnp.asarray(f_code), f_val=jnp.asarray(f_val),
        b_code=jnp.asarray(b_code), b_val=jnp.asarray(b_val),
        spill=SpillData(
            spill_coo=spill_coo, has_spill=bool(spilled.size),
        ),
        dense_cols=jnp.asarray(dense_cols),
        dense_col_ids=jnp.asarray(dense_col_ids, jnp.int32),
        dense_rows=jnp.asarray(dense_rows),
        dense_row_ids=jnp.asarray(dense_row_ids, jnp.int32),
        col_perm_fwd=perm_fwd, col_perm_inv=perm_inv,
        host_coo=host_coo,
        n_rows=int(n_rows), n_cols=int(n_cols),
        nbr=nbr, nbc=nbc, a_f=a_f, a_b=a_b,
        depth_f=depth_f, depth_b=depth_b,
        has_dense_cols=bool(dense_col_ids.size),
        has_dense_rows=bool(dense_row_ids.size),
        has_col_perm=col_perm is not None,
        unit_vals=unit,
    )


def from_scipy_csr_pallas(csr, depth_cap: int = 128, pad_nnz: Optional[int] = None,
                          dtype=jnp.float32) -> PallasSparseMatrix:
    csr = csr.tocsr()
    csr.sum_duplicates()
    coo = csr.tocoo()
    return build_pallas_matrix(
        coo.row.astype(np.int64), coo.col.astype(np.int64), coo.data,
        csr.shape[0], csr.shape[1], depth_cap=depth_cap, pad_nnz=pad_nnz,
        dtype=dtype)


# ---------------------------------------------------------------------------
# Streaming support: uniform chunk layouts
# ---------------------------------------------------------------------------


class DroppedHostCoo(HostCoo):
    """Placeholder for streaming chunks whose host triples were freed.

    Streaming keeps MANY chunk layouts resident in host RAM; the canonical
    triples would roughly double that footprint for cold paths the trainer
    never touches.  Shape-class equality/hash (nnz == 0) still works, so jit
    caches behave; any cold-path use fails loudly instead of returning
    empty statistics.
    """

    def __init__(self, n_rows, n_cols):
        super().__init__(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), int(n_rows), int(n_cols),
        )

    def _dropped(self, *args, **kwargs):
        raise RuntimeError(
            "host COO triples were dropped for this streaming chunk; "
            "cold-path statistics (col_nnz / col_min_max / to_dense) are "
            "unavailable — compute them at ingest time instead"
        )

    col_nnz = _dropped
    col_min_max = _dropped
    to_dense = _dropped


def layout_to_host(P: PallasSparseMatrix) -> PallasSparseMatrix:
    """Pull every array leaf of a layout back to host numpy (streaming
    chunks live in host RAM and are ``device_put`` per optimizer pass)."""
    return jax.tree.map(np.asarray, P)


def _pad_axis(
    arr: np.ndarray, axis: int, target: int, constant_values=0
) -> np.ndarray:
    """Zero-pad by default; slot-CODE arrays must pass
    ``constant_values=EMPTY_MARK`` — an all-zero code pad reads as a VALID
    slot (win 0, ohi 0, lo 0) under the unit-value layout."""
    cur = arr.shape[axis]
    if cur == target:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - cur)
    return np.pad(arr, widths, constant_values=constant_values)


def uniformize_pallas_layouts(
    mats: list[PallasSparseMatrix],
    drop_host_coo: bool = True,
) -> list[PallasSparseMatrix]:
    """Pad a list of layouts over the SAME (n_rows, n_cols) shape to one
    common pytree structure and shape set, so one jitted program serves
    every chunk of a streamed dataset (out-of-core training — SURVEY.md §7
    "Host→device ingest bandwidth for 1B rows").

    Chunks differ in packed sublane counts (a_f/a_b), spill size, and dense
    stripe counts; all are padded to the max across chunks with inert
    entries (zero values contribute ``g·0 = 0`` in the kernels; zero-value
    dense stripes and spill entries likewise).  Chunks must be built with
    ``col_permutation=False`` — per-chunk permutations could not share one
    compiled program.  All leaves must already be host numpy
    (:func:`layout_to_host`); padding happens entirely on host.
    """
    if not mats:
        return []
    targets = uniformize_targets(mats)
    return [uniformize_one(m, targets, drop_host_coo) for m in mats]


def uniformize_targets(mats: list[PallasSparseMatrix]) -> dict:
    """The cross-chunk max shapes/flags :func:`uniformize_one` pads to.
    Reads only metadata and (for the mixed unit-vals case, inside
    uniformize_one) codes — cheap on disk-backed (memmap) leaves, which
    is what lets a spilling chunk store pad-and-respill ONE chunk at a
    time instead of materializing every padded layout at once."""
    m0 = mats[0]
    for m in mats[1:]:
        if (m.n_rows, m.n_cols) != (m0.n_rows, m0.n_cols):
            raise ValueError(
                f"chunk shape mismatch: {(m.n_rows, m.n_cols)} vs "
                f"{(m0.n_rows, m0.n_cols)}"
            )
    if any(m.has_col_perm for m in mats):
        raise ValueError(
            "streaming chunks must be built with col_permutation=False"
        )
    return {
        "a_f": max(m.a_f for m in mats),
        "a_b": max(m.a_b for m in mats),
        "kc": max(m.dense_col_ids.shape[0] for m in mats),
        "kr": max(m.dense_row_ids.shape[0] for m in mats),
        "any_spill": any(m.spill.has_spill for m in mats),
        "spill_budget": max(max(m.spill.spill_coo.nnz for m in mats), 1),
        "depth_f": max(m.depth_f for m in mats),
        "depth_b": max(m.depth_b for m in mats),
        # unit_vals must be uniform (it is pytree meta).  A mixed set
        # keeps the valued layout: unit chunks materialize val = 1.0 at
        # valid slots.
        "all_unit": all(m.unit_vals for m in mats),
    }


def uniformize_one(
    m: PallasSparseMatrix, t: dict, drop_host_coo: bool = True
) -> PallasSparseMatrix:
    """Pad ONE layout to the :func:`uniformize_targets` shapes."""
    from photon_ml_tpu.ops.sparse import pad_coo_triples

    all_unit = t["all_unit"]
    if m.unit_vals and not all_unit:
        m = dataclasses.replace(
            m,
            f_val=(np.asarray(m.f_code) >= 0).astype(np.float32),
            b_val=(np.asarray(m.b_code) >= 0).astype(np.float32),
            unit_vals=False,
        )
    sc = m.spill.spill_coo
    rows, cols, vals = pad_coo_triples(
        np.asarray(sc.row_ids), np.asarray(sc.col_ids),
        np.asarray(sc.values), t["spill_budget"],
    )
    spill = SpillData(
        spill_coo=SparseMatrix(
            row_ids=rows, col_ids=cols, values=vals,
            n_rows=m.n_rows, n_cols=m.n_cols,
        ),
        has_spill=t["any_spill"],
    )
    host_coo = (
        DroppedHostCoo(m.n_rows, m.n_cols) if drop_host_coo
        else m.host_coo
    )
    return dataclasses.replace(
        m,
        f_code=_pad_axis(np.asarray(m.f_code), 2, t["a_f"],
                         constant_values=EMPTY_MARK),
        f_val=(
            np.asarray(m.f_val) if all_unit
            else _pad_axis(np.asarray(m.f_val), 2, t["a_f"])
        ),
        b_code=_pad_axis(np.asarray(m.b_code), 2, t["a_b"],
                         constant_values=EMPTY_MARK),
        b_val=(
            np.asarray(m.b_val) if all_unit
            else _pad_axis(np.asarray(m.b_val), 2, t["a_b"])
        ),
        spill=spill,
        dense_cols=_pad_axis(np.asarray(m.dense_cols), 0, t["kc"]),
        dense_col_ids=_pad_axis(
            np.asarray(m.dense_col_ids), 0, t["kc"]
        ),
        dense_rows=_pad_axis(np.asarray(m.dense_rows), 0, t["kr"]),
        dense_row_ids=_pad_axis(
            np.asarray(m.dense_row_ids), 0, t["kr"]
        ),
        host_coo=host_coo,
        a_f=t["a_f"], a_b=t["a_b"],
        depth_f=t["depth_f"], depth_b=t["depth_b"],
        has_dense_cols=t["kc"] > 0,
        has_dense_rows=t["kr"] > 0,
    )
