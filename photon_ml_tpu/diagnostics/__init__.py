from photon_ml_tpu.diagnostics.report import (
    TrainingReport,
    bootstrap_metric_ci,
    feature_importance,
    hosmer_lemeshow,
)

__all__ = [
    "TrainingReport",
    "bootstrap_metric_ci",
    "feature_importance",
    "hosmer_lemeshow",
]
