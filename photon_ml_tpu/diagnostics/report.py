"""Training diagnostics reports.

The reference's older upstream versions shipped a ``diagnostics`` package
producing HTML training reports — bootstrap confidence intervals,
Hosmer–Lemeshow calibration, feature importance — later removed upstream
(SURVEY.md §5.1 [LOW]).  Rebuilt here as a small host-side module: all
statistics are one-shot numpy over scores/labels already on host, so
nothing touches the device.

Outputs: a JSON artifact (machine-readable, the source of truth) and a
self-contained HTML page (no external assets — the reference's reports
were HDFS-browsable single files; these are scp-able single files).
"""

from __future__ import annotations

import dataclasses
import html
import json
import os
from typing import Callable, Optional, Sequence

import numpy as np


def hosmer_lemeshow(
    scores: np.ndarray,
    labels: np.ndarray,
    n_groups: int = 10,
    scores_are_margins: bool = True,
) -> dict:
    """Hosmer–Lemeshow goodness-of-fit for a binary classifier.

    ``scores_are_margins`` (default): scores are raw margins and are
    squashed through the logistic link; pass False when they are already
    probabilities.  (Explicit, not range-detected: a regularized model's
    margins can legitimately all fall inside [0, 1], where a heuristic
    would silently treat them as probabilities and report a bogus
    statistic.)  Rows are cut into ``n_groups`` deciles of predicted
    probability; the statistic is ``Σ (O-E)²/(E(1-E/n))`` over groups,
    asymptotically χ²(n_groups-2) under good calibration.  Returns the
    statistic, degrees of freedom, an approximate p-value, and the
    per-decile table.
    """
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels, np.float64)
    p = 1.0 / (1.0 + np.exp(-scores)) if scores_are_margins else scores
    if not scores_are_margins and (p.min() < 0.0 or p.max() > 1.0):
        raise ValueError(
            "scores_are_margins=False but scores fall outside [0, 1]"
        )
    order = np.argsort(p, kind="stable")
    p, y = p[order], labels[order]
    edges = np.linspace(0, len(p), n_groups + 1).astype(int)
    stat = 0.0
    table = []
    for g in range(n_groups):
        lo, hi = edges[g], edges[g + 1]
        if hi <= lo:
            continue
        n = hi - lo
        observed = float(np.sum(y[lo:hi]))
        expected = float(np.sum(p[lo:hi]))
        denom = expected * (1.0 - expected / n)
        if denom > 1e-12:
            stat += (observed - expected) ** 2 / denom
        table.append({
            "group": g,
            "n": int(n),
            "mean_predicted": float(np.mean(p[lo:hi])),
            "observed_rate": observed / n,
        })
    dof = max(n_groups - 2, 1)
    return {
        "statistic": float(stat),
        "dof": dof,
        "p_value": float(_chi2_sf(stat, dof)),
        "table": table,
    }


def _chi2_sf(x: float, k: int) -> float:
    """Survival function of χ²(k) — scipy when present, else the
    Wilson–Hilferty normal approximation (fine for a report)."""
    try:
        from scipy.stats import chi2

        return float(chi2.sf(x, k))
    except Exception:
        import math

        if x <= 0:
            return 1.0
        z = ((x / k) ** (1.0 / 3.0) - (1.0 - 2.0 / (9.0 * k))) / np.sqrt(
            2.0 / (9.0 * k)
        )
        return float(0.5 * (1.0 - math.erf(z / np.sqrt(2.0))))


def bootstrap_metric_ci(
    metric_fn: Callable[[np.ndarray, np.ndarray], float],
    scores: np.ndarray,
    labels: np.ndarray,
    n_boot: int = 200,
    alpha: float = 0.05,
    seed: int = 0,
) -> dict:
    """Percentile bootstrap CI for any metric(scores, labels) — the
    reference's report CIs.  Resampling is row-wise with replacement;
    degenerate resamples (single-class for AUC-like metrics) are skipped
    via NaN filtering."""
    scores = np.asarray(scores)
    labels = np.asarray(labels)
    rng = np.random.default_rng(seed)
    n = len(scores)
    stats = []
    for _ in range(n_boot):
        idx = rng.integers(0, n, size=n)
        try:
            v = float(metric_fn(scores[idx], labels[idx]))
        except Exception:
            continue
        if np.isfinite(v):
            stats.append(v)
    stats = np.asarray(stats)
    point = float(metric_fn(scores, labels))
    if stats.size == 0:
        return {"point": point, "lo": point, "hi": point, "n_boot": 0}
    return {
        "point": point,
        "lo": float(np.quantile(stats, alpha / 2)),
        "hi": float(np.quantile(stats, 1 - alpha / 2)),
        "n_boot": int(stats.size),
    }


def feature_importance(
    coefficients: np.ndarray,
    feature_std: Optional[np.ndarray] = None,
    names: Optional[Sequence[str]] = None,
    top_k: int = 25,
    name_fn: Optional[Callable[[int], str]] = None,
) -> list:
    """|coefficient| x feature-std importances (the standardized effect
    size the reference's report ranked by), top-k descending.

    ``name_fn(index) -> name`` resolves names lazily for just the top-k —
    at millions of features, materializing a full ``names`` list only to
    label 25 rows would dominate the report cost."""
    w = np.asarray(coefficients, np.float64)
    std = (
        np.ones_like(w) if feature_std is None
        else np.asarray(feature_std, np.float64)
    )
    imp = np.abs(w) * std
    order = np.argsort(-imp)[:top_k]

    def _name(j: int) -> str:
        if names is not None:
            return str(names[j])
        if name_fn is not None:
            return str(name_fn(j))
        return f"feature_{j}"

    return [
        {
            "feature": _name(int(j)),
            "coefficient": float(w[j]),
            "importance": float(imp[j]),
        }
        for j in order
        if imp[j] > 0
    ]


@dataclasses.dataclass
class TrainingReport:
    """Collects per-run diagnostics and writes report.json + report.html."""

    task: str
    sections: list = dataclasses.field(default_factory=list)

    def add_convergence(self, lam, values, grad_norms) -> None:
        values = [float(v) for v in np.asarray(values) if np.isfinite(v)]
        gnorms = [float(g) for g in np.asarray(grad_norms) if np.isfinite(g)]
        self.sections.append({
            "kind": "convergence",
            "lambda": float(lam),
            "values": values,
            "grad_norms": gnorms,
            "iterations": max(len(values) - 1, 0),
        })

    def add_metric(self, name: str, lam, ci: dict) -> None:
        self.sections.append({
            "kind": "metric", "name": name, "lambda": float(lam), **ci,
        })

    def add_calibration(self, lam, hl: dict) -> None:
        self.sections.append({
            "kind": "calibration", "lambda": float(lam), **hl,
        })

    def add_importance(self, lam, importances: list) -> None:
        self.sections.append({
            "kind": "feature_importance", "lambda": float(lam),
            "top": importances,
        })

    # -- output --------------------------------------------------------
    def to_json(self) -> dict:
        return {"task": self.task, "sections": self.sections}

    def save(self, output_dir: str) -> tuple[str, str]:
        os.makedirs(output_dir, exist_ok=True)
        jpath = os.path.join(output_dir, "report.json")
        hpath = os.path.join(output_dir, "report.html")
        with open(jpath, "w") as f:
            json.dump(self.to_json(), f, indent=2)
        with open(hpath, "w") as f:
            f.write(self._render_html())
        return jpath, hpath

    def _render_html(self) -> str:
        parts = [
            "<!doctype html><meta charset='utf-8'>",
            "<title>photon_ml_tpu training report</title>",
            "<style>body{font:14px sans-serif;margin:2em;max-width:60em}"
            "table{border-collapse:collapse;margin:1em 0}"
            "td,th{border:1px solid #ccc;padding:2px 8px;text-align:right}"
            "th{background:#f0f0f0}caption{font-weight:bold;text-align:left}"
            "svg{background:#fafafa;border:1px solid #eee}</style>",
            f"<h1>Training report — {html.escape(self.task)}</h1>",
        ]
        for s in self.sections:
            kind = s["kind"]
            lam = s.get("lambda")
            if kind == "convergence":
                parts.append(
                    f"<h2>Convergence (λ={lam:g}, "
                    f"{s['iterations']} iterations)</h2>"
                )
                parts.append(_sparkline(s["values"]))
                parts.append(_kv_table(
                    "objective value per iteration",
                    {str(i): f"{v:.8g}" for i, v in enumerate(s["values"])},
                ))
            elif kind == "metric":
                parts.append(
                    f"<h2>{html.escape(s['name'])} (λ={lam:g})</h2>"
                    f"<p>{s['point']:.6f} "
                    f"(95% CI [{s['lo']:.6f}, {s['hi']:.6f}], "
                    f"{s['n_boot']} bootstrap resamples)</p>"
                )
            elif kind == "calibration":
                parts.append(
                    f"<h2>Hosmer–Lemeshow calibration (λ={lam:g})</h2>"
                    f"<p>χ²={s['statistic']:.3f}, dof={s['dof']}, "
                    f"p={s['p_value']:.4f}</p>"
                )
                rows = "".join(
                    f"<tr><td>{r['group']}</td><td>{r['n']}</td>"
                    f"<td>{r['mean_predicted']:.4f}</td>"
                    f"<td>{r['observed_rate']:.4f}</td></tr>"
                    for r in s["table"]
                )
                parts.append(
                    "<table><caption>deciles</caption>"
                    "<tr><th>group</th><th>n</th><th>mean predicted</th>"
                    "<th>observed rate</th></tr>" + rows + "</table>"
                )
            elif kind == "feature_importance":
                parts.append(f"<h2>Feature importance (λ={lam:g})</h2>")
                rows = "".join(
                    f"<tr><td style='text-align:left'>"
                    f"{html.escape(r['feature'])}</td>"
                    f"<td>{r['coefficient']:.6g}</td>"
                    f"<td>{r['importance']:.6g}</td></tr>"
                    for r in s["top"]
                )
                parts.append(
                    "<table><tr><th>feature</th><th>coefficient</th>"
                    "<th>|coef|·std</th></tr>" + rows + "</table>"
                )
        return "\n".join(parts)


def _kv_table(caption: str, kv: dict) -> str:
    rows = "".join(
        f"<tr><td>{html.escape(k)}</td><td>{html.escape(str(v))}</td></tr>"
        for k, v in kv.items()
    )
    return (
        f"<table><caption>{html.escape(caption)}</caption>"
        "<tr><th>iteration</th><th>value</th></tr>" + rows + "</table>"
    )


def _sparkline(values, width=480, height=80) -> str:
    """Inline SVG line of the convergence trace (no external assets)."""
    v = np.asarray([x for x in values if np.isfinite(x)], np.float64)
    if v.size < 2:
        return ""
    lo, hi = float(v.min()), float(v.max())
    span = hi - lo or 1.0
    xs = np.linspace(4, width - 4, v.size)
    ys = height - 4 - (v - lo) / span * (height - 8)
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(xs, ys))
    return (
        f"<svg width='{width}' height='{height}'>"
        f"<polyline points='{pts}' fill='none' "
        "stroke='#36c' stroke-width='1.5'/></svg>"
    )
