"""Continuous train→serve loop: delta publishing, online refinement,
freshness SLOs.

The train→serve handoff used to be a full model directory plus a full
fingerprint-verified reload — a model is only as fresh as the slowest
end-to-end retrain+swap.  This package closes the loop (docs/freshness.md):

- :mod:`photon_ml_tpu.freshness.delta` — diff two models into a compact,
  self-digested artifact holding only the changed entities, and apply it
  back with bitwise parity against a full reload.
- :mod:`photon_ml_tpu.freshness.publisher` — crash-safe publication:
  append-only journal (``tuning/state.py`` style) around an
  atomic-rename artifact write, so a crash mid-publish resumes exactly;
  bounded retention (keep-last-K pruning + journal compaction) gated on
  the per-subscriber ack sidecar, so a root never outgrows its disk and
  never drops a delta a registered subscriber still needs.
- :mod:`photon_ml_tpu.freshness.applier` — subscribe side: watch a
  publication root and hot-apply new deltas into a live service.
- :mod:`photon_ml_tpu.freshness.online` — seeded per-entity SGD/AdaGrad
  refinement consuming labeled events between full CD sweeps,
  warm-started from the serving model, publishing through the same
  delta path.

Freshness is measured, not assumed: every publication carries the wall
epoch of its newest event, and the apply side records
``freshness_event_to_servable_seconds`` the moment the delta is live.
"""

from photon_ml_tpu.freshness.delta import (  # noqa: F401
    DeltaBaseMismatchError,
    DeltaError,
    DeltaFormatError,
    ModelDelta,
    apply_delta,
    diff_game_models,
    diff_model_dirs,
    model_table_checksums,
    read_delta,
    write_delta,
)
from photon_ml_tpu.freshness.publisher import (  # noqa: F401
    DeltaPublisher,
    Publication,
    read_acks,
    read_publications,
    write_ack,
)
from photon_ml_tpu.freshness.applier import DeltaApplier  # noqa: F401
from photon_ml_tpu.freshness.online import (  # noqa: F401
    LabeledEvent,
    OnlineRefiner,
    RefinerConfig,
)
