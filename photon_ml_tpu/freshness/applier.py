"""The subscribe side of the publish/subscribe loop.

:class:`DeltaApplier` watches a publication root (the journal a
:class:`~photon_ml_tpu.freshness.publisher.DeltaPublisher` writes) and
applies every newly-committed delta to a live
:class:`~photon_ml_tpu.serving.service.ScoringService` in sequence
order, via the service's delta reload path (``swap_delta`` — bitwise
parity, zero dropped requests, one-step rollback).  It reads the
journal READ-ONLY: a subscriber never repairs or advances the
publisher's state.

Freshness accounting lives here and in the swapper: the swapper records
``freshness_event_to_servable_seconds`` at the commit instant; the
applier keeps the STALENESS gauges current between applies —
``freshness_model_age_seconds`` is how long ago the newest servable
event happened, and it grows until the next delta lands (the "model is
stale — now what?" runbook in docs/freshness.md keys off it).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.freshness.publisher import (
    SNAPSHOT_MODEL_DIR,
    Publication,
    read_publications,
    write_ack,
)


class DeltaApplier:
    """Apply committed publications from ``root`` to ``service``.

    Use :meth:`poll_once` synchronously (the selfcheck and tests do) or
    :meth:`start`/:meth:`stop` for a background polling thread.  A
    publication whose apply comes back ``rolled_back`` (torn artifact,
    base mismatch, failed probe) is NOT retried — its sequence number
    is recorded as failed and the loop moves on, because re-applying
    the same artifact to the same base deterministically fails the same
    way; the operator escalates to a full reload (the runbook).

    Pass a ``subscriber_id`` to register with the root's ack sidecar
    (``acks/<subscriber_id>``): the applier acks its high-water
    ``applied_seq`` after every advance, and the publisher's retention
    then refuses to prune any publication this subscriber has not
    consumed yet.  Registration happens at construction (acked seq 0),
    so a freshly-attached subscriber immediately pins the whole root.
    Failed sequences are acked too — they are never retried, so
    holding their artifacts would pin the root forever.
    """

    def __init__(
        self,
        service,
        root: str,
        poll_interval_s: float = 0.25,
        subscriber_id: Optional[str] = None,
    ):
        self._service = service
        self.root = root
        self.poll_interval_s = float(poll_interval_s)
        self.subscriber_id = subscriber_id
        self.applied_seq = 0
        if subscriber_id is not None:
            write_ack(root, subscriber_id, self.applied_seq)
        self.applied = 0
        self.failed: List[int] = []
        #: wall epoch of the newest event now servable (staleness anchor).
        self._servable_event_wall: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- synchronous ---------------------------------------------------------
    def pending(self) -> List[Publication]:
        """Committed publications not yet applied, in sequence order."""
        return [
            p for p in read_publications(self.root)
            if p.seq > self.applied_seq
        ]

    def poll_once(self) -> list:
        """Apply every pending publication; returns their SwapResults
        (empty when the root has nothing new) and refreshes the
        staleness gauges either way."""
        tel = telemetry_mod.current()
        results = []
        seq_before = self.applied_seq
        for pub in self.pending():
            result = self._apply(pub)
            results.append(result)
            self.applied_seq = pub.seq
            if result.status == "swapped":
                self.applied += 1
                if pub.event_wall_epoch is not None:
                    self._servable_event_wall = pub.event_wall_epoch
            else:
                self.failed.append(pub.seq)
                tel.counter("freshness_apply_failures_total").inc()
                tel.event(
                    "freshness.apply_failed",
                    seq=pub.seq,
                    path=pub.path,
                    stage=result.stage,
                    reason=result.reason,
                )
        if self.subscriber_id is not None and self.applied_seq > seq_before:
            write_ack(self.root, self.subscriber_id, self.applied_seq)
        self._refresh_staleness()
        return results

    def _apply(self, pub: Publication):
        """One publication -> the matching reload path: deltas patch
        the live model (``mode="delta"``), snapshots full-reload from
        the artifact's ``model/`` subdir (a snapshot is a complete
        model, not a patch — applying one re-bases the subscriber)."""
        if pub.kind == "snapshot":
            return self._service.reload(
                os.path.join(pub.path, SNAPSHOT_MODEL_DIR)
            )
        return self._service.reload(pub.path, mode="delta")

    def _refresh_staleness(self) -> None:
        if self._servable_event_wall is None:
            return
        now_wall = time.time()
        telemetry_mod.current().gauge(
            "freshness_model_age_seconds"
        ).set(max(0.0, now_wall - self._servable_event_wall))

    # -- background ----------------------------------------------------------
    def start(self) -> "DeltaApplier":
        if self._thread is not None:
            raise RuntimeError("applier already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="freshness-applier", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — keep polling
                # A transient reload refusal (SwapInProgressError from a
                # concurrent operator /reload) must not kill the loop.
                telemetry_mod.current().event(
                    "freshness.poll_error",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
            self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "DeltaApplier":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stats(self) -> dict:
        return {
            "root": self.root,
            "subscriber_id": self.subscriber_id,
            "applied_seq": self.applied_seq,
            "applied": self.applied,
            "failed": list(self.failed),
            "servable_event_wall": self._servable_event_wall,
        }
