"""Freshness CLI: the continuous train→serve loop selfcheck.

::

    python -m photon_ml_tpu.freshness --selfcheck

runs the WHOLE loop, end to end, device-free beyond the CPU backend:

1. "Train" v1 (the synthetic GAME workload every serving selfcheck
   uses) and bring it up on a live 2-replica supervised service.
2. Simulate CONCEPT DRIFT: labeled events whose labels come from a
   drifted ground-truth model, not the serving one.
3. Online-refine the touched entities from those events
   (:class:`~photon_ml_tpu.freshness.online.OnlineRefiner`).
4. Delta-publish the refinement crash-safely
   (:class:`~photon_ml_tpu.freshness.publisher.DeltaPublisher`) and
   hot-apply it through the subscribe side
   (:class:`~photon_ml_tpu.freshness.applier.DeltaApplier`) — both
   firing MID-PHASE of the ``freshness`` loadgen scenario, while
   open-loop traffic flows.

And asserts the contracts that make the loop trustworthy:

- ZERO failed requests across the whole scenario (publish and apply
  are invisible to traffic);
- the delta-patched serving tables are BITWISE-IDENTICAL to a full
  save→load of the refined model (delta apply is a pure optimization,
  never a divergence);
- one-step rollback restores the pre-delta version, bitwise;
- ``freshness_event_to_servable_seconds`` (the freshness SLO) landed
  in metrics.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np


def _drift_events(serving, truth, n_events: int, seed: int, now_wall: float):
    """Labeled events over the FIRST slice of the entity space, labels
    drawn from the drifted ``truth`` model's mean response — what a real
    click log would say after the world moved under the serving model."""
    from photon_ml_tpu.freshness.online import LabeledEvent
    from photon_ml_tpu.serving.runtime import _host_mean

    rng = np.random.default_rng(seed)
    truth_re = truth.model.models["per_entity"]
    truth_fixed = np.asarray(
        truth.model.models["fixed"].model.coefficients.means, np.float32
    )
    events = []
    for _ in range(n_events):
        entity = f"u{rng.integers(max(8, serving.n_entities // 8))}"
        xf = rng.normal(size=serving.fixed_dim).astype(np.float32)
        xr = rng.normal(size=serving.re_dim).astype(np.float32)
        row = np.zeros(serving.re_dim, np.float32)
        pair = truth_re.coefficients.get(entity)
        if pair is not None:
            cols, vals = pair
            row[np.asarray(cols, np.int64)] = vals
        margin = float(np.dot(truth_fixed, xf) + np.dot(row, xr))
        label = float(
            _host_mean(truth.model.task, np.array([margin], np.float32))[0]
        )
        events.append(LabeledEvent(
            features={serving.fixed_shard: xf, serving.re_shard: xr},
            ids={serving.entity_key: entity},
            label=label,
            wall_epoch=now_wall,
        ))
    return events


def run_selfcheck(out_dir: str) -> list[str]:
    """The end-to-end freshness pass.  Returns failure strings."""
    import time

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.freshness.applier import DeltaApplier
    from photon_ml_tpu.freshness.delta import model_table_checksums
    from photon_ml_tpu.freshness.online import OnlineRefiner, RefinerConfig
    from photon_ml_tpu.freshness.publisher import DeltaPublisher
    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    failures: list[str] = []
    serving_w = SyntheticWorkload(n_entities=64, seed=3)
    truth_w = SyntheticWorkload(n_entities=64, seed=4)  # the drifted world
    v1_dir = os.path.join(out_dir, "models", "v1")
    refined_dir = os.path.join(out_dir, "models", "refined")
    save_game_model(serving_w.model, serving_w.index_maps, v1_dir)

    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)

    def factory() -> ScoringRuntime:
        return ScoringRuntime.load(v1_dir, rt_cfg)

    def make_request(i: int, phase) -> dict:
        req = serving_w.request(i)
        if phase.entity_pool is not None:
            lo, hi = phase.entity_pool
            span = max(1, int((hi - lo) * serving_w.n_entities))
            req["ids"][serving_w.entity_key] = (
                f"u{int(lo * serving_w.n_entities) + i % span}"
            )
        return req

    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="freshness-selfcheck"
    ) as tel:
        base_model, _ = ScoringRuntime.load_model(v1_dir)
        refiner = OnlineRefiner(base_model, RefinerConfig(seed=7))
        event_wall = time.time()
        events = _drift_events(
            serving_w, truth_w, n_events=60, seed=11, now_wall=event_wall
        )
        publisher = DeltaPublisher(os.path.join(out_dir, "publications"))
        supervisor = ReplicaSupervisor(
            factory, n_replicas=2, probe_interval_s=0.1
        )
        service = ScoringService(supervisor, BatcherConfig(
            max_batch_size=8, max_wait_us=2_000, max_queue=256,
        ))
        applier = DeltaApplier(service, publisher.root)
        with service:
            def publish_delta() -> dict:
                refiner.consume(events)
                pub = refiner.publish(publisher)
                return {"seq": pub.seq, "rows": pub.n_changed_rows}

            def apply_delta_action() -> dict:
                results = applier.poll_once()
                return {
                    "applied": [r.status for r in results],
                    "version": service.swapper.version,
                }

            report = loadgen.run_scenario(
                service.submit, make_request,
                loadgen.SCENARIOS["freshness"],
                base_rate_rps=120.0,
                actions={
                    "publish_delta": publish_delta,
                    "apply_delta": apply_delta_action,
                },
            )
            if report.errors or report.rejected:
                failures.append(
                    f"freshness scenario saw {report.errors} errors and "
                    f"{report.rejected} rejections (expected 0/0) across "
                    f"{report.completed} requests"
                )
            if report.completed < 100:
                failures.append(
                    f"freshness scenario completed only "
                    f"{report.completed} requests; the pass did not "
                    "exercise the path"
                )
            for key in ("publish_delta", "apply_delta"):
                if not isinstance(report.actions.get(key), dict):
                    failures.append(
                        f"scenario action {key} did not run cleanly: "
                        f"{report.actions.get(key)!r}"
                    )
            if applier.applied != 1 or applier.failed:
                failures.append(
                    f"applier applied={applier.applied} "
                    f"failed={applier.failed}, expected exactly one "
                    "clean apply"
                )
            if service.swapper.version != 2:
                failures.append(
                    "expected model_version 2 after the delta apply, "
                    f"got {service.swapper.version}"
                )

            # Bitwise parity against a FULL save->load of the refined
            # model: the delta path must be a pure optimization.
            save_game_model(
                refiner.refined_model(), serving_w.index_maps, refined_dir
            )
            full_model, _ = ScoringRuntime.load_model(refined_dir)
            want = model_table_checksums(full_model)
            for rep in supervisor.replicas:
                got = model_table_checksums(rep.batcher.runtime.model)
                if got != want:
                    failures.append(
                        f"replica {rep.rid}: delta-patched tables are "
                        "NOT bitwise-identical to a full reload of the "
                        f"refined model ({got} != {want})"
                    )
            served = supervisor.replicas[0].batcher.runtime.model
            pe_served = served.models["per_entity"].coefficients
            pe_full = full_model.models["per_entity"].coefficients
            if set(pe_served) != set(pe_full) or any(
                pe_served[k][0].tobytes() != pe_full[k][0].tobytes()
                or pe_served[k][1].tobytes() != pe_full[k][1].tobytes()
                for k in pe_full
            ):
                failures.append(
                    "per-entity coefficient arrays diverge from the "
                    "full reload (checksum collision?)"
                )

            # One-step rollback restores the pre-delta version, bitwise.
            rb = service.swapper.rollback()
            if service.swapper.version != 1:
                failures.append(
                    f"rollback -> {rb.status}, version "
                    f"{service.swapper.version} (expected 1)"
                )
            base_want = model_table_checksums(base_model)
            for rep in supervisor.replicas:
                if model_table_checksums(
                    rep.batcher.runtime.model
                ) != base_want:
                    failures.append(
                        f"replica {rep.rid}: rollback did not restore "
                        "the pre-delta tables bitwise"
                    )
        snap = tel.snapshot()

    counters = snap["counters"]
    for name, minimum in (
        ("freshness_deltas_published_total", 1),
        ("freshness_deltas_applied_total", 1),
        ("freshness_online_events_total", 1),
        ("serving_swaps_total", 1),
    ):
        if counters.get(name, 0) < minimum:
            failures.append(
                f"{name} = {counters.get(name, 0)}, expected >= {minimum}"
            )
    metrics_path = os.path.join(out_dir, "metrics.json")
    try:
        with open(metrics_path) as f:
            metrics = json.load(f)
        hist = metrics.get("histograms", {}).get(
            "freshness_event_to_servable_seconds"
        )
        if not hist or not hist.get("count"):
            failures.append(
                "freshness_event_to_servable_seconds missing/empty in "
                "metrics.json — the freshness SLO was not measured"
            )
    except (OSError, json.JSONDecodeError) as exc:
        failures.append(f"metrics.json unreadable: {exc}")
    return failures


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.freshness",
        description="continuous train->serve loop (delta publishing, "
        "online refinement, freshness SLOs)",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument(
        "--output-dir",
        help="keep the selfcheck artifacts (models, publications, "
        "metrics.json) here instead of a temp dir",
    )
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not args.selfcheck:
        build_arg_parser().print_help()
        return 2
    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
        failures = run_selfcheck(args.output_dir)
    else:
        with tempfile.TemporaryDirectory(
            prefix="photon_freshness_selfcheck_"
        ) as td:
            failures = run_selfcheck(td)
    if failures:
        print("freshness selfcheck FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("freshness selfcheck PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
