"""Model deltas: diff two models into a compact artifact, apply it back.

A delta artifact is a directory:

    delta.json                  manifest (self-digested, shm_model style)
    segment-<coordinate>.npz    one payload file per CHANGED coordinate

The manifest records, per coordinate, an ORDER-INDEPENDENT table
checksum of the base and of the target (sha256 over sorted entities'
exact float32/int32 bit patterns — unlike the save-order Avro
fingerprints in ``io/game_store.py``, these are computable from any
in-memory model, so online refinement can diff without a disk round
trip).  Apply verifies the serving model against every base checksum
before touching anything ("this delta was diffed against a different
base" is a refusal, not a corruption), patches only the changed
entities, and verifies the result against the target checksums — so a
delta-applied model is PROVABLY bitwise-identical to a full reload of
the target.

Random-effect segments hold only the changed entities (CSR-style
concatenated cols/vals plus per-entity spans); fixed-effect segments
hold the replacement dense vector (a fixed coordinate has no per-entity
granularity).  Every segment carries its sha256 in the manifest; the
manifest carries a digest of itself — torn writes and tampering both
fail loudly at read.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io as io_lib
import json
import os
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

DELTA_FORMAT = "photon-model-delta-v1"
MANIFEST_FILE = "delta.json"


class DeltaError(RuntimeError):
    """Base class for delta refusals — every message names the artifact
    or model at fault and what the operator should do about it."""


class DeltaFormatError(DeltaError):
    """The artifact itself is unreadable: torn, tampered, or not a
    delta.  Re-publish from the source models; never apply it."""


class DeltaBaseMismatchError(DeltaError):
    """The artifact is intact but was diffed against a DIFFERENT base
    than the model it is being applied to.  Applying it would produce a
    model that matches neither endpoint — do a full reload instead."""


# ---------------------------------------------------------------------------
# Order-independent table checksums
# ---------------------------------------------------------------------------

def _canon_cols(cols) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(cols, np.int32))


def _canon_vals(vals) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(vals, np.float32))


def fixed_table_checksum(glm: GeneralizedLinearModel) -> str:
    """sha256 over the dense float32 coefficient (and variance) bit
    patterns of one fixed-effect GLM."""
    h = hashlib.sha256()
    h.update(str(glm.task).encode())
    h.update(b"\x00MEANS\x00")
    h.update(_canon_vals(glm.coefficients.means).tobytes())
    h.update(b"\x00VARIANCES\x00")
    if glm.coefficients.variances is not None:
        h.update(_canon_vals(glm.coefficients.variances).tobytes())
    return h.hexdigest()


def random_table_checksum(sub: RandomEffectModel) -> str:
    """sha256 over (entity, cols, vals, variances) for every entity in
    SORTED entity order — two tables with the same content hash equal
    regardless of dict insertion order, so an in-memory refined model
    and its disk round trip agree."""
    h = hashlib.sha256()
    h.update(str(sub.task).encode())
    for entity in sorted(sub.coefficients, key=str):
        cols, vals = sub.coefficients[entity]
        h.update(b"\x00ENTITY\x00")
        h.update(str(entity).encode())
        h.update(b"\x00")
        h.update(_canon_cols(cols).tobytes())
        h.update(_canon_vals(vals).tobytes())
        var = None if sub.variances is None else sub.variances.get(entity)
        if var is None:
            h.update(b"\x00")
        else:
            h.update(b"\x01")
            h.update(_canon_vals(var).tobytes())
    return h.hexdigest()


def model_table_checksums(model: GameModel) -> Dict[str, str]:
    """Coordinate name → order-independent table checksum."""
    out = {}
    for name, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            out[name] = fixed_table_checksum(sub.model)
        else:
            out[name] = random_table_checksum(sub)
    return out


# ---------------------------------------------------------------------------
# The delta value object
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CoordinateDelta:
    """One coordinate's change set.  ``changed_entities`` /
    ``removed`` carry the random-effect payload; ``means`` /
    ``variances`` the fixed-effect replacement.  An unchanged
    coordinate has neither — it rides along only so apply can verify
    its base checksum."""

    name: str
    kind: str  # "fixed" | "random"
    feature_shard: str
    base_checksum: str
    target_checksum: str
    entity_key: str = ""
    n_features: int = 0
    # random-effect payload: entity -> (cols int32, vals float32,
    # variances float32 | None)
    changed_entities: Optional[Dict[str, Tuple]] = None
    removed: Tuple[str, ...] = ()
    # fixed-effect payload
    means: Optional[np.ndarray] = None
    variances: Optional[np.ndarray] = None

    @property
    def changed(self) -> bool:
        return self.base_checksum != self.target_checksum

    @property
    def n_changed(self) -> int:
        if self.kind == "fixed":
            return 1 if self.changed else 0
        return len(self.changed_entities or {}) + len(self.removed)


@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """An ordered set of coordinate deltas between two structurally
    identical models, plus the wall epoch of the newest event the
    target model has absorbed (the freshness anchor)."""

    task: str
    coordinates: List[CoordinateDelta]
    event_wall_epoch: Optional[float] = None

    @property
    def changed_coordinates(self) -> List[CoordinateDelta]:
        return [c for c in self.coordinates if c.changed]

    @property
    def n_changed_rows(self) -> int:
        return sum(c.n_changed for c in self.coordinates)

    @property
    def empty(self) -> bool:
        return not any(c.changed for c in self.coordinates)


# ---------------------------------------------------------------------------
# Diff
# ---------------------------------------------------------------------------

def _rows_equal(a: Tuple, b: Tuple, va, vb) -> bool:
    if _canon_cols(a[0]).tobytes() != _canon_cols(b[0]).tobytes():
        return False
    if _canon_vals(a[1]).tobytes() != _canon_vals(b[1]).tobytes():
        return False
    if (va is None) != (vb is None):
        return False
    if va is not None and _canon_vals(va).tobytes() != _canon_vals(vb).tobytes():
        return False
    return True


def _structural_refusal(name: str, why: str) -> DeltaError:
    return DeltaError(
        f"cannot delta coordinate {name!r}: {why} — a delta expresses "
        "changed coefficient VALUES only; structural changes (added/"
        "removed coordinates, kind or shard changes) need a full model "
        "publish + full reload"
    )


def diff_game_models(
    base: GameModel,
    target: GameModel,
    event_wall_epoch: Optional[float] = None,
) -> ModelDelta:
    """Diff two in-memory models with identical coordinate structure.

    ``event_wall_epoch`` is the wall time of the newest labeled event the
    target has absorbed; it rides the artifact so the apply side can
    record event→servable latency."""
    if base.task != target.task:
        raise _structural_refusal(
            "*", f"task changed ({base.task!r} -> {target.task!r})"
        )
    if list(base.models) != list(target.models):
        raise _structural_refusal(
            "*",
            f"coordinate set changed ({list(base.models)} -> "
            f"{list(target.models)})",
        )
    coords: List[CoordinateDelta] = []
    for name, base_sub in base.models.items():
        target_sub = target.models[name]
        if type(base_sub) is not type(target_sub):
            raise _structural_refusal(name, "coordinate kind changed")
        if base_sub.feature_shard != target_sub.feature_shard:
            raise _structural_refusal(name, "feature shard changed")
        if isinstance(base_sub, FixedEffectModel):
            base_ck = fixed_table_checksum(base_sub.model)
            target_ck = fixed_table_checksum(target_sub.model)
            coords.append(CoordinateDelta(
                name=name,
                kind="fixed",
                feature_shard=base_sub.feature_shard,
                base_checksum=base_ck,
                target_checksum=target_ck,
                means=(
                    None if base_ck == target_ck
                    else _canon_vals(target_sub.model.coefficients.means)
                ),
                variances=(
                    None
                    if base_ck == target_ck
                    or target_sub.model.coefficients.variances is None
                    else _canon_vals(target_sub.model.coefficients.variances)
                ),
            ))
            continue
        if base_sub.entity_key != target_sub.entity_key:
            raise _structural_refusal(name, "entity key changed")
        base_ck = random_table_checksum(base_sub)
        target_ck = random_table_checksum(target_sub)
        changed: Dict[str, Tuple] = {}
        removed: List[str] = []
        if base_ck != target_ck:
            bvar = base_sub.variances or {}
            tvar = target_sub.variances or {}
            for entity, row in target_sub.coefficients.items():
                prev = base_sub.coefficients.get(entity)
                if prev is not None and _rows_equal(
                    prev, row, bvar.get(entity), tvar.get(entity)
                ):
                    continue
                changed[str(entity)] = (
                    _canon_cols(row[0]),
                    _canon_vals(row[1]),
                    None if tvar.get(entity) is None
                    else _canon_vals(tvar[entity]),
                )
            removed = [
                str(e) for e in base_sub.coefficients
                if e not in target_sub.coefficients
            ]
        coords.append(CoordinateDelta(
            name=name,
            kind="random",
            feature_shard=base_sub.feature_shard,
            base_checksum=base_ck,
            target_checksum=target_ck,
            entity_key=base_sub.entity_key,
            n_features=target_sub.n_features,
            changed_entities=changed or None,
            removed=tuple(sorted(removed)),
        ))
    return ModelDelta(
        task=target.task,
        coordinates=coords,
        event_wall_epoch=event_wall_epoch,
    )


def diff_model_dirs(
    base_path: str,
    target_path: str,
    event_wall_epoch: Optional[float] = None,
) -> ModelDelta:
    """Diff two PERSISTED models (GAME directories or GLM ``.avro``
    files, as ``serving.runtime.ScoringRuntime.load_model`` accepts).

    The per-coordinate save-time fingerprints (``read_fingerprints`` in
    the io stores — a cheap manifest HEAD, no coefficient parse) gate
    the expensive per-entity comparison: a coordinate whose Avro
    checksum is unchanged is content-identical and skips straight to
    "unchanged".  Fingerprint-less legacy models are refused there with
    a pointed error."""
    # Imported here: serving.runtime pulls in the jit kernel machinery,
    # which delta consumers that never touch serving shouldn't pay for.
    from photon_ml_tpu.io import game_store, model_store
    from photon_ml_tpu.serving.runtime import ScoringRuntime

    equal_fingerprints: set = set()
    try:
        if os.path.isdir(base_path) or os.path.isdir(target_path):
            base_fps = game_store.read_fingerprints(base_path)
            target_fps = game_store.read_fingerprints(target_path)
        else:
            base_fps = {"fixed": model_store.read_fingerprints(base_path)}
            target_fps = {"fixed": model_store.read_fingerprints(target_path)}
    except FileNotFoundError as e:
        raise DeltaError(
            f"cannot diff {base_path!r} -> {target_path!r}: {e} — both "
            "endpoints must be persisted models with fingerprints"
        ) from e
    for name, fp in base_fps.items():
        other = target_fps.get(name)
        if other is not None and (
            fp.get("coefficient_checksum")
            == other.get("coefficient_checksum")
        ):
            equal_fingerprints.add(name)

    base_model, _ = ScoringRuntime.load_model(base_path)
    target_model, _ = ScoringRuntime.load_model(target_path)
    delta = diff_game_models(
        base_model, target_model, event_wall_epoch=event_wall_epoch
    )
    # Soundness cross-check: a fingerprint-equal coordinate must have
    # diffed to "unchanged" (the converse is fine — save order differs).
    for coord in delta.coordinates:
        if coord.name in equal_fingerprints and coord.changed:
            raise DeltaError(
                f"coordinate {coord.name!r}: save-time fingerprints match "
                "but table content differs — one of the models was "
                "modified after save; re-save both endpoints"
            )
    return delta


# ---------------------------------------------------------------------------
# Artifact write / read
# ---------------------------------------------------------------------------

def _manifest_digest(manifest: dict) -> str:
    # Same discipline as serving/shm_model.py: sha256 over the canonical
    # JSON of everything but the self-digest field.
    body = {k: v for k, v in manifest.items() if k != "manifest_sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, default=str).encode("utf-8")
    ).hexdigest()


def _random_segment_arrays(coord: CoordinateDelta) -> Dict[str, np.ndarray]:
    entities = sorted(coord.changed_entities or {})
    starts = [0]
    var_starts = [0]
    cols_parts, vals_parts, var_parts = [], [], []
    has_var = []
    for e in entities:
        cols, vals, var = coord.changed_entities[e]
        cols_parts.append(cols)
        vals_parts.append(vals)
        starts.append(starts[-1] + len(cols))
        if var is None:
            has_var.append(0)
        else:
            has_var.append(1)
            var_parts.append(var)
        var_starts.append(var_starts[-1] + (0 if var is None else len(var)))
    return {
        "entity_ids": np.asarray(entities, dtype=np.str_),
        "starts": np.asarray(starts, np.int64),
        "cols": (
            np.concatenate(cols_parts) if cols_parts
            else np.zeros(0, np.int32)
        ),
        "vals": (
            np.concatenate(vals_parts) if vals_parts
            else np.zeros(0, np.float32)
        ),
        "has_var": np.asarray(has_var, np.uint8),
        "var_starts": np.asarray(var_starts, np.int64),
        "var_vals": (
            np.concatenate(var_parts) if var_parts
            else np.zeros(0, np.float32)
        ),
    }


def _fixed_segment_arrays(coord: CoordinateDelta) -> Dict[str, np.ndarray]:
    arrays = {"means": coord.means}
    if coord.variances is not None:
        arrays["variances"] = coord.variances
    return arrays


def write_delta(delta: ModelDelta, directory: str) -> dict:
    """Write the artifact into ``directory`` (created if needed) and
    return the manifest.  The npz bytes are built in memory first so the
    manifest's per-segment sha256 covers exactly what lands on disk."""
    os.makedirs(directory, exist_ok=True)
    manifest = {
        "format": DELTA_FORMAT,
        "task": delta.task,
        "event_wall_epoch": delta.event_wall_epoch,
        "coordinates": [],
    }
    for coord in delta.coordinates:
        entry = {
            "name": coord.name,
            "kind": coord.kind,
            "feature_shard": coord.feature_shard,
            "base_table_checksum": coord.base_checksum,
            "target_table_checksum": coord.target_checksum,
            "changed": coord.changed,
        }
        if coord.kind == "random":
            entry["entity_key"] = coord.entity_key
            entry["n_features"] = int(coord.n_features)
            entry["removed"] = list(coord.removed)
        if coord.changed:
            arrays = (
                _fixed_segment_arrays(coord) if coord.kind == "fixed"
                else _random_segment_arrays(coord)
            )
            buf = io_lib.BytesIO()
            np.savez(buf, **arrays)
            payload = buf.getvalue()
            fname = f"segment-{coord.name}.npz"
            with open(os.path.join(directory, fname), "wb") as f:
                f.write(payload)
            entry["file"] = fname
            entry["nbytes"] = len(payload)
            entry["sha256"] = hashlib.sha256(payload).hexdigest()
            entry["n_changed"] = coord.n_changed
        manifest["coordinates"].append(entry)
    manifest["manifest_sha256"] = _manifest_digest(manifest)
    with open(os.path.join(directory, MANIFEST_FILE), "w") as f:
        json.dump(manifest, f, indent=2)
    return manifest


def _read_manifest(directory: str) -> dict:
    path = os.path.join(directory, MANIFEST_FILE)
    if not os.path.exists(path):
        raise DeltaFormatError(
            f"{directory}: no {MANIFEST_FILE} — not a delta artifact "
            "(or a publish died before staging completed; the publisher "
            "journal names the survivor)"
        )
    try:
        with open(path) as f:
            manifest = json.load(f)
    except ValueError as e:
        raise DeltaFormatError(
            f"{path}: unparseable manifest ({e}) — the artifact write "
            "was torn; re-publish the delta"
        ) from e
    if manifest.get("format") != DELTA_FORMAT:
        raise DeltaFormatError(
            f"{path}: format {manifest.get('format')!r}, expected "
            f"{DELTA_FORMAT!r}"
        )
    if manifest.get("manifest_sha256") != _manifest_digest(manifest):
        raise DeltaFormatError(
            f"{path}: manifest self-digest mismatch — the manifest was "
            "modified or torn after publish; refuse and re-publish"
        )
    return manifest


def read_delta(directory: str) -> ModelDelta:
    """Read and VERIFY an artifact: manifest self-digest, then every
    segment's sha256, then parse.  Any mismatch is a pointed
    :class:`DeltaFormatError` — a tampered or torn delta never reaches
    apply."""
    manifest = _read_manifest(directory)
    coords: List[CoordinateDelta] = []
    for entry in manifest["coordinates"]:
        kwargs = dict(
            name=entry["name"],
            kind=entry["kind"],
            feature_shard=entry["feature_shard"],
            base_checksum=entry["base_table_checksum"],
            target_checksum=entry["target_table_checksum"],
            entity_key=entry.get("entity_key", ""),
            n_features=int(entry.get("n_features", 0)),
            removed=tuple(entry.get("removed", ())),
        )
        if entry.get("changed"):
            seg_path = os.path.join(directory, entry["file"])
            try:
                with open(seg_path, "rb") as f:
                    payload = f.read()
            except FileNotFoundError:
                raise DeltaFormatError(
                    f"{seg_path}: segment named by the manifest is "
                    "missing — the artifact is incomplete; re-publish"
                ) from None
            actual = hashlib.sha256(payload).hexdigest()
            if actual != entry["sha256"]:
                raise DeltaFormatError(
                    f"{seg_path}: segment sha256 mismatch (file "
                    f"{actual[:16]}…, manifest {entry['sha256'][:16]}…) "
                    "— the segment was modified/truncated after "
                    "publish; refuse and re-publish"
                )
            arrays = dict(np.load(io_lib.BytesIO(payload)))
            if entry["kind"] == "fixed":
                kwargs["means"] = np.asarray(arrays["means"], np.float32)
                if "variances" in arrays:
                    kwargs["variances"] = np.asarray(
                        arrays["variances"], np.float32
                    )
            else:
                starts = arrays["starts"]
                var_starts = arrays["var_starts"]
                changed: Dict[str, Tuple] = {}
                for i, entity in enumerate(arrays["entity_ids"]):
                    cols = arrays["cols"][starts[i]:starts[i + 1]]
                    vals = arrays["vals"][starts[i]:starts[i + 1]]
                    var = None
                    if arrays["has_var"][i]:
                        var = arrays["var_vals"][
                            var_starts[i]:var_starts[i + 1]
                        ]
                    changed[str(entity)] = (
                        _canon_cols(cols), _canon_vals(vals),
                        None if var is None else _canon_vals(var),
                    )
                kwargs["changed_entities"] = changed or None
        coords.append(CoordinateDelta(**kwargs))
    return ModelDelta(
        task=manifest["task"],
        coordinates=coords,
        event_wall_epoch=manifest.get("event_wall_epoch"),
    )


# ---------------------------------------------------------------------------
# Apply
# ---------------------------------------------------------------------------

def apply_delta(model: GameModel, delta: ModelDelta) -> GameModel:
    """Return a NEW model = ``model`` patched by ``delta``.

    Never mutates ``model`` (its random-effect ``_packed`` caches are
    immutable-after-build, and the serving runtime may be scoring from
    it on another thread).  Verifies every coordinate's base checksum
    before building anything and the target checksum after — the result
    is provably bitwise-identical to a full load of the delta's target."""
    if model.task != delta.task:
        raise DeltaBaseMismatchError(
            f"delta is for task {delta.task!r} but the model is "
            f"{model.task!r} — wrong delta for this service"
        )
    by_name = {c.name: c for c in delta.coordinates}
    if set(by_name) != set(model.models):
        raise DeltaBaseMismatchError(
            f"delta covers coordinates {sorted(by_name)} but the model "
            f"has {sorted(model.models)} — the delta was diffed against "
            "a structurally different base; do a full reload"
        )
    # Verify the WHOLE base first: refusing before any work means a
    # mismatch can never leave a half-patched model behind.
    for name, sub in model.models.items():
        coord = by_name[name]
        actual = (
            fixed_table_checksum(sub.model)
            if isinstance(sub, FixedEffectModel)
            else random_table_checksum(sub)
        )
        if actual != coord.base_checksum:
            raise DeltaBaseMismatchError(
                f"coordinate {name!r}: serving table checksum "
                f"{actual[:16]}… does not match the delta's base "
                f"{coord.base_checksum[:16]}… — this delta was diffed "
                "against a DIFFERENT base model (stale serving version "
                "or out-of-order apply); do a full reload or re-diff "
                "against the live version"
            )
    new_models: Dict[str, object] = {}
    for name, sub in model.models.items():
        coord = by_name[name]
        if not coord.changed:
            new_models[name] = sub
            continue
        if isinstance(sub, FixedEffectModel):
            new_models[name] = FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(
                        jnp.asarray(coord.means),
                        None if coord.variances is None
                        else jnp.asarray(coord.variances),
                    ),
                    sub.model.task,
                ),
                sub.feature_shard,
            )
            continue
        table = dict(sub.coefficients)
        var_table = dict(sub.variances or {})
        for entity in coord.removed:
            table.pop(entity, None)
            var_table.pop(entity, None)
        for entity, (cols, vals, var) in (coord.changed_entities or {}).items():
            table[entity] = (cols, vals)
            if var is None:
                var_table.pop(entity, None)
            else:
                var_table[entity] = var
        new_models[name] = RandomEffectModel(
            coefficients=table,
            feature_shard=sub.feature_shard,
            entity_key=sub.entity_key,
            task=sub.task,
            n_features=coord.n_features or sub.n_features,
            variances=var_table or None,
        )
    patched = GameModel(models=new_models, task=model.task)
    for name, sub in patched.models.items():
        coord = by_name[name]
        actual = (
            fixed_table_checksum(sub.model)
            if isinstance(sub, FixedEffectModel)
            else random_table_checksum(sub)
        )
        if actual != coord.target_checksum:
            raise DeltaError(
                f"coordinate {name!r}: patched table checksum "
                f"{actual[:16]}… does not match the delta's target "
                f"{coord.target_checksum[:16]}… — the artifact is "
                "internally inconsistent; re-publish the delta"
            )
    return patched
