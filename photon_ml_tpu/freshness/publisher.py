"""Crash-safe delta publication.

A publication root is a directory:

    publish_journal.jsonl    append-only decision log (tuning/state.py
                             discipline: one JSON line per record,
                             flush+fsync before append returns)
    delta-<seq>/             published artifacts (delta.py layout)
    delta-<seq>.staging/     an in-flight write (never read by anyone)

The publish protocol brackets an atomic-rename artifact write with
journal records, so a kill at ANY instant leaves the root in a state
the next :class:`DeltaPublisher` (or an explicit :meth:`resume`)
completes deterministically:

    begin(seq)        journaled first — the staging dir is claimed
    <stage artifact>  written into delta-<seq>.staging/, self-digested
    <atomic rename>   delta-<seq>.staging/ -> delta-<seq>/
    commit(seq)       journaled last — the publication is now visible

Crash before the rename: the staging dir is garbage; resume removes it
and journals ``abort``.  Crash after the rename but before ``commit``:
the artifact is complete and verified on disk; resume journals the
missing ``commit`` — the SAME publication an uninterrupted run would
have made, never a half-published artifact.  Subscribers only ever see
``commit``-journaled sequence numbers (:meth:`publications`), so a torn
publish is invisible to the apply side.

Retention (bounded roots)
-------------------------

A continuously-refining loop publishes forever; without pruning the
root grows without bound.  :meth:`DeltaPublisher.retain` (or the
``retain_last`` constructor knob, which prunes after every publish)
keeps the newest K committed publications and removes the rest —
journal compaction first (write-temp + fsync + atomic rename, so the
journal is never torn), artifact directories second (a kill in between
leaves orphan ``delta-*`` dirs that the next retention sweeps).  Two
things are NEVER pruned: an unsettled ``begin`` (an in-flight publish
is not ours to judge) and the newest committed publication (an empty
root would strand every subscriber).  Sequence numbering survives
compaction — the kept records still carry the max seq, so
``_next_seq`` never moves backward and a resumed publisher continues
the same sequence.

Ack sidecar (``acks/<subscriber_id>``)
--------------------------------------

Deltas are incremental: pruning a publication a subscriber has not yet
applied forces that subscriber into a full reload.  Subscribers
therefore register an ack file under ``acks/`` (atomic write via
:func:`write_ack`; :class:`~photon_ml_tpu.freshness.applier.DeltaApplier`
does this when given a ``subscriber_id``), and retention refuses to
prune any publication newer than the slowest registered ack — those
sequences are reported as ``blocked`` with the GUILTY subscriber ids
(``blocking``), so the operator knows exactly which subscriber to chase
or unregister (:func:`remove_ack` releases the prune).  A root with no
registered subscribers prunes on age alone.

Snapshot publications (cluster cold start)
------------------------------------------

Deltas patch a base the subscriber already has; a brand-new host has no
base.  :meth:`DeltaPublisher.publish_snapshot` publishes a FULL model
directory under the same journal protocol (``snapshot-<seq>/`` with a
self-digested ``snapshot.json`` listing every file's sha256), so a cold
host can bootstrap from the newest snapshot over the wire
(photon_ml_tpu/cluster/distribution.py) and then catch up by deltas —
no shared filesystem anywhere on the serving path.  Snapshots ride the
same sequence space, retention, and ack discipline as deltas;
:class:`Publication.kind` tells the apply side which reload path to
take.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil
import threading
import time
from typing import Dict, List, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.freshness.delta import (
    MANIFEST_FILE,
    DeltaError,
    ModelDelta,
    _manifest_digest,
    _read_manifest,
    write_delta,
)
from photon_ml_tpu.io.checkpoint import fsync_file


@dataclasses.dataclass(frozen=True)
class Publication:
    """One committed publication, as subscribers see it.  ``kind`` is
    ``"delta"`` (incremental, delta.py layout) or ``"snapshot"`` (full
    model dir + ``snapshot.json``, the cold-start bootstrap)."""

    seq: int
    path: str
    manifest_sha256: str
    event_wall_epoch: Optional[float]
    n_changed_rows: int
    publish_wall_epoch: float
    kind: str = "delta"


class PublishAborted(RuntimeError):
    """Raised by the journal's test abort hook to simulate a kill at a
    deterministic record boundary (tuning/state.py idiom)."""


ACKS_DIR = "acks"

#: Subscriber ids become filenames under ``acks/`` — keep them to the
#: same safe alphabet as tenant slugs, no path separators or dots-only.
_SUBSCRIBER_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

_ARTIFACT_DIR_RE = re.compile(r"^(?:delta|snapshot)-(\d+)$")

#: Snapshot artifact manifest filename and format tag (delta.py keeps
#: ``delta.json`` / photon-model-delta-v1 for incremental artifacts).
SNAPSHOT_MANIFEST = "snapshot.json"
SNAPSHOT_FORMAT = "photon-model-snapshot-v1"
#: Model files live under this subdir of a snapshot artifact, so the
#: apply side reloads ``<artifact>/model`` without the manifest riding
#: along inside the model directory.
SNAPSHOT_MODEL_DIR = "model"


def write_ack(
    root: str, subscriber_id: str, seq: int, fsync: bool = True
) -> str:
    """Record that ``subscriber_id`` has applied (or deliberately
    skipped) every publication up to and including ``seq``.  Atomic
    (write-temp + rename), so retention never reads a torn ack.
    Returns the ack file path."""
    if not _SUBSCRIBER_ID_RE.match(subscriber_id):
        raise ValueError(
            f"subscriber id {subscriber_id!r} is not a safe filename "
            "([A-Za-z0-9][A-Za-z0-9._-]*, max 64 chars)"
        )
    acks = os.path.join(root, ACKS_DIR)
    os.makedirs(acks, exist_ok=True)
    path = os.path.join(acks, subscriber_id + ".json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "subscriber_id": subscriber_id,
            "acked_seq": int(seq),
            "wall_epoch": time.time(),
        }, f)
        if fsync:
            fsync_file(f)
    os.replace(tmp, path)
    return path


def read_acks(root: str) -> Dict[str, int]:
    """Acked sequence number per registered subscriber.  A missing
    ``acks/`` dir means no subscribers are registered (retention prunes
    on age alone); an unparseable ack file is skipped — ack writes are
    atomic, so garbage there is not ours."""
    acks = os.path.join(root, ACKS_DIR)
    if not os.path.isdir(acks):
        return {}
    out: Dict[str, int] = {}
    for name in sorted(os.listdir(acks)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(acks, name)) as f:
                record = json.load(f)
            out[str(record["subscriber_id"])] = int(record["acked_seq"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            continue
    return out


def remove_ack(root: str, subscriber_id: str) -> bool:
    """Unregister a subscriber from the root's ack sidecar, releasing
    any retention hold its stale ack was keeping (``retain`` reports
    the guilty id in ``blocking``).  Returns ``True`` when an ack file
    was actually removed.  This is the operator's lever against a
    subscriber that registered and then died without acking — the
    runbook move after ``blocking`` names it."""
    if not _SUBSCRIBER_ID_RE.match(subscriber_id):
        raise ValueError(
            f"subscriber id {subscriber_id!r} is not a safe filename "
            "([A-Za-z0-9][A-Za-z0-9._-]*, max 64 chars)"
        )
    path = os.path.join(root, ACKS_DIR, subscriber_id + ".json")
    try:
        os.remove(path)
    except FileNotFoundError:
        return False
    telemetry_mod.current().event(
        "freshness.subscriber_unregistered",
        root=root, subscriber_id=subscriber_id,
    )
    return True


def _write_snapshot_manifest(
    staging: str, event_wall_epoch: Optional[float]
) -> dict:
    """Digest every file under ``staging/model`` into a self-digested
    ``snapshot.json`` (delta.py's manifest discipline: sha256 per file,
    manifest_sha256 over the canonical JSON of the rest)."""
    model_root = os.path.join(staging, SNAPSHOT_MODEL_DIR)
    files: Dict[str, dict] = {}
    for dirpath, _dirnames, filenames in os.walk(model_root):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            rel = os.path.join(
                SNAPSHOT_MODEL_DIR, os.path.relpath(full, model_root)
            )
            with open(full, "rb") as f:
                payload = f.read()
            files[rel] = {
                "sha256": hashlib.sha256(payload).hexdigest(),
                "nbytes": len(payload),
            }
    if not files:
        raise DeltaError(
            f"{model_root}: empty model directory — nothing to snapshot"
        )
    manifest = {
        "format": SNAPSHOT_FORMAT,
        "event_wall_epoch": event_wall_epoch,
        "files": files,
    }
    manifest["manifest_sha256"] = _manifest_digest(manifest)
    with open(os.path.join(staging, SNAPSHOT_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2)
    return manifest


def read_snapshot_manifest(directory: str) -> dict:
    """Parse and digest-verify a snapshot artifact's ``snapshot.json``.
    Raises :class:`DeltaError` on a missing/torn/tampered manifest —
    the same refusal contract as delta.py's ``read_delta``."""
    path = os.path.join(directory, SNAPSHOT_MANIFEST)
    if not os.path.exists(path):
        raise DeltaError(
            f"{directory}: no {SNAPSHOT_MANIFEST} — not a snapshot "
            "artifact (or the publish died before staging completed)"
        )
    try:
        with open(path) as f:
            manifest = json.load(f)
    except ValueError as e:
        raise DeltaError(
            f"{path}: unparseable snapshot manifest ({e}) — the "
            "artifact write was torn; re-publish the snapshot"
        ) from e
    if manifest.get("format") != SNAPSHOT_FORMAT:
        raise DeltaError(
            f"{path}: format {manifest.get('format')!r}, expected "
            f"{SNAPSHOT_FORMAT!r}"
        )
    expected = manifest.get("manifest_sha256")
    if _manifest_digest(manifest) != expected:
        raise DeltaError(
            f"{path}: manifest self-digest mismatch — the manifest was "
            "modified after publish; refuse and re-publish"
        )
    return manifest


class DeltaPublisher:
    """Publish :class:`~photon_ml_tpu.freshness.delta.ModelDelta`
    artifacts into a root directory, crash-safely.

    Thread-safe; one lock serializes publishes (a publication root has
    one writer — concurrent publishers on one root would race the
    sequence counter, which the claim-by-journal protocol would surface
    as a rename failure rather than corruption).
    """

    JOURNAL = "publish_journal.jsonl"

    def __init__(
        self,
        root: str,
        fsync: bool = True,
        abort_after: Optional[int] = None,
        retain_last: Optional[int] = None,
    ):
        if retain_last is not None and retain_last < 1:
            raise ValueError(
                f"retain_last must be >= 1 (the newest committed "
                f"publication is never pruned), got {retain_last}"
            )
        self.root = root
        self.fsync = fsync
        self.abort_after = abort_after
        self.retain_last = retain_last
        self.path = os.path.join(root, self.JOURNAL)
        self._lock = sanitizers.tracked(
            threading.Lock(), "freshness.publisher"
        )
        self._f = None
        self._written = 0
        os.makedirs(root, exist_ok=True)
        self.resume()

    # -- journal ------------------------------------------------------------
    def _append(self, record: dict) -> None:
        # Caller holds self._lock.
        if self.abort_after is not None and self._written >= self.abort_after:
            raise PublishAborted(
                f"journal abort hook: {self._written} records written"
            )
        if self._f is None:
            self._f = open(self.path, "a")
        self._f.write(json.dumps(record) + "\n")
        if self.fsync:
            fsync_file(self._f)
        else:
            self._f.flush()
        self._written += 1

    def _read(self) -> List[dict]:
        """Every complete journal record; a torn FINAL line is dropped,
        a torn line anywhere else raises (not an append-only journal)."""
        if not os.path.exists(self.path):
            return []
        if self._f is not None:
            self._f.flush()
        with open(self.path) as f:
            lines = f.read().splitlines()
        records = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break
                raise DeltaError(
                    f"{self.path}: corrupt journal line {i + 1} (not the "
                    "tail) — the file was edited or is not an append-only "
                    "journal; restore it from backup"
                ) from None
        return records

    # -- paths --------------------------------------------------------------
    def _final_dir(self, seq: int, artifact: str = "delta") -> str:
        return os.path.join(self.root, f"{artifact}-{seq:06d}")

    def _staging_dir(self, seq: int, artifact: str = "delta") -> str:
        return self._final_dir(seq, artifact) + ".staging"

    def _artifact_dirs(self, seq: int) -> List[str]:
        """Every directory (final or staging, either kind) a sequence
        number may occupy — retention removes whichever exists."""
        return [
            self._final_dir(seq, a) + suffix
            for a in ("delta", "snapshot")
            for suffix in ("", ".staging")
        ]

    # -- resume -------------------------------------------------------------
    def resume(self) -> List[dict]:
        """Complete or clean every in-flight publication, exactly as an
        uninterrupted run would have.  Returns the repair records
        journaled (empty on a clean root).  Called from ``__init__`` so
        merely constructing a publisher heals its root."""
        with self._lock:
            records = self._read()
            settled = {
                r["seq"] for r in records if r["kind"] in ("commit", "abort")
            }
            repairs: List[dict] = []
            max_seq = 0
            for r in records:
                max_seq = max(max_seq, r["seq"])
                if r["kind"] != "begin" or r["seq"] in settled:
                    continue
                seq = r["seq"]
                artifact = r.get("artifact", "delta")
                final = self._final_dir(seq, artifact)
                staging = self._staging_dir(seq, artifact)
                manifest_name = (
                    SNAPSHOT_MANIFEST if artifact == "snapshot"
                    else MANIFEST_FILE
                )
                if os.path.exists(os.path.join(final, manifest_name)):
                    # Crashed between the atomic rename and the commit
                    # record: the artifact is complete — verify and
                    # journal the commit an uninterrupted run would have.
                    manifest = (
                        read_snapshot_manifest(final)
                        if artifact == "snapshot"
                        else _read_manifest(final)
                    )
                    repair = {
                        "kind": "commit",
                        "seq": seq,
                        "artifact": artifact,
                        "path": final,
                        "manifest_sha256": manifest["manifest_sha256"],
                        "event_wall_epoch": manifest.get("event_wall_epoch"),
                        "n_changed_rows": (
                            0 if artifact == "snapshot"
                            else _manifest_rows(manifest)
                        ),
                        "publish_wall_epoch": r["publish_wall_epoch"],
                        "resumed": True,
                    }
                else:
                    # Crashed before the rename: nothing was published.
                    if os.path.isdir(staging):
                        shutil.rmtree(staging)
                    repair = {"kind": "abort", "seq": seq, "resumed": True}
                self._append(repair)
                repairs.append(repair)
            self._next_seq = max_seq + 1
            return repairs

    # -- publish ------------------------------------------------------------
    def publish(self, delta: ModelDelta) -> Publication:
        """Write ``delta`` as the next sequenced artifact.  Returns the
        committed :class:`Publication`.  Raises whatever the chaos
        harness injects at the ``publish.delta`` boundaries — after
        which a :meth:`resume` (or the next constructor) settles the
        root deterministically."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            publish_wall = time.time()
            self._append({
                "kind": "begin",
                "seq": seq,
                "publish_wall_epoch": publish_wall,
                "event_wall_epoch": delta.event_wall_epoch,
            })
            chaos_mod.maybe_fail("publish.delta", stage="journal", seq=seq)
            staging = self._staging_dir(seq)
            if os.path.isdir(staging):
                shutil.rmtree(staging)
            manifest = write_delta(delta, staging)
            chaos_mod.maybe_fail("publish.delta", stage="artifact", seq=seq)
            final = self._final_dir(seq)
            os.rename(staging, final)
            chaos_mod.maybe_fail("publish.delta", stage="commit", seq=seq)
            record = {
                "kind": "commit",
                "seq": seq,
                "path": final,
                "manifest_sha256": manifest["manifest_sha256"],
                "event_wall_epoch": delta.event_wall_epoch,
                "n_changed_rows": delta.n_changed_rows,
                "publish_wall_epoch": publish_wall,
            }
            self._append(record)
            retention = (
                self._retain_locked(self.retain_last)
                if self.retain_last is not None
                else None
            )
        hub = telemetry_mod.current()
        hub.counter("freshness_deltas_published_total").inc()
        hub.counter("freshness_delta_rows").inc(delta.n_changed_rows)
        hub.counter("freshness_delta_bytes").inc(_artifact_bytes(manifest))
        if retention is not None and retention["pruned"]:
            hub.counter("freshness_retention_pruned_total").inc(
                len(retention["pruned"])
            )
        return _publication(record)

    def publish_snapshot(
        self,
        model_dir: str,
        event_wall_epoch: Optional[float] = None,
    ) -> Publication:
        """Publish a FULL model directory as the next sequenced
        artifact (``snapshot-<seq>/model/`` + self-digested
        ``snapshot.json``) under the same begin/stage/rename/commit
        journal protocol as :meth:`publish` — a kill at any instant is
        settled by the next :meth:`resume`.  This is the cold-start
        anchor for publication-based model distribution: a host with no
        base pulls the newest snapshot, then catches up by deltas."""
        if not os.path.isdir(model_dir):
            raise DeltaError(
                f"{model_dir}: not a directory — publish_snapshot "
                "takes a saved model directory"
            )
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            publish_wall = time.time()
            self._append({
                "kind": "begin",
                "seq": seq,
                "artifact": "snapshot",
                "publish_wall_epoch": publish_wall,
                "event_wall_epoch": event_wall_epoch,
            })
            chaos_mod.maybe_fail("publish.delta", stage="journal", seq=seq)
            staging = self._staging_dir(seq, "snapshot")
            if os.path.isdir(staging):
                shutil.rmtree(staging)
            shutil.copytree(
                model_dir, os.path.join(staging, SNAPSHOT_MODEL_DIR)
            )
            manifest = _write_snapshot_manifest(staging, event_wall_epoch)
            chaos_mod.maybe_fail("publish.delta", stage="artifact", seq=seq)
            final = self._final_dir(seq, "snapshot")
            os.rename(staging, final)
            chaos_mod.maybe_fail("publish.delta", stage="commit", seq=seq)
            record = {
                "kind": "commit",
                "seq": seq,
                "artifact": "snapshot",
                "path": final,
                "manifest_sha256": manifest["manifest_sha256"],
                "event_wall_epoch": event_wall_epoch,
                "n_changed_rows": 0,
                "publish_wall_epoch": publish_wall,
            }
            self._append(record)
            retention = (
                self._retain_locked(self.retain_last)
                if self.retain_last is not None
                else None
            )
        hub = telemetry_mod.current()
        hub.counter("freshness_snapshots_published_total").inc()
        hub.counter("freshness_snapshot_bytes").inc(
            sum(int(e["nbytes"]) for e in manifest["files"].values())
        )
        if retention is not None and retention["pruned"]:
            hub.counter("freshness_retention_pruned_total").inc(
                len(retention["pruned"])
            )
        return _publication(record)

    # -- retention ----------------------------------------------------------
    def retain(self, keep_last: int) -> dict:
        """Prune committed publications older than the newest
        ``keep_last``, compacting the journal and removing their
        artifact directories.  Returns a summary dict::

            {"pruned": [seq...],    # removed this call
             "blocked": [seq...],   # prunable by age, held by an ack
             "blocking": {seq: [subscriber_id...]},  # who holds each
             "kept": [seq...]}      # committed seqs still in the root

        Never removes an unsettled ``begin`` or the newest committed
        publication, and refuses any sequence a registered subscriber
        (``acks/``) has not acked yet — ``blocking`` names the guilty
        subscriber per held sequence, so the operator can chase it or
        :func:`remove_ack` it to release the prune.  Crash-safe: the
        journal is compacted by atomic rename BEFORE any artifact dir
        is removed, and orphan dirs from a kill in between are swept by
        the next retention."""
        with self._lock:
            retention = self._retain_locked(keep_last)
        hub = telemetry_mod.current()
        if retention["pruned"]:
            hub.counter(
                "freshness_retention_pruned_total"
            ).inc(len(retention["pruned"]))
        if retention["blocked"]:
            hub.counter(
                "freshness_retention_blocked_total"
            ).inc(len(retention["blocked"]))
            hub.event(
                "freshness.retention_blocked",
                root=self.root,
                blocked=retention["blocked"],
                blocking={
                    str(s): ids
                    for s, ids in retention["blocking"].items()
                },
            )
        return retention

    def _retain_locked(self, keep_last: int) -> dict:
        # Caller holds self._lock.
        if keep_last < 1:
            raise ValueError(
                f"keep_last must be >= 1 (the newest committed "
                f"publication is never pruned), got {keep_last}"
            )
        records = self._read()
        committed = sorted(
            {r["seq"] for r in records if r["kind"] == "commit"}
        )
        candidates = committed[:-keep_last]
        acks = read_acks(self.root)
        min_acked = min(acks.values()) if acks else None
        pruned = sorted(
            s for s in candidates if min_acked is None or s <= min_acked
        )
        blocked = sorted(set(candidates) - set(pruned))
        blocking = {
            s: sorted(sid for sid, acked in acks.items() if acked < s)
            for s in blocked
        }
        kept = sorted(set(committed) - set(pruned))
        summary = {
            "pruned": pruned, "blocked": blocked,
            "blocking": blocking, "kept": kept,
        }
        if not pruned:
            # Still sweep orphan dirs a prior kill may have left.
            self._sweep_orphans(records)
            return summary
        # floor: the oldest surviving commit.  Everything pruned sits
        # below it, so journal records for settled aborts down there are
        # noise too — drop them with the pruned commits.  Unsettled
        # begins and anything >= floor (including a trailing abort with
        # the max seq, which anchors _next_seq) survive compaction.
        floor = kept[0]
        settled = {
            r["seq"] for r in records if r["kind"] in ("commit", "abort")
        }
        drop = set(pruned) | {
            s for s in settled if s < floor and s not in set(committed)
        }
        compacted = [r for r in records if r["seq"] not in drop]
        compacted.append({
            "kind": "retention",
            "seq": max(drop),
            "pruned": sorted(drop),
            "floor_seq": floor,
            "wall_epoch": time.time(),
        })
        # Compact via write-temp + fsync + atomic rename.  The open
        # append handle points at the OLD inode — close it first so the
        # next _append reopens the compacted file.
        if self._f is not None:
            self._f.close()
            self._f = None
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for r in compacted:
                f.write(json.dumps(r) + "\n")
            if self.fsync:
                fsync_file(f)
        os.replace(tmp, self.path)
        # Only now remove artifacts: a kill before this point leaves
        # orphan dirs (swept below / next time), never a journal that
        # references a missing artifact.
        for seq in sorted(drop):
            for path in self._artifact_dirs(seq):
                if os.path.isdir(path):
                    shutil.rmtree(path)
        self._sweep_orphans(compacted)
        return summary

    def _sweep_orphans(self, records: List[dict]) -> None:
        # Caller holds self._lock.  A delta-*/snapshot-* dir whose seq
        # no journal record references is a leftover from a kill between
        # journal compaction and artifact removal — safe to delete
        # (subscribers only ever follow commit records).  Retention
        # records describe PRUNED seqs, so they don't count as
        # references.
        referenced = {
            r["seq"] for r in records if r["kind"] != "retention"
        }
        for name in os.listdir(self.root):
            m = _ARTIFACT_DIR_RE.match(name)
            if m is None or int(m.group(1)) in referenced:
                continue
            path = os.path.join(self.root, name)
            if os.path.isdir(path):
                shutil.rmtree(path)

    def publications(self) -> List[Publication]:
        """Committed publications in sequence order — the only view
        subscribers get, so in-flight or aborted publishes are
        invisible to the apply side."""
        with self._lock:
            return [
                _publication(r)
                for r in self._read()
                if r["kind"] == "commit"
            ]

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "DeltaPublisher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_publications(root: str) -> List[Publication]:
    """Committed publications under ``root``, in sequence order, without
    constructing a :class:`DeltaPublisher` (whose constructor RESUMES —
    i.e. writes).  This is the subscriber entry point: read-only, torn
    final journal line tolerated, in-flight/aborted publishes invisible.
    A missing journal is an empty root, not an error."""
    journal = os.path.join(root, DeltaPublisher.JOURNAL)
    if not os.path.exists(journal):
        return []
    with open(journal) as f:
        lines = f.read().splitlines()
    out: List[Publication] = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise DeltaError(
                f"{journal}: corrupt journal line {i + 1} (not the tail) "
                "— the file was edited or is not an append-only journal; "
                "restore it from backup"
            ) from None
        if record["kind"] == "commit":
            out.append(_publication(record))
    return out


def _manifest_rows(manifest: dict) -> int:
    # n_changed already counts removals (CoordinateDelta.n_changed).
    return sum(int(c.get("n_changed", 0)) for c in manifest["coordinates"])


def _artifact_bytes(manifest: dict) -> int:
    return sum(int(c.get("nbytes", 0)) for c in manifest["coordinates"])


def _publication(record: dict) -> Publication:
    return Publication(
        seq=record["seq"],
        path=record["path"],
        manifest_sha256=record["manifest_sha256"],
        event_wall_epoch=record.get("event_wall_epoch"),
        n_changed_rows=int(record.get("n_changed_rows", 0)),
        publish_wall_epoch=record["publish_wall_epoch"],
        kind=record.get("artifact", "delta"),
    )
