"""Online per-entity refinement between full coordinate-descent sweeps.

A full CD sweep (``game/training.py``) refits every coordinate against
the whole dataset — the freshest model it can produce is hours old by
the time it lands.  :class:`OnlineRefiner` closes that gap for the
coordinates where staleness actually hurts: the RANDOM effects.  It
warm-starts from the serving model's per-entity coefficients, folds in
labeled events one at a time with seeded SGD/AdaGrad on the canonical-
link gradient, and hands the result to the SAME delta publish path a
full sweep would use (``diff_game_models`` → ``DeltaPublisher``), so
the serving side cannot tell refined deltas from retrained ones.

Scope is deliberate: fixed effects are NOT touched (they move slowly
and globally; refitting them from a trickle of events would let one hot
entity's traffic drag the global model), and per-entity posteriors
(variances) are dropped for refined entities — point-estimate SGD says
nothing about the posterior, and shipping a stale variance next to a
fresh mean would be worse than shipping none.

Determinism: updates are plain float32 numpy in event order; two
refiners fed the same events from the same base produce bitwise-equal
models (the tests assert it via table checksums).  ``config.seed`` only
drives the optional event shuffle in :meth:`OnlineRefiner.consume`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.freshness.delta import ModelDelta, diff_game_models
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)


@dataclasses.dataclass(frozen=True)
class LabeledEvent:
    """One observed (features, entity ids, label) outcome.

    ``wall_epoch`` is when the event HAPPENED (not when it was
    processed) — it anchors the freshness SLO: the published delta
    carries the newest event's wall epoch, and the swapper measures
    ``freshness_event_to_servable_seconds`` against it at commit.
    """

    features: dict  # feature shard -> np.float32 (D,) dense vector
    ids: dict  # entity-key name -> str entity id
    label: float
    offset: float = 0.0
    wall_epoch: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class RefinerConfig:
    """Knobs for one refinement pass."""

    #: "adagrad" (per-coordinate adaptive step, the default — robust to
    #: feature-scale spread) or "sgd" (constant step).
    algorithm: str = "adagrad"
    learning_rate: float = 0.1
    #: L2 pull toward the warm-start coefficients (NOT toward zero):
    #: online refinement trusts the full sweep's estimate and should
    #: drift from it only as far as the events justify.
    l2: float = 0.0
    adagrad_eps: float = 1e-8
    #: clamp on the per-event error term, so one mislabeled outlier
    #: cannot blow up a low-traffic entity's row.
    max_error: float = 100.0
    seed: int = 0


class OnlineRefiner:
    """Refine a GAME model's random-effect rows from labeled events."""

    def __init__(self, model: GameModel, config: Optional[RefinerConfig] = None):
        self.config = config or RefinerConfig()
        if self.config.algorithm not in ("sgd", "adagrad"):
            raise ValueError(
                f"unknown refiner algorithm {self.config.algorithm!r} — "
                "expected 'sgd' or 'adagrad'"
            )
        self._base = model
        self._rng = np.random.default_rng(self.config.seed)
        # Dense working rows, built lazily per touched entity:
        # (coordinate, entity) -> float32 (n_features,).  Untouched
        # entities never leave the base model's sparse table, so the
        # exported model is bitwise-identical to the base everywhere the
        # events didn't reach — which is what keeps the delta small.
        self._work: Dict[Tuple[str, str], np.ndarray] = {}
        #: AdaGrad squared-gradient accumulators, same keying.
        self._accum: Dict[Tuple[str, str], np.ndarray] = {}
        #: warm-start anchors for the L2 pull (dense copy at first touch).
        self._anchor: Dict[Tuple[str, str], np.ndarray] = {}
        self.events = 0
        self.latest_event_wall: Optional[float] = None

    # -- model access --------------------------------------------------------
    def _dense_row(self, name: str, sub: RandomEffectModel, entity: str):
        key = (name, entity)
        row = self._work.get(key)
        if row is None:
            row = np.zeros(sub.n_features, np.float32)
            pair = sub.coefficients.get(entity)
            if pair is not None:
                cols, vals = pair
                row[np.asarray(cols, np.int64)] = np.asarray(vals, np.float32)
            self._work[key] = row
            self._anchor[key] = row.copy()
            self._accum[key] = np.zeros(sub.n_features, np.float32)
        return row

    def _margin(self, event: LabeledEvent) -> float:
        margin = float(event.offset)
        for name, coord in self._base.models.items():
            if isinstance(coord, FixedEffectModel):
                x = event.features.get(coord.feature_shard)
                if x is not None:
                    means = np.asarray(coord.model.coefficients.means)
                    margin += float(
                        np.dot(means.astype(np.float32), np.asarray(x, np.float32))
                    )
                continue
            entity = event.ids.get(coord.entity_key)
            x = event.features.get(coord.feature_shard)
            if entity is None or x is None:
                continue
            row = self._dense_row(name, coord, str(entity))
            margin += float(np.dot(row, np.asarray(x, np.float32)))
        return margin

    def _mean(self, margin: float) -> float:
        # Function-local import: keeps `import photon_ml_tpu.freshness`
        # from dragging in the serving runtime (and its jit machinery)
        # when only the delta/publisher side is wanted.
        from photon_ml_tpu.serving.runtime import _host_mean

        return float(_host_mean(self._base.task, np.array([margin], np.float32))[0])

    # -- refinement ----------------------------------------------------------
    def step(self, event: LabeledEvent) -> float:
        """Fold one event into the working rows.  Returns the per-event
        error term (mean(margin) − label, post-clamp) for monitoring."""
        chaos_mod.maybe_fail(
            "online.step", events=self.events, ids=dict(event.ids)
        )
        cfg = self.config
        err = self._mean(self._margin(event)) - float(event.label)
        err = float(np.clip(err, -cfg.max_error, cfg.max_error))
        err32 = np.float32(err)
        for name, coord in self._base.models.items():
            if isinstance(coord, FixedEffectModel):
                continue
            entity = event.ids.get(coord.entity_key)
            x = event.features.get(coord.feature_shard)
            if entity is None or x is None:
                continue
            key = (name, str(entity))
            row = self._dense_row(name, coord, str(entity))
            x32 = np.asarray(x, np.float32)
            grad = err32 * x32
            if cfg.l2:
                grad = grad + np.float32(cfg.l2) * (row - self._anchor[key])
            if cfg.algorithm == "adagrad":
                acc = self._accum[key]
                acc += grad * grad
                step = grad / np.sqrt(acc + np.float32(cfg.adagrad_eps))
            else:
                step = grad
            row -= np.float32(cfg.learning_rate) * step
        self.events += 1
        if event.wall_epoch is not None:
            if self.latest_event_wall is None or (
                event.wall_epoch > self.latest_event_wall
            ):
                self.latest_event_wall = float(event.wall_epoch)
        telemetry_mod.current().counter("freshness_online_events_total").inc()
        return err

    def consume(
        self, events: Iterable[LabeledEvent], shuffle: bool = False
    ) -> List[float]:
        """Step through ``events`` (optionally in a seed-determined
        shuffled order); returns the per-event error terms."""
        batch = list(events)
        if shuffle:
            self._rng.shuffle(batch)
        return [self.step(e) for e in batch]

    # -- export --------------------------------------------------------------
    @property
    def touched(self) -> Dict[str, List[str]]:
        """Coordinate name -> sorted entity ids with refined rows."""
        out: Dict[str, List[str]] = {}
        for name, entity in self._work:
            out.setdefault(name, []).append(entity)
        return {name: sorted(ents) for name, ents in out.items()}

    def refined_model(self) -> GameModel:
        """A new :class:`GameModel` with refined rows re-sparsified and
        every untouched entity's arrays SHARED with the base model (so a
        subsequent diff sees them as bitwise-unchanged for free)."""
        models = {}
        for name, coord in self._base.models.items():
            if isinstance(coord, FixedEffectModel):
                models[name] = coord
                continue
            refined = {
                entity for (cname, entity) in self._work if cname == name
            }
            if not refined:
                models[name] = coord
                continue
            coeffs = dict(coord.coefficients)
            variances = dict(coord.variances) if coord.variances else None
            for entity in refined:
                row = self._work[(name, entity)]
                cols = np.flatnonzero(row).astype(np.int32)
                coeffs[entity] = (cols, row[cols].astype(np.float32))
                if variances is not None:
                    # Point-estimate refinement invalidates the posterior.
                    variances.pop(entity, None)
            models[name] = RandomEffectModel(
                coefficients=coeffs,
                feature_shard=coord.feature_shard,
                entity_key=coord.entity_key,
                task=coord.task,
                n_features=coord.n_features,
                variances=variances,
            )
        return GameModel(models=models, task=self._base.task)

    def delta(self) -> ModelDelta:
        """Diff the refined model against the warm-start base."""
        return diff_game_models(
            self._base,
            self.refined_model(),
            event_wall_epoch=self.latest_event_wall,
        )

    def publish(self, publisher):
        """Publish the refinement through ``publisher``
        (:class:`~photon_ml_tpu.freshness.publisher.DeltaPublisher`) —
        the same artifact path a full retrain would use.  Returns the
        :class:`~photon_ml_tpu.freshness.publisher.Publication`."""
        return publisher.publish(self.delta())
