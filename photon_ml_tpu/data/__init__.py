from photon_ml_tpu.data.dataset import GlmData  # noqa: F401
