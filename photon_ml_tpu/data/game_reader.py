"""GAME training-data ingest (Avro).

The analogue of the reference's ``AvroDataReader`` for GAME data
(SURVEY.md §2 "Avro IO", §3.2): each record carries response / weight /
offset, an ``ids`` map (entity id columns: userId, itemId, ...), and
feature bags as a map shard-name → [ {name, term, value} ] — the reference's
"feature shards"/"bags".  Reading produces per-shard CSR matrices over
per-shard feature index maps (built on the fly or supplied, the reference's
``IndexMapLoader`` behaviors).
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.io import avro

GAME_EXAMPLE_SCHEMA = {
    "type": "record",
    "name": "GameTrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string"]},
        {"name": "response", "type": "double"},
        {"name": "weight", "type": ["null", "double"]},
        {"name": "offset", "type": ["null", "double"]},
        {"name": "ids", "type": {"type": "map", "values": "string"}},
        {
            "name": "features",
            "type": {
                "type": "map",
                "values": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "GameFeatureAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ],
                    },
                },
            },
        },
    ],
}


def write_game_avro(path: str, rows: list[dict]) -> None:
    """Write GAME examples (dicts shaped like GAME_EXAMPLE_SCHEMA)."""
    avro.write_container(path, GAME_EXAMPLE_SCHEMA, rows)


def read_game_avro(
    path: str,
    index_maps: Optional[dict] = None,
    add_intercept_shards: tuple[str, ...] = (),
    logger=None,
):
    """Read GAME Avro data.

    Returns ``(shards, ids, response, weight, offset, uids, index_maps)``
    where ``shards`` maps shard name → CSR matrix indexed by
    ``index_maps[shard]`` (built from the data when not supplied — supplying
    them is the scoring path, where unseen features are dropped, as the
    reference's scoring driver does).
    """
    _, records = avro.read_container(path)
    n = len(records)
    response = np.zeros(n, np.float32)
    weight = np.ones(n, np.float32)
    offset = np.zeros(n, np.float32)
    uids: list[Optional[str]] = []
    id_cols: dict[str, list] = {}
    shard_rows: dict[str, tuple[list, list, list]] = {}  # rows, cols, vals
    building = index_maps is None
    if building:
        index_maps = {}
    forward: dict[str, dict] = {
        s: dict(m) for s, m in (index_maps or {}).items()
    }

    dropped: dict[str, int] = {}

    for i, rec in enumerate(records):
        response[i] = rec["response"]
        if rec["weight"] is not None:
            weight[i] = rec["weight"]
        if rec["offset"] is not None:
            offset[i] = rec["offset"]
        uids.append(rec["uid"])
        for k, v in rec["ids"].items():
            id_cols.setdefault(k, [None] * n)[i] = v
        for shard, feats in rec["features"].items():
            if not building and shard not in forward:
                # Scoring path: a whole feature shard absent from the
                # supplied index maps is skipped (same policy as dropping
                # unseen features), counted below.
                dropped[shard] = dropped.get(shard, 0) + len(feats)
                continue
            rows, cols, vals = shard_rows.setdefault(shard, ([], [], []))
            fwd = forward.setdefault(shard, {})
            for f in feats:
                key = feature_key(f["name"], f["term"])
                idx = fwd.get(key)
                if idx is None:
                    if not building:
                        dropped[shard] = dropped.get(shard, 0) + 1
                        continue  # scoring path: drop unseen features
                    idx = len(fwd)
                    fwd[key] = idx
                rows.append(i)
                cols.append(idx)
                vals.append(f["value"])

    if dropped:
        # Default to the module logger; drivers pass their PhotonLogger so
        # the warning lands in the job's photon.log artifact too.
        (logger or logging.getLogger(__name__)).warning(
            "read_game_avro(%s): dropped features absent from supplied index "
            "maps: %s",
            path,
            ", ".join(f"{s}={c}" for s, c in sorted(dropped.items())),
        )

    shards: dict = {}
    out_maps: dict = {}
    for shard, (rows, cols, vals) in shard_rows.items():
        fwd = forward[shard]
        if building and shard in add_intercept_shards:
            fwd.setdefault(INTERCEPT_KEY, len(fwd))
        d = len(fwd)
        imap = index_maps[shard] if not building else IndexMap.build(fwd)
        if shard in add_intercept_shards and INTERCEPT_KEY in imap:
            icol = imap[INTERCEPT_KEY]
            rows = rows + list(range(n))
            cols = cols + [icol] * n
            vals = vals + [1.0] * n
        shards[shard] = sp.csr_matrix(
            (np.asarray(vals, np.float32),
             (np.asarray(rows, np.int64), np.asarray(cols, np.int64))),
            shape=(n, d),
        )
        out_maps[shard] = imap

    ids = {k: np.asarray(v) for k, v in id_cols.items()}
    return shards, ids, response, weight, offset, uids, out_maps
