"""GAME training-data ingest (Avro).

The analogue of the reference's ``AvroDataReader`` for GAME data
(SURVEY.md §2 "Avro IO", §3.2): each record carries response / weight /
offset, an ``ids`` map (entity id columns: userId, itemId, ...), and
feature bags as a map shard-name → [ {name, term, value} ] — the reference's
"feature shards"/"bags".  Reading produces per-shard CSR matrices over
per-shard feature index maps (built on the fly or supplied, the reference's
``IndexMapLoader`` behaviors).

Scale path: the file is STREAMED block-by-block (``io.avro.iter_blocks``) —
no list of record dicts is ever materialized — and blocks whose schema
matches the GAME example layout decode through a specialized flat decoder
(direct byte-offset parsing into typed accumulators, no per-record dict /
BytesIO / recursion).  Files with other schemas fall back to the generic
datum decoder, record by record.
"""

from __future__ import annotations

import logging
import struct
from typing import Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.io import avro

GAME_EXAMPLE_SCHEMA = {
    "type": "record",
    "name": "GameTrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string"]},
        {"name": "response", "type": "double"},
        {"name": "weight", "type": ["null", "double"]},
        {"name": "offset", "type": ["null", "double"]},
        {"name": "ids", "type": {"type": "map", "values": "string"}},
        {
            "name": "features",
            "type": {
                "type": "map",
                "values": {
                    "type": "array",
                    "items": {
                        "type": "record",
                        "name": "GameFeatureAvro",
                        "fields": [
                            {"name": "name", "type": "string"},
                            {"name": "term", "type": "string"},
                            {"name": "value", "type": "double"},
                        ],
                    },
                },
            },
        },
    ],
}


def write_game_avro(path: str, rows: list[dict]) -> None:
    """Write GAME examples (dicts shaped like GAME_EXAMPLE_SCHEMA)."""
    avro.write_container(path, GAME_EXAMPLE_SCHEMA, rows)


def _normalize_schema(s):
    """Canonical form for structural comparison: expand shorthand strings,
    drop annotation-only keys (doc/aliases/namespace/default)."""
    if isinstance(s, str):
        return {"type": s}
    if isinstance(s, list):
        return [_normalize_schema(b) for b in s]
    if isinstance(s, dict):
        keep = {}
        for k in ("type", "name", "fields", "items", "values", "symbols"):
            if k in s:
                v = s[k]
                if k == "fields":
                    v = [
                        {
                            "name": f["name"],
                            "type": _normalize_schema(f["type"]),
                        }
                        for f in v
                    ]
                elif k in ("type", "items", "values") and not isinstance(
                    v, str
                ):
                    v = _normalize_schema(v)
                keep[k] = v
        return keep
    return s


def _is_game_schema(schema) -> bool:
    """The flat byte-offset decoder is only safe when the schema matches the
    GAME example layout EXACTLY (field order, types, union branch order) —
    name-only matching would misparse e.g. a non-union ``uid``."""
    try:
        return _normalize_schema(schema) == _normalize_schema(
            GAME_EXAMPLE_SCHEMA
        )
    except (TypeError, KeyError):
        return False


class _Accumulator:
    """Typed columnar sinks shared by both decode paths."""

    def __init__(self, building: bool, forward: dict):
        self.building = building
        self.forward = forward  # shard -> {feature key -> col}
        self.response: list[float] = []
        self.weight: list[float] = []
        self.offset: list[float] = []
        self.uids: list[Optional[str]] = []
        self.id_cols: dict[str, list] = {}
        # shard -> (rows list, cols list, vals list)
        self.shard_rows: dict[str, tuple[list, list, list]] = {}
        self.dropped: dict[str, int] = {}
        self.n = 0

    def add_id(self, key: str, value: str) -> None:
        lst = self.id_cols.get(key)
        if lst is None:
            lst = self.id_cols[key] = []
        if len(lst) < self.n:  # rows before this column first appeared
            lst.extend([None] * (self.n - len(lst)))
        lst.append(value)

    def touch_shard(self, shard: str) -> None:
        """A shard seen in the data materializes (possibly all-zero) unless
        the scoring path is dropping it wholesale."""
        if shard not in self.shard_rows and (
            self.building or shard in self.forward
        ):
            if self.building and shard not in self.forward:
                self.forward[shard] = {}
            self.shard_rows[shard] = ([], [], [])

    def add_feature(self, shard: str, key: str, value: float) -> None:
        fwd = self.forward.get(shard)
        if fwd is None:
            if not self.building:
                self.dropped[shard] = self.dropped.get(shard, 0) + 1
                return
            fwd = self.forward[shard] = {}
        # The shard entry must exist even if every feature is dropped:
        # scoring data whose features all drifted out of the index map still
        # needs an all-zero (n, d) matrix, not a missing dict key.
        entry = self.shard_rows.get(shard)
        if entry is None:
            entry = self.shard_rows[shard] = ([], [], [])
        idx = fwd.get(key)
        if idx is None:
            if not self.building:
                self.dropped[shard] = self.dropped.get(shard, 0) + 1
                return
            idx = len(fwd)
            fwd[key] = idx
        entry[0].append(self.n)
        entry[1].append(idx)
        entry[2].append(value)

    def finish_row(self) -> None:
        self.n += 1


def _decode_game_blocks(path: str, acc: _Accumulator) -> None:
    """Specialized streaming decoder for GAME-schema container files."""
    for _schema, count, payload in avro.iter_blocks(path):
        _decode_game_payload(payload, count, acc)


def _decode_game_payload(payload, count: int, acc: _Accumulator) -> None:
    """Decode ONE container block's payload into ``acc`` (shared by the
    whole-file reader and the bounded-block scoring iterator)."""
    unpack_double = struct.Struct("<d").unpack_from
    pos = 0
    mv = payload

    def read_long():
        nonlocal pos
        shift = 0
        n = 0
        while True:
            b = mv[pos]
            pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return (n >> 1) ^ -(n & 1)
            shift += 7

    def read_str():
        nonlocal pos
        ln = read_long()
        s = mv[pos : pos + ln].decode("utf-8")
        pos += ln
        return s

    for _ in range(count):
        acc.uids.append(read_str() if read_long() == 1 else None)
        acc.response.append(unpack_double(mv, pos)[0])
        pos += 8
        if read_long() == 1:
            acc.weight.append(unpack_double(mv, pos)[0])
            pos += 8
        else:
            acc.weight.append(1.0)
        if read_long() == 1:
            acc.offset.append(unpack_double(mv, pos)[0])
            pos += 8
        else:
            acc.offset.append(0.0)
        # ids map
        while True:
            c = read_long()
            if c == 0:
                break
            if c < 0:
                c = -c
                read_long()  # skip byte-size prefix
            for _ in range(c):
                k = read_str()
                acc.add_id(k, read_str())
        # features map: shard -> [ {name, term, value} ]
        while True:
            c = read_long()
            if c == 0:
                break
            if c < 0:
                c = -c
                read_long()
            for _ in range(c):
                shard = read_str()
                acc.touch_shard(shard)
                while True:
                    fc = read_long()
                    if fc == 0:
                        break
                    if fc < 0:
                        fc = -fc
                        read_long()
                    for _ in range(fc):
                        name = read_str()
                        term = read_str()
                        val = unpack_double(mv, pos)[0]
                        pos += 8
                        acc.add_feature(
                            shard, feature_key(name, term), val
                        )
        acc.finish_row()


def _native_preload_args(forward: dict) -> list:
    """Encode the shard vocabularies ONCE for session preloading — the
    per-block sessions of the streaming iterator must not re-sort and
    re-encode a multi-million-key vocabulary per yielded block."""
    import ctypes

    out = []
    for shard, fwd in forward.items():
        keys = [k for k, _ in sorted(fwd.items(), key=lambda kv: kv[1])]
        arr = (ctypes.c_char_p * len(keys))(
            *[k.encode("utf-8") for k in keys]
        )
        out.append((shard.encode("utf-8"), arr, len(keys)))
    return out


def _native_new(lib, acc: _Accumulator, preload_args: list = None):
    """Fresh native decode session with the accumulator's shard maps
    preloaded (scoring mode)."""
    h = lib.gd_new(1 if acc.building else 0)
    if not acc.building:
        if preload_args is None:
            preload_args = _native_preload_args(acc.forward)
        for shard_b, arr, nkeys in preload_args:
            lib.gd_preload_shard(h, shard_b, arr, nkeys)
    return h


def _native_feed(lib, h, path: str, payload, count: int) -> None:
    rc = lib.gd_decode_block(h, payload, len(payload), count)
    if rc != 0:
        raise ValueError(
            f"{path}: {lib.gd_error(h).decode()} (native decoder)"
        )


def _decode_game_blocks_native(path: str, acc: _Accumulator) -> bool:
    """Decode through the C++ session (photon_ml_tpu/native): the whole
    per-feature hot path — varint parsing AND the feature-key→column hash
    lookups — runs in native code; only columnar arrays cross back.
    Returns False (leaving ``acc`` untouched) when the native library is
    unavailable, True on success.  Raises ValueError on malformed input,
    like the Python decoders."""
    from photon_ml_tpu.native import load_game_decoder

    lib = load_game_decoder()
    if lib is None:
        return False
    h = _native_new(lib, acc)
    try:
        for _schema, count, payload in avro.iter_blocks(path):
            _native_feed(lib, h, path, payload, count)
        _native_extract(lib, h, acc)
        return True
    finally:
        lib.gd_free(h)


def _native_extract(lib, h, acc: _Accumulator) -> None:
    """Pull the session's accumulated columnar arrays into ``acc``
    (REPLACES the columnar fields — callers pass a fresh accumulator)."""
    import ctypes

    n = lib.gd_n_rows(h)
    acc.n = int(n)

    resp = np.empty(n, np.float64)
    wt = np.empty(n, np.float64)
    off = np.empty(n, np.float64)
    as_d = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    as_i = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    as_f = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    if n:
        lib.gd_copy_row_data(h, as_d(resp), as_d(wt), as_d(off))
    acc.response = resp
    acc.weight = wt
    acc.offset = off

    def _strings(blob_len, copy_fn):
        blob = ctypes.create_string_buffer(max(int(blob_len), 1))
        start = np.empty(n, np.int64)
        end = np.empty(n, np.int64)
        if n:
            copy_fn(blob, as_i(start), as_i(end))
        raw = blob.raw
        return [
            raw[s:e].decode("utf-8") if s >= 0 else None
            for s, e in zip(start, end)
        ]

    acc.uids = _strings(
        lib.gd_uid_blob_len(h),
        lambda b, s, e: lib.gd_copy_uids(h, b, s, e),
    )
    for i in range(lib.gd_n_id_cols(h)):
        name = lib.gd_id_col_name(h, i).decode("utf-8")
        acc.id_cols[name] = _strings(
            lib.gd_id_col_blob_len(h, i),
            lambda b, s, e, i=i: lib.gd_copy_id_col(h, i, b, s, e),
        )

    for i in range(lib.gd_n_shards(h)):
        shard = lib.gd_shard_name(h, i).decode("utf-8")
        dropped = int(lib.gd_shard_dropped(h, i))
        if dropped:
            acc.dropped[shard] = dropped
        if lib.gd_shard_unknown(h, i) or not lib.gd_shard_seen(h, i):
            # Unknown shard (scoring) → excluded; preloaded shard never
            # seen in the data → excluded (matches the Python paths).
            continue
        nnz = int(lib.gd_shard_nnz(h, i))
        rows = np.empty(nnz, np.int64)
        cols = np.empty(nnz, np.int64)
        vals = np.empty(nnz, np.float32)
        if nnz:
            lib.gd_copy_shard_coo(h, i, as_i(rows), as_i(cols), as_f(vals))
        acc.shard_rows[shard] = (rows, cols, vals)
        if acc.building:
            nkeys = int(lib.gd_shard_nkeys(h, i))
            blob = ctypes.create_string_buffer(
                max(int(lib.gd_shard_keys_blob_len(h, i)), 1)
            )
            offsets = np.empty(nkeys, np.int64)
            if nkeys:
                lib.gd_copy_shard_keys(h, i, blob, as_i(offsets))
            raw = blob.raw
            keys = []
            pos = 0
            for koff in offsets:
                keys.append(raw[pos:koff].decode("utf-8"))
                pos = int(koff)
            acc.forward[shard] = {k: j for j, k in enumerate(keys)}


def _decode_generic(path: str, acc: _Accumulator) -> None:
    """Fallback: stream records through the generic datum decoder."""
    for rec in avro.iter_container(path):
        _add_generic_record(rec, acc)


def _add_generic_record(rec, acc: _Accumulator) -> None:
    acc.uids.append(rec.get("uid"))
    acc.response.append(float(rec["response"]))
    acc.weight.append(
        1.0 if rec.get("weight") is None else float(rec["weight"])
    )
    acc.offset.append(
        0.0 if rec.get("offset") is None else float(rec["offset"])
    )
    for k, v in rec.get("ids", {}).items():
        acc.add_id(k, v)
    for shard, feats in rec.get("features", {}).items():
        acc.touch_shard(shard)
        for f in feats:
            acc.add_feature(
                shard, feature_key(f["name"], f["term"]), f["value"]
            )
    acc.finish_row()


def read_game_avro(
    path: str,
    index_maps: Optional[dict] = None,
    add_intercept_shards: tuple[str, ...] = (),
    logger=None,
):
    """Read GAME Avro data.

    Returns ``(shards, ids, response, weight, offset, uids, index_maps)``
    where ``shards`` maps shard name → CSR matrix indexed by
    ``index_maps[shard]`` (built from the data when not supplied — supplying
    them is the scoring path, where unseen features are dropped, as the
    reference's scoring driver does).
    """
    building = index_maps is None
    forward: dict[str, dict] = {
        s: dict(m) for s, m in (index_maps or {}).items()
    }
    acc = _Accumulator(building, forward)
    if _is_game_schema(avro.read_schema(path)):
        if not _decode_game_blocks_native(path, acc):
            _decode_game_blocks(path, acc)
    else:
        _decode_generic(path, acc)
    n = acc.n

    if acc.dropped:
        # Default to the module logger; drivers pass their PhotonLogger so
        # the warning lands in the job's photon.log artifact too.
        (logger or logging.getLogger(__name__)).warning(
            "read_game_avro(%s): dropped features absent from supplied index "
            "maps: %s",
            path,
            ", ".join(f"{s}={c}" for s, c in sorted(acc.dropped.items())),
        )

    shards: dict = {}
    out_maps: dict = {}
    for shard, (rows, cols, vals) in acc.shard_rows.items():
        fwd = forward[shard]
        if building and shard in add_intercept_shards:
            fwd.setdefault(INTERCEPT_KEY, len(fwd))
        imap = index_maps[shard] if not building else IndexMap.build(fwd)
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        vals = np.asarray(vals, np.float32)
        if shard in add_intercept_shards and INTERCEPT_KEY in imap:
            icol = imap[INTERCEPT_KEY]
            rows = np.concatenate([rows, np.arange(n, dtype=np.int64)])
            cols = np.concatenate([cols, np.full(n, icol, np.int64)])
            vals = np.concatenate([vals, np.ones(n, np.float32)])
        shards[shard] = sp.csr_matrix(
            (vals, (rows, cols)), shape=(n, len(fwd)),
        )
        out_maps[shard] = imap

    ids = {}
    for k, lst in acc.id_cols.items():
        if len(lst) < n:  # trailing rows missing this column
            lst.extend([None] * (n - len(lst)))
        ids[k] = np.asarray(lst)
    response = np.asarray(acc.response, np.float32)
    weight = np.asarray(acc.weight, np.float32)
    offset = np.asarray(acc.offset, np.float32)
    return shards, ids, response, weight, offset, acc.uids, out_maps


def iter_game_avro(
    path: str,
    index_maps: dict,
    block_rows: int = 1 << 16,
    logger=None,
    id_keys=(),
):
    """Stream GAME Avro data in bounded row blocks — the out-of-core
    SCORING read path (SURVEY.md §3.3: the reference's scoring driver
    handles arbitrary-size data via Spark partitions; here the bound is
    one block of rows, never the file).

    Yields ``(shards, ids, response, weight, offset, uids)`` per block.
    Blocks flush at container-block boundaries once at least ``block_rows``
    rows accumulated, so a yielded block can exceed ``block_rows`` by at
    most one container block's rows.  ``index_maps`` is REQUIRED: scoring
    uses the saved maps (unseen features drop); a block-local index build
    would give inconsistent columns across blocks.

    Every index-mapped shard materializes in every block (all-zero when
    the block carries no features for it), and every key in ``id_keys``
    (the model's entity-id columns) materializes in every block's ``ids``
    (None-padded) — per-block consumers need stable dict layouts, not
    ones keyed by what happened to appear in the block's rows.
    """
    if index_maps is None:
        raise ValueError(
            "iter_game_avro needs saved index maps (the scoring path)"
        )
    if block_rows <= 0:
        raise ValueError(f"block_rows must be positive, got {block_rows}")
    forward: dict[str, dict] = {
        s: dict(m) for s, m in index_maps.items()
    }
    dropped_total: dict[str, int] = {}

    def fresh_acc() -> _Accumulator:
        return _Accumulator(False, forward)

    def assemble(acc: _Accumulator):
        n = acc.n
        shards = {}
        for shard, fwd in forward.items():
            rows, cols, vals = acc.shard_rows.get(shard, ([], [], []))
            shards[shard] = sp.csr_matrix(
                (
                    np.asarray(vals, np.float32),
                    (
                        np.asarray(rows, np.int64),
                        np.asarray(cols, np.int64),
                    ),
                ),
                shape=(n, len(fwd)),
            )
        ids = {}
        # Canonical (sorted) key order: block-local insertion order would
        # differ from the resident reader's whole-file order, and a raw
        # set union is hash-order nondeterministic run to run.  The
        # scoring driver also sorts at the write point; both layers being
        # canonical keeps streamed/resident outputs byte-identical.
        for k in sorted(set(acc.id_cols) | set(id_keys)):
            lst = acc.id_cols.get(k, [])
            if len(lst) < n:
                lst.extend([None] * (n - len(lst)))
            ids[k] = np.asarray(lst)
        for s, c in acc.dropped.items():
            dropped_total[s] = dropped_total.get(s, 0) + c
        return (
            shards,
            ids,
            np.asarray(acc.response, np.float32),
            np.asarray(acc.weight, np.float32),
            np.asarray(acc.offset, np.float32),
            acc.uids,
        )

    acc = fresh_acc()
    if _is_game_schema(avro.read_schema(path)):
        from photon_ml_tpu.native import load_game_decoder

        lib = load_game_decoder()
        if lib is not None:
            # Native path: one C++ session per yielded block — the varint
            # + feature-hash hot loop stays native exactly where streaming
            # matters (multi-GB files); only columnar arrays cross back.
            h = None
            preload = _native_preload_args(forward)
            try:
                for _schema, count, payload in avro.iter_blocks(path):
                    if h is None:
                        h = _native_new(lib, acc, preload)
                    _native_feed(lib, h, path, payload, count)
                    if int(lib.gd_n_rows(h)) >= block_rows:
                        _native_extract(lib, h, acc)
                        lib.gd_free(h)
                        h = None
                        yield assemble(acc)
                        acc = fresh_acc()
                if h is not None:
                    _native_extract(lib, h, acc)
                    lib.gd_free(h)
                    h = None
            finally:
                if h is not None:
                    lib.gd_free(h)
        else:
            for _schema, count, payload in avro.iter_blocks(path):
                _decode_game_payload(payload, count, acc)
                if acc.n >= block_rows:
                    yield assemble(acc)
                    acc = fresh_acc()
    else:
        for rec in avro.iter_container(path):
            _add_generic_record(rec, acc)
            if acc.n >= block_rows:
                yield assemble(acc)
                acc = fresh_acc()
    if acc.n:
        yield assemble(acc)
    if dropped_total:
        (logger or logging.getLogger(__name__)).warning(
            "iter_game_avro(%s): dropped features absent from supplied "
            "index maps: %s",
            path,
            ", ".join(
                f"{s}={c}" for s, c in sorted(dropped_total.items())
            ),
        )
