"""Staged named-dataset resolution (a1a, MovieLens-20M).

BASELINE.json's benchmark configs name public datasets this environment
cannot download (no egress).  ``resolve_dataset`` finds a staged copy —
``$PHOTON_DATA_DIR/<name>`` first, then ``<repo>/datasets/<name>`` — or
returns None; callers (integration tests, benchmark hooks) must then skip
LOUDLY rather than substitute synthetic data silently.  Staging
instructions live in ``datasets/README.md``.
"""

from __future__ import annotations

import os
from typing import Optional

_REPO_DATASETS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "datasets",
)


def resolve_dataset(name: str) -> Optional[str]:
    """Absolute path of a staged dataset file, or None when not staged."""
    env_dir = os.environ.get("PHOTON_DATA_DIR")
    for root in ([env_dir] if env_dir else []) + [_REPO_DATASETS]:
        path = os.path.join(root, name)
        if os.path.exists(path):
            return path
    return None


def skip_reason(name: str) -> str:
    return (
        f"named dataset {name!r} is not staged (no network egress in this "
        f"environment); stage it under datasets/ or $PHOTON_DATA_DIR — see "
        "datasets/README.md for the exact curl commands"
    )
