"""LIBSVM-format ingest (host side).

The reference's driver tests train on classic LIBSVM datasets such as ``a1a``
(SURVEY.md §4; BASELINE.json: "L2 logistic regression on a1a (LIBSVM)").
This is the host-side text→CSR path; Avro ingest lives in io/avro.py.

Pure NumPy parsing — the output feeds
:func:`photon_ml_tpu.data.dataset.make_glm_data` which pads to static shapes
before anything touches the device.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp


def read_libsvm(
    path: str,
    n_features: int | None = None,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
    add_intercept: bool = False,
    drop_out_of_range: bool = False,
):
    """Read a LIBSVM/SVMlight text file.

    Returns ``(X, y)`` with X a scipy CSR matrix and y float32 labels.
    ``±1`` labels are mapped to ``{0, 1}`` when ``binary_labels_to_01`` (the
    losses' convention).  ``add_intercept`` appends a constant-1 column at
    index ``n_features`` (the reference appends its intercept last as well).
    ``drop_out_of_range`` silently drops features with index >= n_features —
    the scoring/validation convention (features unseen at training time
    contribute nothing), matching the GAME reader's scoring path.
    """
    labels: list[float] = []
    indptr = [0]
    indices: list[int] = []
    values: list[float] = []
    offset = 0 if zero_based else 1
    max_col = -1

    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for item in parts[1:]:
                idx_s, val_s = item.split(":")
                col = int(idx_s) - offset
                if col < 0:
                    raise ValueError(
                        f"negative feature index {col} — wrong zero_based setting?"
                    )
                if (
                    drop_out_of_range
                    and n_features is not None
                    and col >= n_features
                ):
                    continue
                max_col = max(max_col, col)
                indices.append(col)
                values.append(float(val_s))
            indptr.append(len(indices))

    n_rows = len(labels)
    d = n_features if n_features is not None else max_col + 1
    if max_col >= d:
        raise ValueError(f"feature index {max_col} >= n_features={d}")
    X = sp.csr_matrix(
        (
            np.asarray(values, np.float32),
            np.asarray(indices, np.int32),
            np.asarray(indptr, np.int64),
        ),
        shape=(n_rows, d),
    )
    y = np.asarray(labels, np.float32)
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    if add_intercept:
        X = sp.hstack([X, np.ones((n_rows, 1), np.float32)], format="csr")
    return X, y


def write_libsvm(path: str, X, y, zero_based: bool = False) -> None:
    """Inverse of :func:`read_libsvm` (test round-trips, synthetic fixtures)."""
    X = sp.csr_matrix(X)
    offset = 0 if zero_based else 1
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            start, end = X.indptr[i], X.indptr[i + 1]
            feats = " ".join(
                f"{int(c) + offset}:{v:.17g}"
                for c, v in zip(X.indices[start:end], X.data[start:end])
            )
            f.write(f"{y[i]:.17g} {feats}\n".rstrip() + "\n")
