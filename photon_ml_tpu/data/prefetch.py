"""Bounded-depth background prefetch for host→device chunk streams.

The synchronous pattern (``device_put`` then step, inline in the consume
loop) leaves every host-side cost — packing, slicing, dispatch syscalls,
multihost local-block assembly — on the critical path between two device
programs.  This module moves all of it off that path, as a three-stage
software pipeline (the classic latency-hiding shape from the TPU
performance literature — double buffering generalized to a bounded
window):

    pack thread:      get_item(k) ──bounded hand-off queue──►
    transfer thread:  put(item) → [transfer timed to completion] ──►
    caller thread:    queue → consume(k, dev) → release permit

Pack and transfer are SEPARATE threads: chunk k+1's host-side
materialization (staging-buffer stacking, memmap paging, multihost
assembly) runs while chunk k's bytes are still crossing the link — the
transfer thread, not the packer, waits on the transferred array's
readiness, so the link and the host-side copy machinery stay busy
simultaneously.  A bounded hand-off queue (``depth`` items) keeps the
packer from running arbitrarily ahead of the link (host RAM for packed
items stays O(depth)).

A semaphore of ``depth`` permits bounds how many device items are live
(transferred or transferring, not yet consumed): ``depth=2`` is the
classic double buffer (chunk k+1 moves while chunk k computes, ≤2 chunks
in HBM), ``depth=1`` degrades to serial transfer/compute (the
measurement baseline), larger depths absorb jittery transports.  A
permit is released only after ``consume`` returns — consumers that sync
on their results bound actual HBM residency, not just Python references
(the streamed accumulators sync on a bounded window of carries:
optim/streaming.py).

Every transfer is timed to completion on the transfer thread, so
:class:`TransferStats` reports ACHIEVED bytes/second, not dispatch rate
— the distinction that made round 1's throughput numbers wrong (see
ops/README.md "Measurement discipline").  The stats attribute wall time
to STAGES so a regression names the guilty one: ``pack_seconds`` (host
materialization), ``dispatch_seconds`` (the ``put`` call itself, i.e.
Python/runtime dispatch — a subset of ``h2d_seconds``), ``h2d_seconds``
(dispatch through transfer completion) and ``consume_seconds`` (the
caller's per-item compute dispatch + syncs).  When the pipeline
overlaps, the summed stage seconds EXCEED the pass's wall time — the
signature bench_streaming checks for.  Stall counters tell the two
failure stories apart: ``consumer_stalls`` (compute waited on the
queue: the stream is ingest-bound — the 150× gap's signature) vs
``producer_stalls`` (transfers waited on compute: the link is keeping
up and further h2d work is pointless).
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from typing import Callable

import jax

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod

#: how long the caller waits for the background threads after a pass (a
#: healthy pipeline joins in microseconds — this bounds a WEDGED thread).
#: Module-level so tests can shrink it without patching call sites.
JOIN_TIMEOUT_SECONDS = 30.0


@dataclasses.dataclass
class TransferStats:
    """Cumulative host→device transfer observability for one stream.

    Aggregated across passes (``reset()`` between measurement windows);
    ``gbps``/``chunk_seconds`` derive the headline rates and the
    ``*_seconds`` fields attribute wall time per pipeline stage.
    """

    chunks: int = 0  # transfers completed
    bytes: int = 0  # WIRE bytes moved (what actually crossed the link)
    logical_bytes: int = 0  # decoded bytes those transfers stand for
    pack_seconds: float = 0.0  # summed get_item wall (pack stage)
    dispatch_seconds: float = 0.0  # summed put() call wall (⊂ h2d_seconds)
    h2d_seconds: float = 0.0  # summed per-transfer wall time (to completion)
    consume_seconds: float = 0.0  # summed consume() wall (compute stage)
    producer_stalls: int = 0  # transfer waited for a free permit (healthy)
    producer_stall_seconds: float = 0.0
    consumer_stalls: int = 0  # compute waited for a transfer (ingest-bound)
    consumer_stall_seconds: float = 0.0
    passes: int = 0  # completed pipeline runs
    max_live: int = 0  # high-water of concurrently-live device items
    max_live_bytes: int = 0  # high-water of live device BYTES (HBM bound)

    @property
    def gbps(self) -> float:
        """Achieved h2d rate over everything recorded, GB/s — WIRE
        bytes, so this stays an honest link measurement even when the
        stream is compressed."""
        return (
            self.bytes / self.h2d_seconds / 1e9 if self.h2d_seconds else 0.0
        )

    @property
    def compression_ratio(self) -> float:
        """logical/wire bytes over everything recorded (1.0 = raw)."""
        return self.logical_bytes / self.bytes if self.bytes else 1.0

    @property
    def chunk_seconds(self) -> float:
        """Mean per-chunk transfer wall time."""
        return self.h2d_seconds / self.chunks if self.chunks else 0.0

    @property
    def stage_seconds(self) -> float:
        """Summed wall across the three pipeline stages (pack + transfer
        + compute).  When this exceeds a pass's wall-clock time, the
        stages overlapped — the structural witness bench_streaming
        reports.  ``dispatch_seconds`` is a subset of ``h2d_seconds``
        and is NOT double-counted here."""
        return self.pack_seconds + self.h2d_seconds + self.consume_seconds

    def snapshot(self) -> dict:
        d = dataclasses.asdict(self)
        d["gbps"] = self.gbps
        d["chunk_seconds"] = self.chunk_seconds
        d["stage_seconds"] = self.stage_seconds
        d["compression_ratio"] = self.compression_ratio
        return d

    def reset(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, f.default)


class _ProducerFailure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _publish_pass(
    stats: TransferStats, before: tuple, run_max: int,
    run_max_bytes: int = 0,
) -> None:
    """Feed this pass's TransferStats DELTAS into the process telemetry
    registry (PR 1 left the stats a dead-end dataclass unless a caller
    printed them).  Counters accumulate correctly across every stream in
    the process because each pass contributes only its own delta; gauges
    carry the LAST pass's achieved rates.  One call per pass — nothing
    here runs per chunk."""
    tel = telemetry_mod.current()
    if not tel.enabled:
        return
    (bytes0, h2d0, chunks0, cs0, css0, ps0, pss0,
     pack0, disp0, cons0, logical0) = before
    d_bytes = stats.bytes - bytes0
    d_logical = stats.logical_bytes - logical0
    d_h2d = stats.h2d_seconds - h2d0
    d_chunks = stats.chunks - chunks0
    d_pack = stats.pack_seconds - pack0
    d_disp = stats.dispatch_seconds - disp0
    d_cons = stats.consume_seconds - cons0
    tel.counter("h2d_bytes_total").inc(d_bytes)
    # Wire vs logical split (compressed chunk formats): h2d_bytes_total
    # and h2d_gbps stay WIRE-denominated — the honest link measurement —
    # while the stream_* pair lets dashboards derive the encoding's win.
    tel.counter("stream_wire_bytes_total").inc(d_bytes)
    tel.counter("stream_logical_bytes_total").inc(d_logical)
    tel.counter("h2d_chunks_total").inc(d_chunks)
    tel.counter("h2d_seconds").inc(d_h2d)
    tel.counter("prefetch_pack_seconds").inc(d_pack)
    tel.counter("prefetch_dispatch_seconds").inc(d_disp)
    tel.counter("prefetch_consume_seconds").inc(d_cons)
    tel.counter("consumer_stalls").inc(stats.consumer_stalls - cs0)
    tel.counter("consumer_stall_seconds").inc(
        stats.consumer_stall_seconds - css0
    )
    tel.counter("producer_stalls").inc(stats.producer_stalls - ps0)
    tel.counter("producer_stall_seconds").inc(
        stats.producer_stall_seconds - pss0
    )
    tel.counter("prefetch_passes").inc()
    if d_h2d > 0.0:
        tel.gauge("h2d_gbps").set(d_bytes / d_h2d / 1e9)
    if d_bytes > 0:
        tel.gauge("stream_compression_ratio").set(d_logical / d_bytes)
    if d_chunks > 0:
        tel.gauge("h2d_chunk_seconds").set(d_h2d / d_chunks)
        tel.gauge("prefetch_pack_chunk_seconds").set(d_pack / d_chunks)
        tel.gauge("prefetch_dispatch_chunk_seconds").set(d_disp / d_chunks)
        tel.gauge("prefetch_consume_chunk_seconds").set(d_cons / d_chunks)
    tel.gauge("prefetch_max_live").set(run_max)
    # HBM accounting (ROADMAP item 1's measurement foundation): the
    # pass's high-water of transferred-not-yet-consumed device bytes —
    # what the depth bound actually pinned, in bytes rather than items.
    tel.gauge("hbm_live_peak_bytes").set(run_max_bytes)
    tel.event(
        "prefetch.pass",
        chunks=d_chunks,
        bytes=d_bytes,
        h2d_seconds=round(d_h2d, 6),
        pack_seconds=round(d_pack, 6),
        dispatch_seconds=round(d_disp, 6),
        consume_seconds=round(d_cons, 6),
        logical_bytes=d_logical,
        consumer_stalls=stats.consumer_stalls - cs0,
        producer_stalls=stats.producer_stalls - ps0,
        max_live=run_max,
        max_live_bytes=run_max_bytes,
    )


def run_prefetched(
    n_items: int,
    get_item: Callable[[int], object],
    put: Callable[[object], object],
    consume: Callable[[int, object], None],
    depth: int = 2,
    stats: TransferStats | None = None,
    logical_nbytes: Callable[[int], int] | None = None,
) -> int:
    """Stream ``n_items`` through a bounded-depth three-stage pipeline.

    ``get_item(k)`` (pack thread) materializes the host item — packing,
    slicing, stacking, memmap paging all overlap BOTH the link and
    device compute here.  ``put(item)`` (transfer thread) dispatches it
    to the device; the transfer thread — never the packer — waits for
    the transfer to complete, both for honest timing and so ``depth``
    bounds bytes in flight.  ``consume(k, dev)`` (caller thread) runs
    the item's compute; items arrive strictly in order.  Returns this
    run's high-water of live device items (≤ ``depth`` by construction).

    Pack/transfer/consume wall times land in ``stats`` per stage (see
    :class:`TransferStats`).  Pack or transfer exceptions re-raise on
    the caller thread at the failed item's position; a consumer
    exception aborts both background threads promptly (their blocking
    waits poll an abort flag).

    ``logical_nbytes(k)`` — when the host items are COMPRESSED wire
    buffers — reports the decoded bytes item ``k`` stands for, so
    ``stats`` can split wire (``bytes``) from logical
    (``logical_bytes``) transfer accounting.  Defaults to the measured
    wire bytes (ratio 1.0) for uncompressed streams.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    if stats is None:
        stats = TransferStats()
    if n_items == 0:
        stats.passes += 1
        return 0
    stats_before = (
        stats.bytes, stats.h2d_seconds, stats.chunks,
        stats.consumer_stalls, stats.consumer_stall_seconds,
        stats.producer_stalls, stats.producer_stall_seconds,
        stats.pack_seconds, stats.dispatch_seconds, stats.consume_seconds,
        stats.logical_bytes,
    )

    handoff: queue.Queue = queue.Queue(maxsize=depth)
    q: queue.Queue = queue.Queue()
    permits = threading.Semaphore(depth)
    abort = threading.Event()
    live_lock = sanitizers.tracked(threading.Lock(), "prefetch.live")
    live = 0
    live_bytes = 0
    run_max = 0
    run_max_bytes = 0
    # HBM accounting gauges, resolved ONCE per pass (no-op metrics when
    # the hub is disabled, so the per-chunk cost stays one locked set):
    # live device bytes this pipeline currently pins, and how full the
    # prefetch ring is (1.0 = transfers are keeping `depth` items ahead).
    tel = telemetry_mod.current()
    ctx = tel.current_context()
    g_live = tel.gauge("hbm_live_bytes")
    g_occ = tel.gauge("prefetch_ring_occupancy_ratio")

    def _bump(delta: int, nbytes: int) -> None:
        nonlocal live, live_bytes, run_max, run_max_bytes
        with live_lock:
            live += delta
            live_bytes += nbytes
            run_max = max(run_max, live)
            run_max_bytes = max(run_max_bytes, live_bytes)
            lb, occ = live_bytes, live / depth
        g_live.set(lb)
        g_occ.set(occ)

    def _handoff_put(item) -> bool:
        while not abort.is_set():
            try:
                handoff.put(item, timeout=0.05)
                return True
            except queue.Full:
                pass
        return False

    def _packer() -> None:
        # Stage 1: host materialization only — no device calls, so a slow
        # pack never gates the link and a slow link never gates the pack
        # (up to the hand-off bound).  The attached trace context parents
        # this thread's per-pass span under the caller's span, so the
        # Perfetto view nests the pack track inside the streamed solve.
        try:
            with tel.attach(ctx), tel.span(
                "prefetch.pack_stage", items=n_items
            ):
                for k in range(n_items):
                    if abort.is_set():
                        return
                    chaos_mod.maybe_fail("prefetch.pack", item=k)
                    t0 = time.perf_counter()
                    host = get_item(k)
                    stats.pack_seconds += time.perf_counter() - t0
                    nbytes = sum(
                        leaf.nbytes
                        for leaf in jax.tree_util.tree_leaves(host)
                        if hasattr(leaf, "nbytes")
                    )
                    lb = logical_nbytes(k) if logical_nbytes else nbytes
                    if not _handoff_put((k, host, nbytes, lb)):
                        return
                    del host
        except BaseException as exc:  # surfaced on the caller thread
            # In order: the failure rides the hand-off queue behind the
            # items that packed successfully, so the consumer sees items
            # 0..k-1 and then the exception at position k.
            _handoff_put(_ProducerFailure(exc))

    def _transfer() -> None:
        # Stage 2: device dispatch + transfer completion.  Timing waits
        # on the transferred arrays' readiness happen HERE, where they
        # block nobody but the (already link-bound) transfer stream.
        try:
            with tel.attach(ctx), tel.span(
                "prefetch.transfer_stage", items=n_items
            ):
                for _ in range(n_items):
                    item = None
                    while not abort.is_set():
                        try:
                            item = handoff.get(timeout=0.05)
                            break
                        except queue.Empty:
                            pass
                    if item is None:
                        return
                    if isinstance(item, _ProducerFailure):
                        q.put(item)
                        return
                    k, host, nbytes, lb = item
                    if not permits.acquire(blocking=False):
                        t0 = time.perf_counter()
                        while not permits.acquire(timeout=0.05):
                            if abort.is_set():
                                return
                        stats.producer_stalls += 1
                        stats.producer_stall_seconds += (
                            time.perf_counter() - t0
                        )
                    if abort.is_set():
                        return
                    chaos_mod.maybe_fail("prefetch.transfer", item=k)
                    t0 = time.perf_counter()
                    dev = put(host)
                    stats.dispatch_seconds += time.perf_counter() - t0
                    for leaf in jax.tree_util.tree_leaves(dev):
                        if hasattr(leaf, "block_until_ready"):
                            leaf.block_until_ready()
                    stats.h2d_seconds += time.perf_counter() - t0
                    stats.bytes += nbytes
                    stats.logical_bytes += lb
                    stats.chunks += 1
                    _bump(+1, nbytes)
                    q.put((k, dev, nbytes))
                    del dev, host, item
        except BaseException as exc:  # surfaced on the caller thread
            q.put(_ProducerFailure(exc))

    packer = threading.Thread(target=_packer, name="h2d-pack", daemon=True)
    transfer = threading.Thread(
        target=_transfer, name="h2d-prefetch", daemon=True
    )
    packer.start()
    transfer.start()
    try:
        for _ in range(n_items):
            if q.empty():
                t0 = time.perf_counter()
                item = q.get()
                stats.consumer_stalls += 1
                stats.consumer_stall_seconds += time.perf_counter() - t0
            else:
                item = q.get()
            if isinstance(item, _ProducerFailure):
                raise item.exc
            k, dev, nbytes = item
            t0 = time.perf_counter()
            consume(k, dev)
            stats.consume_seconds += time.perf_counter() - t0
            # Drop the device reference BEFORE releasing the permit: the
            # permit accounting is the HBM bound, and a live reference
            # here would let a freed permit admit chunk k+depth while
            # chunk k's buffer still cannot be collected.
            del dev, item
            _bump(-1, -nbytes)
            permits.release()
    except BaseException:
        abort.set()
        raise
    finally:
        packer.join(timeout=JOIN_TIMEOUT_SECONDS)
        transfer.join(timeout=JOIN_TIMEOUT_SECONDS)
        leaked = [t.name for t in (packer, transfer) if t.is_alive()]
        if leaked:
            # A wedged daemon thread outliving its pass is a leak — it
            # pins chunk buffers and (on the transfer thread) the device
            # transport.  Returning normally here used to hide that
            # entirely; now it is counted, and raised when this pass was
            # otherwise about to succeed (an already-propagating failure
            # keeps priority — the count still records the leak).
            tel.counter("prefetch_thread_leak").inc(len(leaked))
            tel.event("prefetch.thread_leak", threads=leaked)
            if sys.exc_info()[0] is None:
                raise RuntimeError(
                    f"prefetch pipeline thread(s) {leaked} still alive "
                    f"after join(timeout={JOIN_TIMEOUT_SECONDS}s): a "
                    "wedged daemon thread leaked — its blocking call "
                    "(get_item/put/transfer wait) never returned; the "
                    "pass's results cannot be trusted to be complete"
                )
        while True:  # drop any queued device refs deterministically
            try:
                q.get_nowait()
            except queue.Empty:
                break
    stats.passes += 1
    stats.max_live = max(stats.max_live, run_max)
    stats.max_live_bytes = max(stats.max_live_bytes, run_max_bytes)
    _publish_pass(stats, stats_before, run_max, run_max_bytes)
    return run_max
