"""Feature normalization without touching the data.

The analogue of the reference's ``NormalizationContext`` /
``NormalizationType`` (SURVEY.md §2): training operates in a *scaled*
coefficient space while the (cached, shared, sparse) data stays unscaled.
For scaled feature x'ⱼ = (xⱼ - shiftⱼ)·factorⱼ, the margin of scaled-space
coefficients w is

    m = Σⱼ wⱼ·factorⱼ·xⱼ  -  Σⱼ wⱼ·factorⱼ·shiftⱼ

so the objective only needs two hooks: component-wise coefficient scaling by
``factors`` and a scalar margin correction ``-<w, factors·shifts>``.  Shifts
require an intercept term (exactly the reference's constraint for
STANDARDIZATION).

Conversion back to the original space (for model output) is
``w_original = w_model · factors`` with the intercept absorbing
``-<w_model, factors·shifts>``.
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class NormalizationType(enum.Enum):
    NONE = "none"
    SCALE_WITH_STANDARD_DEVIATION = "scale_with_standard_deviation"
    SCALE_WITH_MAX_MAGNITUDE = "scale_with_max_magnitude"
    STANDARDIZATION = "standardization"


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["factors", "shifts"],
    meta_fields=["intercept_index"],
)
@dataclasses.dataclass
class NormalizationContext:
    """Broadcast-once normalization state (the reference broadcasts this too).

    ``factors`` / ``shifts`` have shape (n_features,).  ``intercept_index``
    is the column holding the constant-1 intercept feature (or None).  The
    intercept's own factor is 1 and shift is 0 by construction.
    """

    factors: Array
    shifts: Array
    intercept_index: Optional[int] = None

    # -- coefficient-space transforms -------------------------------------
    def model_to_original(self, w_model: Array) -> Array:
        """Map scaled-space coefficients to original-space coefficients."""
        w = w_model * self.factors
        if self.intercept_index is not None:
            corr = -jnp.dot(w_model, self.factors * self.shifts)
            w = w.at[self.intercept_index].add(corr)
        return w

    def original_to_model(self, w_orig: Array) -> Array:
        """Inverse of :meth:`model_to_original` (factors must be nonzero)."""
        w = w_orig / self.factors
        if self.intercept_index is not None:
            # Undo the intercept correction: w_orig[i] = w_model[i]·f[i] + corr
            # where corr depends only on non-intercept coords (shift[i] = 0).
            corr = -jnp.dot(w, self.factors * self.shifts)
            w = w.at[self.intercept_index].add(-corr / self.factors[self.intercept_index])
        return w

    @staticmethod
    def identity(n_features: int) -> "NormalizationContext":
        return NormalizationContext(
            factors=jnp.ones((n_features,), jnp.float32),
            shifts=jnp.zeros((n_features,), jnp.float32),
            intercept_index=None,
        )


def build_normalization(
    norm_type: NormalizationType,
    summary,  # BasicStatisticalSummary (data/stats.py); duck-typed
    intercept_index: Optional[int] = None,
) -> NormalizationContext:
    """Build a NormalizationContext from per-feature summary statistics,
    mirroring the reference's ``NormalizationContext(normalizationType,
    summary, interceptId)`` factory."""
    mean = np.asarray(summary.mean, np.float32)
    std = np.sqrt(np.asarray(summary.variance, np.float32))
    max_mag = np.maximum(
        np.abs(np.asarray(summary.max, np.float32)),
        np.abs(np.asarray(summary.min, np.float32)),
    )
    n = mean.shape[0]
    factors = np.ones(n, np.float32)
    shifts = np.zeros(n, np.float32)

    if norm_type is NormalizationType.NONE:
        pass
    elif norm_type is NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors = 1.0 / np.where(std > 0, std, 1.0)
    elif norm_type is NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors = 1.0 / np.where(max_mag > 0, max_mag, 1.0)
    elif norm_type is NormalizationType.STANDARDIZATION:
        if intercept_index is None:
            raise ValueError(
                "STANDARDIZATION requires an intercept term (as in the reference)"
            )
        factors = 1.0 / np.where(std > 0, std, 1.0)
        shifts = mean.copy()
    else:
        raise ValueError(f"unknown normalization type {norm_type}")

    if intercept_index is not None:
        factors[intercept_index] = 1.0
        shifts[intercept_index] = 0.0

    return NormalizationContext(
        factors=jnp.asarray(factors),
        shifts=jnp.asarray(shifts),
        intercept_index=intercept_index,
    )
