"""Per-feature summary statistics.

The analogue of the reference's ``BasicStatisticalSummary`` /
``FeatureDataStatistics`` (SURVEY.md §2, Statistics): weighted per-feature
mean, variance, min, max, and nonzero counts, computed on-device in one pass
of (sparse) column reductions — the reference computes the same via a Spark
aggregate over partitions.  Feeds normalization (data/normalization.py) and
the feature-summary output of the legacy driver.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import GlmData

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["mean", "variance", "min", "max", "nnz", "count"],
    meta_fields=[],
)
@dataclasses.dataclass
class BasicStatisticalSummary:
    mean: Array  # (n_features,) weighted mean
    variance: Array  # (n_features,) weighted (population) variance
    min: Array  # (n_features,)
    max: Array  # (n_features,)
    nnz: Array  # (n_features,) int32 — unweighted nonzero counts
    count: Array  # scalar — total weight


def summarize(data: GlmData, axis_name: str | None = None) -> BasicStatisticalSummary:
    """One-pass weighted feature summary.  Jit-safe; pass ``axis_name`` inside
    ``shard_map`` to psum the moments across row shards (the treeAggregate
    analogue of the reference's distributed summarization)."""
    X = data.features
    w = data.weights
    w_sum = jnp.sum(w)
    s1 = X.rmatvec(w)  # Σ w·x per feature
    s2 = X.sq_rmatvec(w)  # Σ w·x² per feature
    # Padding rows (weight 0) must not leak their zeros into nnz/min/max;
    # the weighted moments exclude them via w already.
    row_mask = w > 0
    nnz = X.col_nnz(row_mask)
    mins, maxs = X.col_min_max(row_mask)

    if axis_name is not None:
        from jax import lax

        w_sum, s1, s2, nnz = lax.psum((w_sum, s1, s2, nnz), axis_name)
        mins = lax.pmin(mins, axis_name)
        maxs = lax.pmax(maxs, axis_name)

    denom = jnp.maximum(w_sum, 1e-30)
    mean = s1 / denom
    variance = jnp.maximum(s2 / denom - mean * mean, 0.0)
    return BasicStatisticalSummary(
        mean=mean, variance=variance, min=mins, max=maxs, nnz=nnz, count=w_sum
    )
