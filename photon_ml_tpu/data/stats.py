"""Per-feature summary statistics.

The analogue of the reference's ``BasicStatisticalSummary`` /
``FeatureDataStatistics`` (SURVEY.md §2, Statistics): weighted per-feature
mean, variance, min, max, and nonzero counts, computed on-device in one pass
of (sparse) column reductions — the reference computes the same via a Spark
aggregate over partitions.  Feeds normalization (data/normalization.py) and
the feature-summary output of the legacy driver.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import GlmData

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["mean", "variance", "min", "max", "nnz", "count"],
    meta_fields=[],
)
@dataclasses.dataclass
class BasicStatisticalSummary:
    mean: Array  # (n_features,) weighted mean
    variance: Array  # (n_features,) weighted (population) variance
    min: Array  # (n_features,)
    max: Array  # (n_features,)
    nnz: Array  # (n_features,) int32 — unweighted nonzero counts
    count: Array  # scalar — total weight


def summarize(data: GlmData, axis_name: str | None = None) -> BasicStatisticalSummary:
    """One-pass weighted feature summary.  Jit-safe; pass ``axis_name`` inside
    ``shard_map`` to psum the moments across row shards (the treeAggregate
    analogue of the reference's distributed summarization)."""
    X = data.features
    w = data.weights
    w_sum = jnp.sum(w)
    s1 = X.rmatvec(w)  # Σ w·x per feature
    s2 = X.sq_rmatvec(w)  # Σ w·x² per feature
    # Padding rows (weight 0) must not leak their zeros into nnz/min/max;
    # the weighted moments exclude them via w already.
    row_mask = w > 0
    nnz = X.col_nnz(row_mask)
    mins, maxs = X.col_min_max(row_mask)

    if axis_name is not None:
        from jax import lax

        w_sum, s1, s2, nnz = lax.psum((w_sum, s1, s2, nnz), axis_name)
        mins = lax.pmin(mins, axis_name)
        maxs = lax.pmax(maxs, axis_name)

    denom = jnp.maximum(w_sum, 1e-30)
    mean = s1 / denom
    variance = jnp.maximum(s2 / denom - mean * mean, 0.0)
    return BasicStatisticalSummary(
        mean=mean, variance=variance, min=mins, max=maxs, nnz=nnz, count=w_sum
    )


def summarize_host(X, weights=None) -> BasicStatisticalSummary:
    """Host-side (numpy/scipy) summary of a raw feature matrix — the GAME
    driver summarizes each feature shard without a device upload.  Same
    semantics as :func:`summarize`: weighted moments over all rows,
    nnz/min/max over live (weight > 0) rows folded with implicit zeros."""
    import numpy as np
    import scipy.sparse as sp

    n, d = X.shape
    w = (
        np.ones(n, np.float64) if weights is None
        else np.asarray(weights, np.float64)
    )
    w_sum = float(w.sum())
    if sp.issparse(X):
        csr = X.tocsr()
        csr.sum_duplicates()
        coo = csr.tocoo()
        rows, cols, vals = coo.row, coo.col, coo.data.astype(np.float64)
        wv = w[rows] * vals
        s1 = np.bincount(cols, weights=wv, minlength=d)
        s2 = np.bincount(cols, weights=wv * vals, minlength=d)
        live = (vals != 0) & (w[rows] > 0)
        c, v = cols[live], vals[live]
        nnz = np.bincount(c, minlength=d)
        mins = np.full(d, np.inf)
        maxs = np.full(d, -np.inf)
        np.minimum.at(mins, c, v)
        np.maximum.at(maxs, c, v)
        n_live = int(np.sum(w > 0))
        has_zero = nnz < n_live
        mins = np.where(has_zero, np.minimum(mins, 0.0), mins)
        maxs = np.where(has_zero, np.maximum(maxs, 0.0), maxs)
    else:
        dense = np.asarray(X, np.float64)
        s1 = w @ dense
        s2 = w @ (dense * dense)
        live_rows = w > 0
        live = dense[live_rows]
        nnz = np.count_nonzero(live, axis=0)
        mins = live.min(axis=0) if live.shape[0] else np.zeros(d)
        maxs = live.max(axis=0) if live.shape[0] else np.zeros(d)
    mean = s1 / max(w_sum, 1e-12)
    variance = np.maximum(s2 / max(w_sum, 1e-12) - mean * mean, 0.0)
    return BasicStatisticalSummary(
        mean=mean.astype(np.float64),
        variance=variance,
        min=np.asarray(mins, np.float64),
        max=np.asarray(maxs, np.float64),
        nnz=np.asarray(nnz, np.int32),
        count=np.float64(w_sum),
    )


def entity_shape_histogram(
    row_counts, col_counts, max_entities: int = 500_000, seed: int = 0
):
    """Distinct per-entity (row count, active-feature count) shapes with
    multiplicities — the summary the GAME entity repacker plans buckets
    from (game/data.py).

    Returns ``(shapes, counts, inverse)``: ``shapes`` is ``(K, 2)`` int64
    sorted lexicographically, ``counts[k]`` how many entities have shape
    k, and ``inverse[e]`` each entity's shape index.  Column counts
    clamp to >= 1 (an entity with no active features still occupies a
    1-wide lane).  Above ``max_entities`` the multiplicities are
    estimated from a seeded uniform subsample (scaled back up), keeping
    plan construction O(max_entities) — ``inverse`` still covers every
    entity, so assignment stays exact; only the cost estimates coarsen.
    """
    import numpy as np

    rows = np.asarray(row_counts, np.int64)
    cols = np.maximum(np.asarray(col_counts, np.int64), 1)
    pairs = np.stack([rows, cols], axis=1)
    shapes, inverse, counts = np.unique(
        pairs, axis=0, return_inverse=True, return_counts=True
    )
    n_ent = len(rows)
    if n_ent > max_entities:
        rng = np.random.default_rng(seed)
        sample = rng.choice(n_ent, size=max_entities, replace=False)
        sample_counts = np.bincount(
            inverse[sample], minlength=len(shapes)
        ).astype(np.float64)
        scale = n_ent / max_entities
        counts = np.maximum(
            np.round(sample_counts * scale), 1
        ).astype(np.int64)
    return shapes.astype(np.int64), counts.astype(np.int64), inverse
