"""Out-of-core GLM training data: a host-RAM chunk store streamed to HBM.

SURVEY.md §7 names "Host→device ingest bandwidth for 1B rows" as a hard
part of the port: the reference keeps the dataset as a persisted Spark RDD
across executor memory, re-scanned by every ``treeAggregate`` pass
(SURVEY.md §3.1).  The TPU analogue here: the dataset lives in HOST RAM as
a list of equal-shaped chunk pytrees, and every objective evaluation
streams them through the chip with double-buffered ``device_put`` —
HBM only ever holds ~2 chunks, so trainable dataset size is bounded by
host RAM (and, with the Avro block reader, by disk), not by HBM.

Design constraints that shape this module:

- **One compiled program must serve every chunk** — per-chunk shapes and
  pytree structure are uniformized at build time (row padding, a common
  nnz budget, :func:`~photon_ml_tpu.ops.sparse_pallas.uniformize_pallas_layouts`
  for the tiled layouts).  A retrace per chunk would dwarf the transfer
  cost.
- **Chunks move as coalesced staging buffers, and live there too.**  A
  chunk's pytree has dozens of small leaves (slot codes, spill triples,
  dense stripes...), and one ``device_put`` per leaf pays the
  transport's fixed per-transfer cost per LEAF instead of per CHUNK —
  the dominant term in the round-5 150× streamed-vs-resident gap.  At
  build time each finished chunk is therefore packed into a few
  dtype-segregated contiguous staging buffers (data/staging.py), shaped
  ``(n_shards, elems)`` so mesh placement shards a buffer exactly like
  the leaves it carries.  ``chunks[k]`` stays the familiar
  :class:`GlmData` pytree, but its numpy leaves are ZERO-COPY VIEWS
  into ``staged[k]`` — host consumers read leaves, the transfer layer
  moves buffers, and the store pays no second copy.  A transfer is
  1-3 large ``device_put`` calls + a compiled slice/reshape unpack
  fused into the per-chunk program (Snap ML's pinned-staging-buffer
  discipline, arXiv:1803.06333).
- **Chunks hold numpy leaves**, never device arrays: the whole point is
  that the resident set exceeds HBM.
- **Ingest is incremental**: :func:`streaming_from_blocks` re-cuts an
  arbitrary block stream (e.g. Avro ``iter_blocks``) at ``chunk_rows``
  boundaries as blocks arrive, building each chunk's device layout the
  moment it fills and dropping the raw rows — peak host memory is the
  finished chunk store plus ~one chunk of raw buffer, never a second full
  copy of the dataset.  Staging packs one chunk at a time, so the peak
  gains only ~one transient chunk copy.
- **Disk-backed stores spill the STAGING buffers** (1-3 ``.npy`` files
  per chunk, memmapped back; leaf views slice the memmap), so a
  disk-resident chunk still reaches the device as a few large paged
  reads, not dozens of small ones.
- **Padding discipline**: rows added to fill the last chunk carry weight 0
  (exactly like the mesh row-padding in parallel/distributed.py), so every
  objective/metric reduction is unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterable

import jax
import numpy as np

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.data.staging import (
    ChunkStaging,
    chunk_view,
    pack_chunk,
    plan_staging,
)
from photon_ml_tpu.ops.sparse import (
    DenseMatrix,
    SparseMatrix,
    canonicalize_coo,
    pad_coo_triples,
)


def _cpu_device():
    """The host CPU device, when a CPU backend exists next to the TPU —
    layout builds placed there never round-trip chunk data through HBM."""
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return None


class _nullctx:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


@dataclasses.dataclass
class StreamingGlmData:
    """A GLM dataset as a list of uniform host-resident chunks.

    ``chunks`` are :class:`GlmData` pytrees with numpy leaves, every chunk
    identical in structure and shape (the last one row-padded with weight
    0).  With ``n_shards > 1`` every array additionally carries a leading
    shard axis for data-parallel placement (the streamed analogue of
    parallel/distributed.DistributedGlmData).

    ``staged``/``staging``: the coalesced transfer representation — per
    chunk, a tuple of dtype-segregated contiguous staging buffers whose
    layout :class:`~photon_ml_tpu.data.staging.ChunkStaging` records.
    When present, ``chunks[k]``'s leaves are zero-copy views into
    ``staged[k]`` (no second host copy) and consumers transfer the
    buffers instead of the leaf pytree.  Builder-produced stores are
    always staged; :meth:`ensure_staged` retrofits hand-built RAM
    stores.
    """

    chunks: list  # list[GlmData], numpy leaves (views into staged[k])
    n_rows: int  # real (unpadded) row count over all chunks
    n_features: int
    chunk_rows: int  # rows per chunk (uniform, incl. padding)
    n_shards: int = 1
    staging: ChunkStaging | None = None
    staged: list | None = None  # per chunk: tuple of staging buffers

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    @property
    def weight_sum(self) -> float:
        return float(sum(np.sum(c.weights) for c in self.chunks))

    def nbytes(self) -> int:
        """Host bytes held by all chunk leaves (for HBM-vs-dataset checks)."""
        return int(sum(
            leaf.nbytes
            for c in self.chunks
            for leaf in jax.tree.leaves(c)
            if hasattr(leaf, "nbytes")
        ))

    @functools.cached_property
    def _has_nonzero_offsets(self) -> bool:
        return bool(any(np.any(c.offsets) for c in self.chunks))

    def has_nonzero_offsets(self) -> bool:
        """Whether any chunk carries data offsets.  Cached after the first
        call — the O(dataset) host scan must not repeat per consumer (a
        GAME config grid constructs one coordinate per grid point against
        the same cached stream)."""
        return self._has_nonzero_offsets

    def ensure_staged(self) -> bool:
        """Pack the chunks into coalesced staging buffers if they are not
        already (hand-built stores; builder output is pre-staged).

        Returns whether the store is staged afterwards.  Disk-backed
        (memmap-leaf) stores that were not staged at build time are left
        alone — packing them here would materialize the whole store in
        RAM, the exact bound the memmaps exist to avoid."""
        if self.staged is not None:
            return True
        if not self.chunks:
            return False
        if any(
            isinstance(leaf, np.memmap)
            for leaf in jax.tree_util.tree_leaves(self.chunks[0])
        ):
            return False
        staging = plan_staging(self.chunks[0], self.n_shards)
        staged, views = [], []
        for c in self.chunks:
            bufs = pack_chunk(staging, c)
            treedef = jax.tree_util.tree_structure(c)
            staged.append(bufs)
            # Replace the originals with views so the buffers hold the
            # only copy (packing is a re-residency, not a duplication).
            views.append(chunk_view(staging, bufs, treedef))
        self.staging = staging
        self.staged = staged
        self.chunks = views
        return True


def spill_tree(tree, dir_: str, tag: str):
    """Replace a pytree's numpy leaves with disk-backed memmaps (one
    ``.npy`` per leaf under ``dir_``).  Downstream code is agnostic:
    ``np.memmap`` is an ndarray, ``device_put`` pages it straight from
    disk, and ``np.asarray`` materializes transiently.  The spill step of
    the MEMORY_AND_DISK residency ladder (the reference persists its
    RDDs exactly so — SURVEY.md §2).  The chunk store itself no longer
    spills per-leaf — its final chunks go to disk as packed staging
    buffers (see the module docstring); this helper serves the
    random-effect datasets and the builder's transient pre-uniformization
    spill."""
    import os

    os.makedirs(dir_, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, np.ndarray) and leaf.size > 0:
            path = os.path.join(dir_, f"{tag}_{i}.npy")
            np.save(path, np.ascontiguousarray(leaf))
            out.append(np.load(path, mmap_mode="r"))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def spill_random_effect_dataset(dataset, dir_: str):
    """A host random-effect dataset with every block's leaves on disk —
    feeds the out-of-core coordinates when even the HOST copy exceeds
    RAM (the blocks page through the OS cache as pass groups slice
    them)."""
    import dataclasses as _dc

    return _dc.replace(
        dataset,
        blocks=[
            spill_tree(b, dir_, f"re_block{i}")
            for i, b in enumerate(dataset.blocks)
        ],
        passive_blocks=[
            None if b is None else spill_tree(b, dir_, f"re_passive{i}")
            for i, b in enumerate(dataset.passive_blocks)
        ],
    )


def make_streaming_glm_data(
    features,
    labels,
    weights=None,
    offsets=None,
    chunk_rows: int = 1 << 20,
    use_pallas: bool | str = "auto",
    depth_cap: int = 128,
    n_shards: int = 1,
    coo_budget: int | None = None,
    storage_dir: str | None = None,
) -> StreamingGlmData:
    """Cut already-materialized host data into uniform chunks.

    ``features``: numpy 2-D array or scipy sparse matrix.  A convenience
    wrapper over :func:`streaming_from_blocks` with the whole dataset as
    one block (the raw rows are the caller's array either way — no extra
    full copy is built; chunks are cut and their layouts built one at a
    time).
    """
    n = features.shape[0]
    weights = (
        np.ones(n, np.float32) if weights is None
        else np.asarray(weights, np.float32)
    )
    offsets = (
        np.zeros(n, np.float32) if offsets is None
        else np.asarray(offsets, np.float32)
    )
    return streaming_from_blocks(
        [(features, np.asarray(labels, np.float32), weights, offsets)],
        n_features=features.shape[1],
        chunk_rows=chunk_rows,
        use_pallas=use_pallas,
        depth_cap=depth_cap,
        n_shards=n_shards,
        coo_budget=coo_budget,
        storage_dir=storage_dir,
    )


def streaming_from_blocks(
    blocks: Iterable,
    n_features: int,
    chunk_rows: int = 1 << 20,
    use_pallas: bool | str = "auto",
    depth_cap: int = 128,
    n_shards: int = 1,
    coo_budget: int | None = None,
    storage_dir: str | None = None,
) -> StreamingGlmData:
    """Build the chunk store from an iterator of ``(X, y[, w[, o]])``
    blocks (e.g. Avro ``iter_blocks`` output), re-cut to ``chunk_rows``
    boundaries AS THEY ARRIVE: each chunk's device layout is built the
    moment it fills and its raw rows are dropped, so peak host memory is
    the finished chunk store plus about one chunk of raw buffer — the
    dataset is never materialized as one giant matrix.

    Blocks may be scipy sparse or numpy (the first block decides; later
    blocks are converted).  ``use_pallas`` chooses the tiled Pallas layout
    for sparse chunks ("auto": on TPU — matching make_glm_data's resident
    heuristic); layouts are built with ``col_permutation=False`` and
    uniformized at the end so one jitted program serves every chunk.
    ``n_shards > 1`` stacks each chunk into per-device row blocks on a
    leading shard axis — for the tiled layout, one per-shard layout each,
    uniformized across chunks × shards and stacked leaf-wise, so the
    streamed-DP shard_map program runs the Pallas kernels per shard.
    """
    import os
    import shutil

    import scipy.sparse as sp

    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    # storage_dir: DISK-backed store.  Each chunk's leaves spill to .npy
    # as the chunk finishes (ingest RAM stays ~one chunk + the raw
    # buffer) and again after cross-chunk uniformization (one padded
    # chunk in RAM at a time); the returned chunks hold memmap leaves
    # that page through the OS cache as training streams them — host
    # RAM stops bounding the trainable size, disk does (the reference's
    # MEMORY_AND_DISK RDD persistence).
    raw_dir = None
    if storage_dir is not None:
        os.makedirs(storage_dir, exist_ok=True)
        if os.listdir(storage_dir):
            # A reused directory would leave a prior (possibly larger)
            # build's chunk files alongside this one — a silent disk leak
            # in the directory whose purpose is bounding disk footprint.
            raise ValueError(
                f"storage_dir {storage_dir!r} is not empty; point each "
                "build at a fresh directory (or clear it first)"
            )
        raw_dir = os.path.join(storage_dir, "raw")
    if n_shards > 1 and chunk_rows % n_shards:
        chunk_rows = -(-chunk_rows // n_shards) * n_shards
    per_shard = chunk_rows // max(n_shards, 1)

    d = int(n_features)
    cpu = _cpu_device()

    # Raw row buffer (≤ one chunk + one incoming block) and finished
    # chunks.  For the tiled-Pallas path the finished entry is a host
    # layout (uniformized at the end); for COO it is canonicalized
    # triples (padded to the global nnz budget at the end); dense chunks
    # are finished outright.
    buf_X: list = []
    buf_y: list = []
    buf_w: list = []
    buf_o: list = []
    buffered = 0
    finished: list = []
    vectors: list = []  # (labels, weights, offsets) per chunk, padded
    n_rows = 0
    mode = None  # "pallas" | "coo" | "dense", fixed by the first block

    def _decide_mode(first_sparse: bool) -> str:
        up = use_pallas
        if up == "auto":
            up = first_sparse and jax.default_backend() == "tpu"
        if up and not first_sparse:
            raise ValueError("use_pallas=True needs sparse features")
        return "pallas" if up else ("coo" if first_sparse else "dense")

    def _finish_chunk(X, y, w, o):
        """X has exactly ``chunk_rows`` rows (zero rows appended for the
        final partial chunk; their weights are 0)."""
        vectors.append((y, w, o))
        if mode == "pallas":
            from photon_ml_tpu.ops.sparse_pallas import (
                build_pallas_matrix,
                layout_to_host,
            )

            # One tiled layout per shard's row block, over (per_shard, d);
            # with n_shards == 1 that is the whole chunk.  All chunk×shard
            # layouts are uniformized together at the end, so one
            # shard_map program serves every chunk (streamed DP at the
            # kernel rate, not the COO rate).
            shard_mats = []
            for s in range(max(n_shards, 1)):
                coo = X[s * per_shard:(s + 1) * per_shard].tocoo()
                # A fresh context per entry: jax.default_device returns a
                # single-use context manager on older jax releases.
                ctx = (
                    jax.default_device(cpu) if cpu is not None
                    else _nullctx()
                )
                with ctx:
                    P = build_pallas_matrix(
                        coo.row.astype(np.int64), coo.col.astype(np.int64),
                        coo.data.astype(np.float32), per_shard, d,
                        depth_cap=depth_cap, col_permutation=False,
                    )
                shard_mats.append(layout_to_host(P))
            if raw_dir is not None:
                shard_mats = [
                    spill_tree(m, raw_dir, f"c{len(finished)}_s{s}")
                    for s, m in enumerate(shard_mats)
                ]
            finished.append(shard_mats)
        elif mode == "coo":
            shards = []
            for s in range(max(n_shards, 1)):
                block = X[s * per_shard:(s + 1) * per_shard]
                coo = block.tocoo()
                shards.append(canonicalize_coo(
                    coo.row, coo.col, coo.data.astype(np.float32),
                    per_shard, d,
                ))
            if raw_dir is not None:
                shards = [
                    spill_tree(t, raw_dir, f"c{len(finished)}_s{s}")
                    for s, t in enumerate(shards)
                ]
            finished.append(shards)
        else:
            dense = np.asarray(X, np.float32)
            feat = DenseMatrix(
                dense if n_shards == 1
                else dense.reshape(n_shards, per_shard, d)
            )
            if storage_dir is not None:
                # Dense needs no cross-chunk uniformization: spill the
                # FINAL leaves directly, no raw copy.
                feat = spill_tree(
                    feat, storage_dir, f"chunk{len(finished)}_X"
                )
            finished.append(feat)

    buf_off = 0  # rows of buf_X[0] already consumed by earlier cuts

    def _pop_rows(take: int):
        """Copy exactly ``take`` rows off the front of the buffer.  A
        cursor (``buf_off``) walks the straddling first entry instead of
        re-slicing its tail, so each cut touches one chunk's worth of rows
        — a single giant input block (the make_streaming_glm_data path) is
        never re-copied once per chunk."""
        nonlocal buffered, buf_off
        Xp, yp, wp, op = [], [], [], []
        got = 0
        while got < take:
            avail = buf_X[0].shape[0] - buf_off
            use = min(avail, take - got)
            lo, hi = buf_off, buf_off + use
            Xp.append(buf_X[0][lo:hi])
            yp.append(buf_y[0][lo:hi])
            wp.append(buf_w[0][lo:hi])
            op.append(buf_o[0][lo:hi])
            got += use
            buf_off += use
            if buf_off == buf_X[0].shape[0]:
                buf_X.pop(0)
                buf_y.pop(0)
                buf_w.pop(0)
                buf_o.pop(0)
                buf_off = 0
        buffered -= take
        X = (
            np.vstack(Xp) if mode == "dense"
            else sp.vstack(Xp).tocsr()
        )
        return X, np.concatenate(yp), np.concatenate(wp), np.concatenate(op)

    def _drain(final: bool) -> None:
        while buffered >= chunk_rows or (final and buffered > 0):
            take = min(buffered, chunk_rows)
            Xc, yc, wc, oc = _pop_rows(take)
            pad = chunk_rows - take
            if pad:
                if mode == "dense":
                    Xc = np.concatenate(
                        [Xc, np.zeros((pad, d), np.float32)]
                    )
                else:
                    Xc = sp.vstack(
                        [Xc, sp.csr_matrix((pad, d), dtype=np.float32)]
                    ).tocsr()
                yc = np.concatenate([yc, np.zeros(pad, np.float32)])
                wc = np.concatenate([wc, np.zeros(pad, np.float32)])
                oc = np.concatenate([oc, np.zeros(pad, np.float32)])
            _finish_chunk(Xc, yc, wc, oc)

    for block in blocks:
        X, y = block[0], block[1]
        m = X.shape[0]
        w = (
            np.asarray(block[2], np.float32)
            if len(block) > 2 and block[2] is not None
            else np.ones(m, np.float32)
        )
        o = (
            np.asarray(block[3], np.float32)
            if len(block) > 3 and block[3] is not None
            else np.zeros(m, np.float32)
        )
        if X.shape[1] != d:
            raise ValueError(
                f"block has {X.shape[1]} features, expected {d}"
            )
        if mode is None:
            mode = _decide_mode(sp.issparse(X))
        if mode == "dense":
            X = X.toarray() if sp.issparse(X) else np.asarray(X, np.float32)
        else:
            X = sp.csr_matrix(X) if not sp.issparse(X) else X.tocsr()
            X.sum_duplicates()
        buf_X.append(X)
        buf_y.append(np.asarray(y, np.float32))
        buf_w.append(w)
        buf_o.append(o)
        buffered += m
        n_rows += m
        _drain(final=False)
    if mode is None:
        raise ValueError("no blocks")
    _drain(final=True)

    staging_box: list = [None]  # ChunkStaging, planned on the first chunk
    staged: list = []

    def _finalize_chunk(gd: GlmData, k: int) -> GlmData:
        """Stage one finished uniform chunk: pack its leaves into the
        dtype-segregated coalesced buffers (RAM: the buffers become the
        only copy, leaves turn into views; disk: the BUFFERS are what
        spills — 1-3 memmapped ``.npy`` per chunk instead of one per
        leaf).  One chunk is transiently duplicated during the pack,
        matching the build's stated peak-memory discipline."""
        if staging_box[0] is None:
            staging_box[0] = plan_staging(gd, n_shards)
        plan = staging_box[0]
        old_files = [
            leaf.filename
            for leaf in jax.tree_util.tree_leaves(gd)
            if isinstance(leaf, np.memmap)
            and getattr(leaf, "filename", None)
        ]
        bufs = pack_chunk(plan, gd)
        if storage_dir is not None:
            spilled = []
            for b, buf in enumerate(bufs):
                path = os.path.join(storage_dir, f"chunk{k}_stage{b}.npy")
                np.save(path, buf)
                spilled.append(np.load(path, mmap_mode="r"))
            bufs = tuple(spilled)
            for path in old_files:
                # Finish-time per-leaf spills (the dense path) are
                # superseded by the packed buffers; removing them keeps
                # the directory's footprint at ~one staged store.
                try:
                    os.remove(path)
                except OSError:
                    pass
        treedef = jax.tree_util.tree_structure(gd)
        staged.append(bufs)
        # The view keeps this chunk's OWN metadata (host_coo cold-path
        # triples differ per chunk even though their shape class — and
        # so the staging plan — is uniform).
        return chunk_view(plan, bufs, treedef)

    # Finalize: uniform shapes across chunks, then stage.
    chunks = []
    if mode == "pallas":
        from photon_ml_tpu.ops.sparse_pallas import (
            uniformize_one,
            uniformize_targets,
        )

        n_sh = max(n_shards, 1)
        # Uniformize across chunks AND shards: every layout shares one
        # pytree structure/shape set, so the per-chunk program compiles
        # once and the stacked shard leaves carry one common leading axis
        # for the mesh sharding.  Targets come from a metadata-only pass;
        # each chunk then pads (and, with storage_dir, respills) ONE at a
        # time — on a disk-backed build, RAM never holds more than one
        # padded chunk.
        targets = uniformize_targets(
            [m for shard_mats in finished for m in shard_mats]
        )
        for k, (y, w, o) in enumerate(vectors):
            ms = [uniformize_one(m, targets) for m in finished[k]]
            if n_shards == 1:
                gd = GlmData(ms[0], y, w, o)
            else:
                feat = jax.tree.map(lambda *xs: np.stack(xs), *ms)
                gd = GlmData(
                    feat,
                    y.reshape(n_shards, per_shard),
                    w.reshape(n_shards, per_shard),
                    o.reshape(n_shards, per_shard),
                )
            chunks.append(_finalize_chunk(gd, k))
            finished[k] = None  # drop the pre-pad layouts as we go
    elif mode == "coo":
        budget = max(
            1,
            max(len(r) for shards in finished for (r, _, _) in shards),
        )
        if coo_budget is not None:
            # Pod runs: every process must pad its COO chunks to ONE
            # agreed budget or the global chunk shapes (and therefore
            # the compiled SPMD programs) diverge across processes.
            if coo_budget < budget:
                raise ValueError(
                    f"coo_budget={coo_budget} is below this store's "
                    f"largest per-shard chunk nnz ({budget})"
                )
            budget = coo_budget
        for k, (shards, (y, w, o)) in enumerate(zip(finished, vectors)):
            padded = [pad_coo_triples(*t, budget) for t in shards]
            if n_shards == 1:
                r, c, v = padded[0]
                feat = SparseMatrix(r, c, v, chunk_rows, d)
                gd = GlmData(feat, y, w, o)
            else:
                feat = SparseMatrix(
                    np.stack([p[0] for p in padded]),
                    np.stack([p[1] for p in padded]),
                    np.stack([p[2] for p in padded]),
                    per_shard, d,
                )
                gd = GlmData(
                    feat,
                    y.reshape(n_shards, per_shard),
                    w.reshape(n_shards, per_shard),
                    o.reshape(n_shards, per_shard),
                )
            chunks.append(_finalize_chunk(gd, k))
            finished[k] = None
    else:
        for k, (feat, (y, w, o)) in enumerate(zip(finished, vectors)):
            if n_shards == 1:
                gd = GlmData(feat, y, w, o)
            else:
                gd = GlmData(
                    feat,
                    y.reshape(n_shards, per_shard),
                    w.reshape(n_shards, per_shard),
                    o.reshape(n_shards, per_shard),
                )
            # Dense feature leaves spilled at finish time are packed
            # into the staging buffers here (and their per-leaf files
            # removed — the buffers supersede them).
            chunks.append(_finalize_chunk(gd, k))

    if raw_dir is not None:
        # The pre-uniformization spill is dead weight once the padded
        # chunks are on disk.
        shutil.rmtree(raw_dir, ignore_errors=True)

    return StreamingGlmData(
        chunks=chunks,
        n_rows=n_rows,
        n_features=d,
        chunk_rows=chunk_rows,
        n_shards=n_shards,
        staging=staging_box[0],
        staged=staged,
    )
