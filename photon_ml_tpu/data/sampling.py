"""Down-sampling.

The analogue of the reference's ``...ml.sampling`` package (SURVEY.md §2):
``DefaultDownSampler`` (uniform row sampling) and
``BinaryClassificationDownSampler`` (negative down-sampling for imbalanced
binary data, with weight re-scaling so the objective stays unbiased).  The
reference applies these to the fixed-effect coordinate's dataset before
training; here they act on host arrays before device upload.
"""

from __future__ import annotations

import numpy as np


class DefaultDownSampler:
    """Keep each row with probability ``rate``, re-weighting survivors by
    ``1/rate`` so weighted sums remain unbiased."""

    def __init__(self, rate: float, seed: int = 0):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"down-sampling rate must be in (0, 1], got {rate}")
        self.rate = rate
        self.seed = seed

    def downsample(self, labels, weights):
        """Returns (row_indices_kept, new_weights_for_kept)."""
        rng = np.random.default_rng(self.seed)
        n = len(labels)
        keep = rng.uniform(size=n) < self.rate
        idx = np.flatnonzero(keep)
        return idx, np.asarray(weights)[idx] / self.rate


class BinaryClassificationDownSampler:
    """Keep all positives; keep each negative with probability ``rate`` and
    re-weight kept negatives by ``1/rate`` (the reference's negative
    down-sampling for imbalanced binary data)."""

    def __init__(self, rate: float, seed: int = 0):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"down-sampling rate must be in (0, 1], got {rate}")
        self.rate = rate
        self.seed = seed

    def downsample(self, labels, weights):
        rng = np.random.default_rng(self.seed)
        labels = np.asarray(labels)
        weights = np.asarray(weights)
        n = len(labels)
        is_pos = labels > 0
        keep = is_pos | (rng.uniform(size=n) < self.rate)
        idx = np.flatnonzero(keep)
        new_w = weights[idx].copy()
        neg_kept = ~is_pos[idx]
        new_w[neg_kept] = new_w[neg_kept] / self.rate
        return idx, new_w
