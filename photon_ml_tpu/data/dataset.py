"""On-device GLM datasets.

The analogue of the reference's ``LabeledPoint`` RDDs and ``FixedEffectDataset``
(SURVEY.md §2, "GAME data layer"), reshaped for TPU: instead of millions of
per-row objects scattered across JVM partitions, one statically-shaped pytree
per shard — features as a :class:`~photon_ml_tpu.ops.sparse.FeatureMatrix`,
labels / weights / offsets as flat arrays.  Padding rows (needed to make every
device's shard the same size) carry ``weight = 0`` so they contribute nothing
to any weighted sum, which is how all downstream math stays mask-free.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.sparse import DenseMatrix, FeatureMatrix, from_scipy_csr

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["features", "labels", "weights", "offsets"],
    meta_fields=[],
)
@dataclasses.dataclass
class GlmData:
    """One shard of GLM training data.

    Mirrors the reference's ``LabeledPoint`` (label, features, offset, weight)
    but batched: all arrays have leading dimension ``n_rows``.
    """

    features: FeatureMatrix
    labels: Array  # (n_rows,)
    weights: Array  # (n_rows,) — 0 for padding rows
    offsets: Array  # (n_rows,) — fixed per-row margin offsets

    @property
    def n_rows(self) -> int:
        return self.features.shape[0]

    @property
    def n_features(self) -> int:
        return self.features.shape[1]

    @property
    def weight_sum(self) -> Array:
        return jnp.sum(self.weights)


def make_glm_data(
    features,
    labels,
    weights=None,
    offsets=None,
    pad_rows: int | None = None,
    pad_nnz: int | None = None,
    dtype=jnp.float32,
    use_pallas: bool | str = "auto",
) -> GlmData:
    """Build a GlmData shard from host data.

    ``features`` may be a numpy 2-D array (→ DenseMatrix) or a scipy sparse
    matrix (→ SparseMatrix / PallasSparseMatrix).  ``pad_rows`` pads the row
    dimension with zero-weight rows up to a static budget.

    ``use_pallas`` selects the tiled Pallas layout for sparse features
    (ops/sparse_pallas.py): ``"auto"`` uses it on TPU when the matrix is
    large enough for the kernels to win (the tiled layout costs host build
    time and ~3x slot memory, and pays off via ~70x faster value+grad);
    ``True``/``False`` force it.
    """
    import scipy.sparse as sp

    n = features.shape[0]
    labels = np.asarray(labels, dtype=np.float32)
    weights = (
        np.ones(n, np.float32) if weights is None else np.asarray(weights, np.float32)
    )
    offsets = (
        np.zeros(n, np.float32) if offsets is None else np.asarray(offsets, np.float32)
    )
    target_rows = pad_rows if pad_rows is not None else n
    if target_rows < n:
        raise ValueError(f"pad_rows={target_rows} < n_rows={n}")
    pad = target_rows - n
    if pad:
        labels = np.concatenate([labels, np.zeros(pad, np.float32)])
        weights = np.concatenate([weights, np.zeros(pad, np.float32)])
        offsets = np.concatenate([offsets, np.zeros(pad, np.float32)])

    if sp.issparse(features):
        if pad:
            features = sp.vstack(
                [features.tocsr(), sp.csr_matrix((pad, features.shape[1]))]
            )
        if use_pallas == "auto":
            from photon_ml_tpu.ops.sparse_pallas import pallas_available

            use_pallas = (
                pallas_available()
                and features.shape[0] >= 65536
                and features.nnz >= 1 << 20
            )
        if use_pallas:
            from photon_ml_tpu.ops.sparse_pallas import from_scipy_csr_pallas

            fm: FeatureMatrix = from_scipy_csr_pallas(
                features, pad_nnz=pad_nnz, dtype=dtype)
        else:
            fm = from_scipy_csr(features, pad_nnz=pad_nnz, dtype=dtype)
    else:
        dense = np.asarray(features)
        if pad:
            dense = np.concatenate(
                [dense, np.zeros((pad, dense.shape[1]), dense.dtype)]
            )
        fm = DenseMatrix(jnp.asarray(dense, dtype=dtype))

    return GlmData(
        features=fm,
        labels=jnp.asarray(labels),
        weights=jnp.asarray(weights),
        offsets=jnp.asarray(offsets),
    )
