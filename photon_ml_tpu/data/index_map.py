"""Feature index maps: feature name ↔ column index.

The analogue of the reference's ``IndexMap`` / ``DefaultIndexMap`` /
``PalDBIndexMap`` + ``IndexMapLoader`` (photon-client ``...ml.index``,
SURVEY.md §2).  The reference needs PalDB (off-heap mmap store) because very
wide feature spaces overflow the Spark driver heap; here the map lives only
on the HOST (devices see int32 column ids exclusively), so a plain dict plus
an mmap-friendly on-disk layout (two numpy arrays: sorted name-hashes and
their indices) covers both use cases without a JVM key-value store.

Feature names follow the reference's ``name`` + ``term`` convention
(``NameAndTerm``): the key is ``f"{name}\x01{term}"``; plain names are keys
with an empty term.  The intercept uses the reference's magic name
``(INTERCEPT)``.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, Mapping

import numpy as np

INTERCEPT_KEY = "(INTERCEPT)"
_SEP = "\x01"


def feature_key(name: str, term: str = "") -> str:
    """The reference joins Avro (name, term) pairs into one feature id."""
    return name if not term else f"{name}{_SEP}{term}"


class IndexMap(Mapping[str, int]):
    """Immutable feature-name → column-index map.

    ``index_to_name`` provides the reverse direction (model output writes
    names next to coefficients, as the reference's Avro model format does).
    """

    def __init__(self, name_to_index: dict[str, int]):
        self._forward = dict(name_to_index)
        n = len(self._forward)
        vals = sorted(self._forward.values())
        if vals and (vals[0] != 0 or vals[-1] != n - 1 or len(set(vals)) != n):
            raise ValueError("indices must be a dense permutation of 0..n-1")
        self._reverse: list[str] = [""] * n
        for k, v in self._forward.items():
            self._reverse[v] = k

    # Mapping interface -----------------------------------------------------
    def __getitem__(self, key: str) -> int:
        return self._forward[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._forward)

    def __len__(self) -> int:
        return len(self._forward)

    def get_index(self, key: str, default: int = -1) -> int:
        return self._forward.get(key, default)

    def index_to_name(self, index: int) -> str:
        return self._reverse[index]

    @property
    def intercept_index(self) -> int | None:
        idx = self._forward.get(INTERCEPT_KEY)
        return idx

    # Construction ----------------------------------------------------------
    @staticmethod
    def build(
        feature_names: Iterable[str], add_intercept: bool = False
    ) -> "IndexMap":
        """Assign dense indices in first-seen order (the reference's
        ``DefaultIndexMap`` builds from an RDD distinct + zipWithIndex)."""
        forward: dict[str, int] = {}
        for name in feature_names:
            if name not in forward:
                forward[name] = len(forward)
        if add_intercept and INTERCEPT_KEY not in forward:
            forward[INTERCEPT_KEY] = len(forward)
        return IndexMap(forward)

    # Persistence (the PalDB replacement) -----------------------------------
    def save(self, directory: str) -> None:
        """Write as JSON (names) — mmap-able binary sidecar for huge maps is
        produced on demand at load time via :meth:`save_binary`."""
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "index_map.json"), "w") as f:
            json.dump(self._forward, f)

    @staticmethod
    def load(directory: str) -> "IndexMap":
        with open(os.path.join(directory, "index_map.json")) as f:
            return IndexMap(json.load(f))

    def save_binary(self, directory: str) -> None:
        """Hash-sorted binary layout for very wide spaces: query without
        loading all names into a Python dict (the PalDB use case)."""
        os.makedirs(directory, exist_ok=True)
        names = np.array(self._reverse)
        hashes = np.array(
            [_stable_hash(k) for k in self._reverse], dtype=np.uint64
        )
        order = np.argsort(hashes, kind="stable")
        np.savez(
            os.path.join(directory, "index_map.npz"),
            hashes=hashes[order],
            indices=np.arange(len(names), dtype=np.int64)[order],
            names=names[order],
        )


def _stable_hash(s: str) -> int:
    """64-bit FNV-1a — stable across processes (Python's hash() is salted)."""
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class BinaryIndexMap:
    """Reader for :meth:`IndexMap.save_binary` layouts: O(log n) lookups over
    mmap'd arrays, no dict materialization — the PalDBIndexMap analogue."""

    def __init__(self, directory: str):
        z = np.load(os.path.join(directory, "index_map.npz"), mmap_mode="r")
        self._hashes = z["hashes"]
        self._indices = z["indices"]
        self._names = z["names"]

    def __len__(self) -> int:
        return len(self._hashes)

    def get_index(self, key: str, default: int = -1) -> int:
        h = np.uint64(_stable_hash(key))
        lo = int(np.searchsorted(self._hashes, h, side="left"))
        # Linear probe over (rare) hash collisions.
        while lo < len(self._hashes) and self._hashes[lo] == h:
            if self._names[lo] == key:
                return int(self._indices[lo])
            lo += 1
        return default
