"""Leaf coalescing: one staging buffer per dtype instead of a pytree of
small transfers.

A chunk of streamed GLM data is a pytree of dozens of numpy leaves (the
tiled Pallas layout alone carries slot codes, values, spill triples,
dense stripes and permutation maps).  Moving it with one ``device_put``
per leaf pays the transport's fixed per-transfer cost dozens of times per
chunk — on a tunneled dev chip that fixed cost is the whole bill, and
even on PCIe hosts small transfers run far below the link rate.  Snap ML
(arXiv:1803.06333) gets its out-of-core GLM throughput from exactly one
discipline: chunks cross tiers as large contiguous staging buffers.

This module is that discipline for the chunk store:

- :func:`plan_staging` maps a chunk's leaves onto a few dtype-segregated
  contiguous buffers (one per distinct leaf dtype, each shaped
  ``(n_shards, elems)`` so mesh placement shards the buffer exactly like
  the leaves it carries);
- :func:`pack_chunk` fills those buffers from a chunk's leaves (host
  side, at store-build time);
- :func:`chunk_view` rebuilds the chunk as ZERO-COPY numpy views into
  the buffers, so the host-resident store costs no extra RAM and every
  existing host-side consumer (weight sums, offset scans, tests) keeps
  reading plain leaf arrays;
- :func:`unpack_device` is the compiled on-device inverse — pure
  slice + reshape, traced INTO the per-chunk program so the restored
  ``GlmData`` view costs no extra dispatch and no host round trip.

The transfer layer then moves a chunk as ``len(buffers)`` large
``device_put`` calls (typically 1-3) instead of ``len(leaves)`` small
ones.

**Compressed chunk formats** (ROADMAP item 1's transfer-avoidance half)
ride the same discipline one level down: :func:`plan_compression` scans
a staged store once and assigns every staging SLOT (one pytree leaf's
segment) an opt-in wire encoding — delta/downcast narrowing for index
blocks, bitmaps for {0,1}-valued float segments (f32, f64 and bf16),
an f32 wire for f64 blocks whose every value round-trips bitwise,
fp16/int8 quantization with per-shard scale sidecars — then re-segregates
the encoded slots into wire buffers by WIRE dtype, so a compressed chunk
still crosses as a few large contiguous transfers.  The decode
(:meth:`ChunkCodec.unpack_device`) is pure slice/cast/cumsum/shift
arithmetic traced INTO the per-chunk program exactly like the plain
unpack, so dequantization costs no extra dispatch and the f32 compute
path downstream is unchanged.  Lossless encodings (delta, downcast,
bitmap) reconstruct the device arrays BITWISE; fp16/int8 are lossy and
opt-in per mode.  The spirit is XGBoost's quantized ELLPACK pages
(arXiv:1806.11248): ship a compact encoding, decode next to the compute.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class _LeafSlot:
    """Where one pytree leaf lives inside the staging buffers."""

    buffer: int  # index into the dtype-segregated buffer list
    offset: int  # element offset within one shard's row of that buffer
    size: int  # elements per shard row
    shape: tuple  # full host leaf shape
    shard_shape: tuple  # per-shard shape (== shape when n_shards == 1)


@dataclasses.dataclass(frozen=True)
class ChunkStaging:
    """The staging-buffer layout shared by every chunk of one store.

    Buffers are dtype-segregated: mixing dtypes in one byte buffer would
    either force per-leaf bitcasts on device or break alignment for
    sub-word dtypes (the Pallas int16 slot codes).  A chunk store has a
    handful of distinct dtypes, so the transfer count stays O(1).
    """

    treedef: Any  # pytree structure (meta fields ride along untransferred)
    dtypes: tuple  # per-buffer numpy dtype
    row_elems: tuple  # per-buffer elements per shard row
    slots: tuple  # _LeafSlot per leaf, in tree_flatten order
    n_shards: int

    @property
    def n_buffers(self) -> int:
        return len(self.dtypes)

    @property
    def nbytes(self) -> int:
        """Staged bytes one chunk occupies (= bytes per chunk transfer)."""
        return sum(
            self.n_shards * r * np.dtype(dt).itemsize
            for r, dt in zip(self.row_elems, self.dtypes)
        )

    def pack(self, chunk) -> tuple:
        return pack_chunk(self, chunk)

    def view(self, buffers: Sequence[np.ndarray], treedef=None):
        return chunk_view(self, buffers, treedef)

    def unpack_device(self, buffers):
        return unpack_device(self, buffers)


def _shard_split(shape: tuple, n_shards: int) -> tuple:
    """Per-shard shape of a leaf.  With ``n_shards > 1`` every chunk leaf
    carries the leading shard axis (data/streaming.py's stacked layout)."""
    if n_shards == 1:
        return shape
    if not shape or shape[0] != n_shards:
        raise ValueError(
            f"sharded chunk leaf has shape {shape}; expected leading "
            f"shard axis of {n_shards}"
        )
    return shape[1:]


def plan_staging(chunk, n_shards: int = 1) -> ChunkStaging:
    """Lay the chunk's leaves out over dtype-segregated staging buffers.

    Every chunk of a store shares one plan (the store uniformizes shapes
    at build time); :func:`pack_chunk` enforces that.
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunk)
    dtypes: list = []
    row_elems: list = []
    slots: list = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        shard_shape = _shard_split(arr.shape, n_shards)
        size = int(math.prod(shard_shape))
        dt = arr.dtype
        if dt not in dtypes:
            dtypes.append(dt)
            row_elems.append(0)
        b = dtypes.index(dt)
        slots.append(
            _LeafSlot(
                buffer=b,
                offset=row_elems[b],
                size=size,
                shape=tuple(arr.shape),
                shard_shape=tuple(shard_shape),
            )
        )
        row_elems[b] += size
    return ChunkStaging(
        treedef=treedef,
        dtypes=tuple(dtypes),
        row_elems=tuple(row_elems),
        slots=tuple(slots),
        n_shards=n_shards,
    )


def pack_chunk(staging: ChunkStaging, chunk) -> tuple:
    """Copy a chunk's leaves into freshly-allocated staging buffers.

    Returns one contiguous ``(n_shards, row_elems)`` array per dtype.
    Memmap leaves are paged in transiently (one chunk of RAM), which is
    exactly the disk-backed build's stated peak.
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunk)
    if treedef != staging.treedef:
        raise ValueError(
            "chunk pytree structure does not match the staging plan "
            f"({treedef} vs {staging.treedef})"
        )
    bufs = [
        np.empty((staging.n_shards, r), dt)
        for r, dt in zip(staging.row_elems, staging.dtypes)
    ]
    for leaf, slot in zip(leaves, staging.slots):
        arr = np.asarray(leaf)
        if tuple(arr.shape) != slot.shape or arr.dtype != staging.dtypes[slot.buffer]:
            raise ValueError(
                f"chunk leaf {arr.shape}/{arr.dtype} does not match the "
                f"staging plan's {slot.shape}/"
                f"{staging.dtypes[slot.buffer]} — chunks must be "
                "uniformized before staging"
            )
        dst = bufs[slot.buffer][:, slot.offset : slot.offset + slot.size]
        dst[...] = np.ascontiguousarray(arr).reshape(
            staging.n_shards, slot.size
        )
    return tuple(bufs)


def chunk_view(staging: ChunkStaging, buffers: Sequence[np.ndarray],
               treedef=None):
    """Rebuild the chunk as zero-copy views into the staging buffers.

    ``treedef`` defaults to the plan's; pass the chunk's OWN treedef when
    per-chunk metadata must survive (the Pallas ``host_coo`` cold-path
    triples are pytree META — structurally equal across chunks but
    content-distinct, and the host-side view must keep each chunk's own).
    """
    leaves = []
    for slot in staging.slots:
        seg = buffers[slot.buffer][:, slot.offset : slot.offset + slot.size]
        leaves.append(seg.reshape(slot.shape))
    return jax.tree_util.tree_unflatten(
        staging.treedef if treedef is None else treedef, leaves
    )


def unpack_device(staging: ChunkStaging, buffers):
    """The compiled on-device unpack: slice + reshape only, traced into
    the per-chunk program.

    Works on the full ``(n_shards, row)`` buffers AND on the ``(1, row)``
    per-device blocks seen inside ``shard_map`` — the leading dim is read
    off the traced buffer, so one definition serves both contexts.
    """
    import jax.numpy as jnp  # noqa: F401  (kept local: host module import)
    from jax import lax

    leaves = []
    for slot in staging.slots:
        buf = buffers[slot.buffer]
        seg = lax.slice_in_dim(
            buf, slot.offset, slot.offset + slot.size, axis=1
        )
        if staging.n_shards == 1:
            leaves.append(seg.reshape(slot.shape))
        else:
            leaves.append(seg.reshape((buf.shape[0],) + slot.shard_shape))
    return jax.tree_util.tree_unflatten(staging.treedef, leaves)


# ---------------------------------------------------------------------------
# Compressed chunk formats: per-slot wire encodings + on-device decode
# ---------------------------------------------------------------------------

#: the ``compress`` knob's values.  "lossless" applies only encodings
#: whose device decode reconstructs the uncompressed arrays BITWISE
#: (delta / integer downcast / {0,1} bitmaps for f32, f64 and bf16 /
#: the f64-over-f32-wire downcast when every value round-trips); "fp16"
#: and "int8"
#: additionally quantize float32 segments (lossy, bounded error — see
#: tests/test_staging.py), keeping the lossless integer encodings.
COMPRESSION_MODES = ("off", "lossless", "fp16", "int8")

#: encodings whose decode is exact (bitwise) on the canonical device
#: dtype; everything else is lossy quantization.
_LOSSLESS_KINDS = frozenset({"raw", "downcast", "delta", "bitmap"})

#: narrowing ladders, same signedness as the original dtype (delta wire
#: values can be negative, so unsigned originals only ever downcast).
_SIGNED_LADDER = (np.int8, np.int16, np.int32)
_UNSIGNED_LADDER = (np.uint8, np.uint16, np.uint32)


@dataclasses.dataclass(frozen=True)
class _SlotEncoding:
    """How one staging slot crosses the wire."""

    kind: str  # raw | downcast | delta | bitmap | fp16 | int8
    wire_buffer: int  # index into the codec's wire buffer list
    wire_offset: int  # element offset within one shard's wire row
    wire_size: int  # wire elements per shard row (bitmap: packed bytes)
    scale_index: int = -1  # int8 only: column in the scale sidecar


@dataclasses.dataclass(frozen=True)
class ChunkCodec:
    """The wire format shared by every chunk of one compressed store.

    Like :class:`ChunkStaging`, one codec serves all chunks (encodings
    are chosen so every chunk's values fit — :func:`plan_compression`
    scans the whole store), so ONE compiled decode+unpack program runs
    per chunk.  Per-chunk data (int8 scales) rides inside the float32
    wire buffer as a fixed-offset sidecar, never as a separate transfer
    — on transports where the fixed per-transfer cost dominates, an
    extra tiny ``device_put`` per chunk would eat the encoding's win.
    """

    staging: ChunkStaging  # the LOGICAL layout being encoded
    mode: str
    encodings: tuple  # _SlotEncoding per slot, in staging.slots order
    wire_dtypes: tuple  # per wire buffer
    wire_row_elems: tuple  # per wire buffer, elements per shard row
    n_scales: int  # int8-quantized slot count (sidecar width)
    scale_buffer: int = -1  # wire buffer holding the scale sidecar
    scale_offset: int = 0

    @property
    def n_buffers(self) -> int:
        return len(self.wire_dtypes)

    @property
    def logical_nbytes(self) -> int:
        """Decoded (f32-path) bytes one chunk expands to on device."""
        return self.staging.nbytes

    @property
    def wire_nbytes(self) -> int:
        """Encoded bytes one chunk actually moves across the link."""
        return sum(
            self.staging.n_shards * r * np.dtype(dt).itemsize
            for r, dt in zip(self.wire_row_elems, self.wire_dtypes)
        )

    @property
    def ratio(self) -> float:
        """logical/wire — >1 means the encoding is shrinking transfers."""
        w = self.wire_nbytes
        return self.logical_nbytes / w if w else 1.0

    @property
    def kinds(self) -> tuple:
        """Distinct non-raw encodings in use (empty = fell back to raw)."""
        return tuple(sorted(
            {e.kind for e in self.encodings if e.kind != "raw"}
        ))

    @property
    def is_lossless(self) -> bool:
        return all(e.kind in _LOSSLESS_KINDS for e in self.encodings)

    def encode(self, buffers: Sequence[np.ndarray]) -> tuple:
        """Encode one chunk's staged buffers into wire buffers (host
        side, once per chunk at compression setup — never per pass)."""
        st = self.staging
        wire = [
            np.zeros((st.n_shards, r), dt)
            for r, dt in zip(self.wire_row_elems, self.wire_dtypes)
        ]
        for slot, enc in zip(st.slots, self.encodings):
            seg = np.asarray(buffers[slot.buffer])[
                :, slot.offset : slot.offset + slot.size
            ]
            dst = wire[enc.wire_buffer][
                :, enc.wire_offset : enc.wire_offset + enc.wire_size
            ]
            if enc.kind == "raw":
                dst[...] = seg
            elif enc.kind == "downcast":
                dst[...] = seg.astype(dst.dtype)
            elif enc.kind == "delta":
                d = seg.astype(np.int64)
                d[:, 1:] -= seg[:, :-1].astype(np.int64)
                dst[...] = d.astype(dst.dtype)
            elif enc.kind == "bitmap":
                dst[...] = np.packbits(seg != 0, axis=1)
            elif enc.kind == "fp16":
                dst[...] = seg.astype(np.float16)
            else:  # int8
                m = np.max(np.abs(seg), axis=1, keepdims=True)
                sc = np.where(m > 0.0, m / 127.0, 1.0).astype(np.float32)
                wire[self.scale_buffer][
                    :,
                    self.scale_offset + enc.scale_index
                    : self.scale_offset + enc.scale_index + 1,
                ] = sc
                dst[...] = np.clip(
                    np.rint(seg / sc), -127, 127
                ).astype(np.int8)
        return tuple(wire)

    def unpack_device(self, wire):
        """The compiled on-device decode + unpack: slice, cast, cumsum
        and bit-shift arithmetic only, traced into the per-chunk program
        (the in-program dequant step).  Replaces
        :func:`unpack_device` for compressed items and obeys the same
        shard_map contract — all slicing is relative, the leading dim is
        read off the traced buffer, and per-shard scales arrive inside
        the (sharded) float32 wire buffer."""
        import jax.numpy as jnp
        from jax import lax

        st = self.staging
        scales = None
        if self.n_scales:
            scales = lax.slice_in_dim(
                wire[self.scale_buffer],
                self.scale_offset,
                self.scale_offset + self.n_scales,
                axis=1,
            )
        leaves = []
        for slot, enc in zip(st.slots, self.encodings):
            buf = wire[enc.wire_buffer]
            seg = lax.slice_in_dim(
                buf, enc.wire_offset, enc.wire_offset + enc.wire_size,
                axis=1,
            )
            odt = jax.dtypes.canonicalize_dtype(st.dtypes[slot.buffer])
            if enc.kind == "downcast":
                seg = seg.astype(odt)
            elif enc.kind == "delta":
                # Exact by modular arithmetic: the deltas were computed
                # from values that fit ``odt``, so their running integer
                # sum reconstructs every value bitwise even where an
                # intermediate wraps.
                seg = jnp.cumsum(seg.astype(odt), axis=1)
            elif enc.kind == "bitmap":
                shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
                bits = (seg[:, :, None] >> shifts) & jnp.uint8(1)
                seg = lax.slice_in_dim(
                    bits.reshape((bits.shape[0], -1)), 0, slot.size,
                    axis=1,
                ).astype(odt)
            elif enc.kind == "fp16":
                seg = seg.astype(odt)
            elif enc.kind == "int8":
                sc = lax.slice_in_dim(
                    scales, enc.scale_index, enc.scale_index + 1, axis=1
                )
                seg = seg.astype(odt) * sc
            if st.n_shards == 1:
                leaves.append(seg.reshape(slot.shape))
            else:
                leaves.append(
                    seg.reshape((buf.shape[0],) + slot.shard_shape)
                )
        return jax.tree_util.tree_unflatten(st.treedef, leaves)


def _narrowest(ladder, lo: int, hi: int, max_itemsize: int):
    """Narrowest ladder dtype (strictly below ``max_itemsize``) that
    holds every value in [lo, hi], or None."""
    for dt in ladder:
        if np.dtype(dt).itemsize >= max_itemsize:
            return None
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return dt
    return None


def _plan_int_slot(dt, segments: list):
    """delta/downcast choice for one integer slot: the narrowest wire
    dtype over BOTH the raw range and the per-row delta range (delta
    wins ties' complement — it needs a cumsum on device, so it must buy
    strictly more narrowing than a plain downcast)."""
    vmin = min(int(s.min()) for s in segments)
    vmax = max(int(s.max()) for s in segments)
    signed = np.dtype(dt).kind == "i"
    ladder = _SIGNED_LADDER if signed else _UNSIGNED_LADDER
    down = _narrowest(ladder, vmin, vmax, np.dtype(dt).itemsize)
    delta = None
    if signed:
        # Only each shard row's FIRST element rides the delta wire raw,
        # so the wire range is (first-column values) ∪ (pairwise deltas)
        # — not the full value range.
        dmin = min(int(s[:, 0].min()) for s in segments)
        dmax = max(int(s[:, 0].max()) for s in segments)
        for s in segments:
            if s.shape[1] < 2:
                continue
            d = s[:, 1:].astype(np.int64) - s[:, :-1].astype(np.int64)
            dmin = min(dmin, int(d.min()))
            dmax = max(dmax, int(d.max()))
        delta = _narrowest(
            _SIGNED_LADDER, dmin, dmax, np.dtype(dt).itemsize
        )
    if delta is not None and (
        down is None
        or np.dtype(delta).itemsize < np.dtype(down).itemsize
    ):
        return "delta", np.dtype(delta)
    if down is not None:
        return "downcast", np.dtype(down)
    return "raw", np.dtype(dt)


def _is_binary_f32(segments: list) -> bool:
    """Every element is BITWISE +0.0 or 1.0 — the strict precondition
    for the bitmap encoding to round-trip exactly (-0.0 would decode to
    +0.0, a bit flip)."""
    for s in segments:
        bits = np.ascontiguousarray(s).view(np.uint32)
        if not np.isin(bits, (0x00000000, 0x3F800000)).all():
            return False
    return True


def _is_binary_f64(segments: list) -> bool:
    """The f64 analogue of :func:`_is_binary_f32`: bitwise +0.0 or 1.0
    only (same -0.0 rejection — its bitmap decode would flip the sign
    bit)."""
    for s in segments:
        bits = np.ascontiguousarray(s).view(np.uint64)
        if not np.isin(
            bits, (0x0000000000000000, 0x3FF0000000000000)
        ).all():
            return False
    return True


def _f32_roundtrips_f64(segments: list) -> bool:
    """Every f64 value survives an f32 wire BITWISE (f64 -> f32 -> f64
    is the identity on the bit pattern), so a half-width wire is still
    lossless.  Indicator-heavy and low-precision feature blocks staged
    as f64 pass; anything needing the extra mantissa (or carrying NaN
    payloads f32 can't hold) falls back to raw."""
    for s in segments:
        rt = s.astype(np.float32).astype(np.float64)
        same = (
            np.ascontiguousarray(rt).view(np.uint64)
            == np.ascontiguousarray(s).view(np.uint64)
        )
        if not same.all():
            return False
    return True


def _bfloat16_dtype():
    """The registered bfloat16 numpy dtype, or None when ml_dtypes is
    absent (it ships with jax, so None is the exotic case)."""
    try:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    except Exception:  # pragma: no cover — ml_dtypes rides with jax
        return None


def _is_binary_bf16(segments: list) -> bool:
    """Bitwise +0.0 or 1.0 in bfloat16 (0x0000 / 0x3F80): the bitmap
    precondition for bf16-staged mask/indicator blocks."""
    for s in segments:
        bits = np.ascontiguousarray(s).view(np.uint16)
        if not np.isin(bits, (0x0000, 0x3F80)).all():
            return False
    return True


def plan_compression(
    staging: ChunkStaging, staged: Sequence, mode: str
) -> ChunkCodec | None:
    """Choose one wire encoding per staging slot, valid for EVERY chunk
    of the store (one scan over ``staged``), and lay the encoded slots
    out over wire buffers re-segregated by wire dtype.

    Returns None for mode "off".  A slot falls back to "raw" whenever
    its values rule the candidate encodings out (e.g. an int64 block
    whose values genuinely need 64 bits, or a float segment exceeding
    fp16 range in fp16 mode) — callers that REQUIRE a win should check
    :attr:`ChunkCodec.ratio` and fail loudly (bench_streaming does).
    """
    if mode == "off":
        return None
    if mode not in COMPRESSION_MODES:
        raise ValueError(
            f"compress must be one of {COMPRESSION_MODES}, got {mode!r}"
        )
    if not staged:
        raise ValueError("plan_compression needs a non-empty staged store")

    def segments(slot):
        return [
            np.asarray(bufs[slot.buffer])[
                :, slot.offset : slot.offset + slot.size
            ]
            for bufs in staged
        ]

    plans: list = []  # (kind, wire_dtype) per slot
    n_scales = 0
    for slot in staging.slots:
        dt = np.dtype(staging.dtypes[slot.buffer])
        if slot.size == 0:
            plans.append(("raw", dt))
            continue
        if dt.kind in "iu" and dt.itemsize >= 2:
            plans.append(_plan_int_slot(dt, segments(slot)))
            continue
        if dt == np.float64:
            # f64 staging is rare (x64-enabled hosts, double-precision
            # offsets) but pays double wire width for it — recover the
            # width wherever the VALUES don't need it, bitwise only.
            segs = segments(slot)
            if _is_binary_f64(segs):
                plans.append(("bitmap", np.dtype(np.uint8)))
                continue
            if _f32_roundtrips_f64(segs):
                plans.append(("downcast", np.dtype(np.float32)))
                continue
            plans.append(("raw", dt))
            continue
        bf16 = _bfloat16_dtype()
        if bf16 is not None and dt == bf16:
            segs = segments(slot)
            if _is_binary_bf16(segs):
                plans.append(("bitmap", np.dtype(np.uint8)))
                continue
            plans.append(("raw", dt))
            continue
        if dt == np.float32:
            segs = segments(slot)
            if _is_binary_f32(segs):
                plans.append(("bitmap", np.dtype(np.uint8)))
                continue
            if mode == "fp16":
                maxabs = max(float(np.max(np.abs(s))) for s in segs)
                if math.isfinite(maxabs) and maxabs <= 65504.0:
                    plans.append(("fp16", np.dtype(np.float16)))
                    continue
            elif mode == "int8":
                if all(np.isfinite(s).all() for s in segs):
                    plans.append(("int8", np.dtype(np.int8)))
                    n_scales += 1
                    continue
        plans.append(("raw", dt))

    # Wire layout: slots grouped by wire dtype, in first-appearance
    # order; the int8 scale sidecar claims float32 wire space FIRST so
    # its offset is independent of the (chunk-varying) data that
    # follows.  Bitmap wire length is the packed byte count.
    wire_dtypes: list = []
    wire_row_elems: list = []

    def wire_alloc(dt, elems: int) -> tuple:
        if dt not in wire_dtypes:
            wire_dtypes.append(dt)
            wire_row_elems.append(0)
        b = wire_dtypes.index(dt)
        off = wire_row_elems[b]
        wire_row_elems[b] += elems
        return b, off

    scale_buffer, scale_offset = -1, 0
    if n_scales:
        scale_buffer, scale_offset = wire_alloc(
            np.dtype(np.float32), n_scales
        )
    encodings: list = []
    scale_i = 0
    for slot, (kind, wdt) in zip(staging.slots, plans):
        elems = (
            (slot.size + 7) // 8 if kind == "bitmap" else slot.size
        )
        b, off = wire_alloc(wdt, elems)
        si = -1
        if kind == "int8":
            si = scale_i
            scale_i += 1
        encodings.append(_SlotEncoding(kind, b, off, elems, si))
    return ChunkCodec(
        staging=staging,
        mode=mode,
        encodings=tuple(encodings),
        wire_dtypes=tuple(wire_dtypes),
        wire_row_elems=tuple(wire_row_elems),
        n_scales=n_scales,
        scale_buffer=scale_buffer,
        scale_offset=scale_offset,
    )


# ---------------------------------------------------------------------------
# Wire dtype tags
# ---------------------------------------------------------------------------

#: Stable one-byte tags for the dtypes that may ride a binary wire
#: frame (serving/wire.py).  The table is append-only: tags are part of
#: the framed layout, so a tag must never be renumbered once a frame
#: version has shipped with it.  Segregating payload segments by dtype
#: tag is the same slot idiom :class:`ChunkCodec` uses for compressed
#: chunk buffers — a decoder maps each directory entry straight onto a
#: typed view of the payload, no per-element parsing.
WIRE_DTYPE_TAGS: tuple = (
    np.dtype(np.float32),   # 0
    np.dtype(np.float64),   # 1
    np.dtype(np.float16),   # 2
    np.dtype(np.int8),      # 3
    np.dtype(np.int16),     # 4
    np.dtype(np.int32),     # 5
    np.dtype(np.int64),     # 6
    np.dtype(np.uint8),     # 7
    np.dtype(np.uint16),    # 8
    np.dtype(np.uint32),    # 9
    np.dtype(np.uint64),    # 10
    np.dtype(np.bool_),     # 11
)

_WIRE_TAG_BY_DTYPE = {dt: i for i, dt in enumerate(WIRE_DTYPE_TAGS)}


def wire_dtype_tag(dtype) -> int:
    """The one-byte wire tag for ``dtype``; raises ``KeyError`` with the
    offending dtype named when it has no tag (complex, object, …)."""
    dt = np.dtype(dtype)
    tag = _WIRE_TAG_BY_DTYPE.get(dt)
    if tag is None:
        raise KeyError(
            f"dtype {dt} has no wire tag; supported: "
            f"{[str(d) for d in WIRE_DTYPE_TAGS]}"
        )
    return tag


def wire_dtype_from_tag(tag: int) -> np.dtype:
    """Inverse of :func:`wire_dtype_tag`; raises ``KeyError`` on an
    unknown tag so decoders refuse rather than misread."""
    if not 0 <= tag < len(WIRE_DTYPE_TAGS):
        raise KeyError(f"unknown wire dtype tag {tag}")
    return WIRE_DTYPE_TAGS[tag]
