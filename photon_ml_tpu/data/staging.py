"""Leaf coalescing: one staging buffer per dtype instead of a pytree of
small transfers.

A chunk of streamed GLM data is a pytree of dozens of numpy leaves (the
tiled Pallas layout alone carries slot codes, values, spill triples,
dense stripes and permutation maps).  Moving it with one ``device_put``
per leaf pays the transport's fixed per-transfer cost dozens of times per
chunk — on a tunneled dev chip that fixed cost is the whole bill, and
even on PCIe hosts small transfers run far below the link rate.  Snap ML
(arXiv:1803.06333) gets its out-of-core GLM throughput from exactly one
discipline: chunks cross tiers as large contiguous staging buffers.

This module is that discipline for the chunk store:

- :func:`plan_staging` maps a chunk's leaves onto a few dtype-segregated
  contiguous buffers (one per distinct leaf dtype, each shaped
  ``(n_shards, elems)`` so mesh placement shards the buffer exactly like
  the leaves it carries);
- :func:`pack_chunk` fills those buffers from a chunk's leaves (host
  side, at store-build time);
- :func:`chunk_view` rebuilds the chunk as ZERO-COPY numpy views into
  the buffers, so the host-resident store costs no extra RAM and every
  existing host-side consumer (weight sums, offset scans, tests) keeps
  reading plain leaf arrays;
- :func:`unpack_device` is the compiled on-device inverse — pure
  slice + reshape, traced INTO the per-chunk program so the restored
  ``GlmData`` view costs no extra dispatch and no host round trip.

The transfer layer then moves a chunk as ``len(buffers)`` large
``device_put`` calls (typically 1-3) instead of ``len(leaves)`` small
ones.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class _LeafSlot:
    """Where one pytree leaf lives inside the staging buffers."""

    buffer: int  # index into the dtype-segregated buffer list
    offset: int  # element offset within one shard's row of that buffer
    size: int  # elements per shard row
    shape: tuple  # full host leaf shape
    shard_shape: tuple  # per-shard shape (== shape when n_shards == 1)


@dataclasses.dataclass(frozen=True)
class ChunkStaging:
    """The staging-buffer layout shared by every chunk of one store.

    Buffers are dtype-segregated: mixing dtypes in one byte buffer would
    either force per-leaf bitcasts on device or break alignment for
    sub-word dtypes (the Pallas int16 slot codes).  A chunk store has a
    handful of distinct dtypes, so the transfer count stays O(1).
    """

    treedef: Any  # pytree structure (meta fields ride along untransferred)
    dtypes: tuple  # per-buffer numpy dtype
    row_elems: tuple  # per-buffer elements per shard row
    slots: tuple  # _LeafSlot per leaf, in tree_flatten order
    n_shards: int

    @property
    def n_buffers(self) -> int:
        return len(self.dtypes)

    @property
    def nbytes(self) -> int:
        """Staged bytes one chunk occupies (= bytes per chunk transfer)."""
        return sum(
            self.n_shards * r * np.dtype(dt).itemsize
            for r, dt in zip(self.row_elems, self.dtypes)
        )

    def pack(self, chunk) -> tuple:
        return pack_chunk(self, chunk)

    def view(self, buffers: Sequence[np.ndarray], treedef=None):
        return chunk_view(self, buffers, treedef)

    def unpack_device(self, buffers):
        return unpack_device(self, buffers)


def _shard_split(shape: tuple, n_shards: int) -> tuple:
    """Per-shard shape of a leaf.  With ``n_shards > 1`` every chunk leaf
    carries the leading shard axis (data/streaming.py's stacked layout)."""
    if n_shards == 1:
        return shape
    if not shape or shape[0] != n_shards:
        raise ValueError(
            f"sharded chunk leaf has shape {shape}; expected leading "
            f"shard axis of {n_shards}"
        )
    return shape[1:]


def plan_staging(chunk, n_shards: int = 1) -> ChunkStaging:
    """Lay the chunk's leaves out over dtype-segregated staging buffers.

    Every chunk of a store shares one plan (the store uniformizes shapes
    at build time); :func:`pack_chunk` enforces that.
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunk)
    dtypes: list = []
    row_elems: list = []
    slots: list = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        shard_shape = _shard_split(arr.shape, n_shards)
        size = int(math.prod(shard_shape))
        dt = arr.dtype
        if dt not in dtypes:
            dtypes.append(dt)
            row_elems.append(0)
        b = dtypes.index(dt)
        slots.append(
            _LeafSlot(
                buffer=b,
                offset=row_elems[b],
                size=size,
                shape=tuple(arr.shape),
                shard_shape=tuple(shard_shape),
            )
        )
        row_elems[b] += size
    return ChunkStaging(
        treedef=treedef,
        dtypes=tuple(dtypes),
        row_elems=tuple(row_elems),
        slots=tuple(slots),
        n_shards=n_shards,
    )


def pack_chunk(staging: ChunkStaging, chunk) -> tuple:
    """Copy a chunk's leaves into freshly-allocated staging buffers.

    Returns one contiguous ``(n_shards, row_elems)`` array per dtype.
    Memmap leaves are paged in transiently (one chunk of RAM), which is
    exactly the disk-backed build's stated peak.
    """
    leaves, treedef = jax.tree_util.tree_flatten(chunk)
    if treedef != staging.treedef:
        raise ValueError(
            "chunk pytree structure does not match the staging plan "
            f"({treedef} vs {staging.treedef})"
        )
    bufs = [
        np.empty((staging.n_shards, r), dt)
        for r, dt in zip(staging.row_elems, staging.dtypes)
    ]
    for leaf, slot in zip(leaves, staging.slots):
        arr = np.asarray(leaf)
        if tuple(arr.shape) != slot.shape or arr.dtype != staging.dtypes[slot.buffer]:
            raise ValueError(
                f"chunk leaf {arr.shape}/{arr.dtype} does not match the "
                f"staging plan's {slot.shape}/"
                f"{staging.dtypes[slot.buffer]} — chunks must be "
                "uniformized before staging"
            )
        dst = bufs[slot.buffer][:, slot.offset : slot.offset + slot.size]
        dst[...] = np.ascontiguousarray(arr).reshape(
            staging.n_shards, slot.size
        )
    return tuple(bufs)


def chunk_view(staging: ChunkStaging, buffers: Sequence[np.ndarray],
               treedef=None):
    """Rebuild the chunk as zero-copy views into the staging buffers.

    ``treedef`` defaults to the plan's; pass the chunk's OWN treedef when
    per-chunk metadata must survive (the Pallas ``host_coo`` cold-path
    triples are pytree META — structurally equal across chunks but
    content-distinct, and the host-side view must keep each chunk's own).
    """
    leaves = []
    for slot in staging.slots:
        seg = buffers[slot.buffer][:, slot.offset : slot.offset + slot.size]
        leaves.append(seg.reshape(slot.shape))
    return jax.tree_util.tree_unflatten(
        staging.treedef if treedef is None else treedef, leaves
    )


def unpack_device(staging: ChunkStaging, buffers):
    """The compiled on-device unpack: slice + reshape only, traced into
    the per-chunk program.

    Works on the full ``(n_shards, row)`` buffers AND on the ``(1, row)``
    per-device blocks seen inside ``shard_map`` — the leading dim is read
    off the traced buffer, so one definition serves both contexts.
    """
    import jax.numpy as jnp  # noqa: F401  (kept local: host module import)
    from jax import lax

    leaves = []
    for slot in staging.slots:
        buf = buffers[slot.buffer]
        seg = lax.slice_in_dim(
            buf, slot.offset, slot.offset + slot.size, axis=1
        )
        if staging.n_shards == 1:
            leaves.append(seg.reshape(slot.shape))
        else:
            leaves.append(seg.reshape((buf.shape[0],) + slot.shard_shape))
    return jax.tree_util.tree_unflatten(staging.treedef, leaves)
