"""Crash-safe tuning state: the append-only journal and the trial store.

The orchestrator (tuning/executor.py) survives kills the same way the
training drivers do — by persisting state as it goes and replaying it on
``--resume`` (io/checkpoint.py).  A hyperparameter search's state is not
one blob but an ordered DECISION LOG: every ask (with the proposer's RNG
state after it), every intermediate rung report, every ASHA
promote/kill, every completion fed back to the proposer, every failure.
``TuningJournal`` appends each decision as one JSON line to
``tuning_state.jsonl`` with the same durability discipline as
io/checkpoint's atomic writes (flush + fsync before the append returns),
so a kill at any instant leaves a clean prefix of the uninterrupted
run's log — plus possibly one torn trailing line, which replay drops.

``replay_journal`` folds the record stream back into orchestrator state:
trials with their per-rung metrics and statuses, the event feed that
rebuilds the proposer (asks re-enter the pending set, tells re-enter the
observation set, in the original order), the last journaled RNG state
(so the resumed search proposes the SAME future points an uninterrupted
run would — reproducibility under resume), and the trailing reports
whose promote/kill/tell decision had not been journaled yet (the resumed
orchestrator re-derives those decisions deterministically).

A resume is REFUSED when the journal's search-space fingerprint (or the
proposer / ASHA / direction configuration) differs from the current
run's: replaying half a search into a different search silently blends
two experiments.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional

import numpy as np

from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.io.checkpoint import _atomic_savez, fsync_file

JOURNAL_VERSION = 1


class SearchAborted(RuntimeError):
    """Raised by the journal's test/selfcheck abort hook to simulate a
    mid-flight kill at a deterministic record boundary."""


class ResumeMismatch(ValueError):
    """The journal on disk belongs to a DIFFERENT search (space
    fingerprint or search configuration changed); resuming would blend
    two experiments."""


class TuningJournal:
    """Append-only JSONL decision log with fsync-per-record durability.

    Threads: the orchestrator appends state-bearing records from its
    processing loop, but worker threads append informational ``retry``
    records mid-trial — the lock keeps lines whole.  ``abort_after``
    raises :class:`SearchAborted` INSTEAD of writing the (n+1)-th record
    of this process, simulating a kill exactly at a record boundary
    (torn trailing lines are covered separately by replay's tolerance).
    """

    FILENAME = "tuning_state.jsonl"

    def __init__(
        self,
        directory: str,
        fsync: bool = True,
        abort_after: Optional[int] = None,
    ):
        self.directory = directory
        self.path = os.path.join(directory, self.FILENAME)
        self.fsync = fsync
        self.abort_after = abort_after
        self._lock = sanitizers.tracked(threading.Lock(), "tuning.journal")
        self._f = None
        self._written = 0

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def clear(self) -> None:
        self.close()
        if self.exists():
            os.remove(self.path)

    def append(self, record: dict) -> None:
        with self._lock:
            if (
                self.abort_after is not None
                and self._written >= self.abort_after
            ):
                raise SearchAborted(
                    f"journal abort hook: {self._written} records written"
                )
            if self._f is None:
                os.makedirs(self.directory, exist_ok=True)
                self._f = open(self.path, "a")
            self._f.write(json.dumps(record, default=_json_default) + "\n")
            if self.fsync:
                fsync_file(self._f)
            else:
                self._f.flush()
            self._written += 1

    def read(self) -> list[dict]:
        """Every complete record on disk.  A torn final line (kill mid-
        write without fsync, or a crashed filesystem) is dropped; a torn
        line anywhere ELSE means the file is not an append-only journal
        and raises."""
        if not self.exists():
            return []
        with self._lock:
            if self._f is not None:
                self._f.flush()
            with open(self.path) as f:
                lines = f.read().splitlines()
        records = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn tail from a mid-write kill
                raise ValueError(
                    f"{self.path}: corrupt journal line {i + 1} (not the "
                    "trailing line — the file was edited or is not a "
                    "journal)"
                )
        return records

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "TuningJournal":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _json_default(o):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


class TrialStore:
    """Per-trial coefficient persistence (``trial_<id>.npz`` next to the
    journal, atomic write via io/checkpoint's protocol).

    Completed trials' coefficient vectors feed the executor's
    nearest-point warm-start cache; journaling them as JSON would bloat
    the decision log at real GLM widths, so they live in sidecar .npz
    files the journal's ``tell`` records imply.  Saved BEFORE the
    trial's ``report`` record is appended, so any journaled completion
    has its coefficients on disk — a resumed search warm-starts exactly
    as the uninterrupted one would."""

    def __init__(self, directory: str):
        self.directory = directory

    def _path(self, trial_id: int) -> str:
        return os.path.join(self.directory, f"trial_{trial_id}.npz")

    def save(
        self, trial_id: int, params: np.ndarray, coefficients: np.ndarray
    ) -> None:
        os.makedirs(self.directory, exist_ok=True)
        _atomic_savez(
            self._path(trial_id),
            {
                "params": np.asarray(params, np.float64),
                "coefficients": np.asarray(coefficients),
            },
        )

    def load(self, trial_id: int):
        """(params, coefficients) or None."""
        path = self._path(trial_id)
        if not os.path.exists(path):
            return None
        with np.load(path) as z:
            return z["params"], z["coefficients"]

    def clear(self) -> None:
        import glob

        for path in glob.glob(os.path.join(self.directory, "trial_*.npz")):
            os.remove(path)


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------

#: journal record types that carry orchestrator state; anything else
#: ("retry", "resumed", future additions) is informational and skipped.
STATE_RECORD_TYPES = (
    "header", "ask", "wave", "report", "promote", "kill", "tell", "fail",
)


@dataclasses.dataclass
class ReplayedTrial:
    id: int
    params: np.ndarray
    status: str = "running"  # running | completed | killed | failed
    rung: int = 0  # current rung (promotions applied)
    reports: dict = dataclasses.field(default_factory=dict)  # rung → record
    final_metric: Optional[float] = None


@dataclasses.dataclass
class ReplayState:
    """Everything the orchestrator needs to continue a journaled search."""

    header: dict
    trials: dict  # id → ReplayedTrial
    #: ("ask", params) | ("tell", params, y) | ("resolve", params) in
    #: journal order — folded into the proposer to rebuild its
    #: observation + pending sets.
    proposer_events: list
    #: proposer RNG state after the last journaled ask (None = no asks).
    rng_state: Optional[dict]
    #: (trial_id, rung, y) for every report whose decision WAS journaled —
    #: inserted into the ASHA rung tables without re-deciding.
    decided_reports: list
    #: report records whose promote/kill/tell decision was lost with the
    #: crash — the resumed orchestrator re-derives them, in this order.
    undecided: list
    #: the last journaled wave's [trial, rung] tasks — the wave in flight
    #: at the crash.  Its unreported tasks must re-run as ONE wave (not
    #: merge with later promotions), or the resumed schedule compresses
    #: rungs relative to the uninterrupted run and proposals diverge.
    last_wave: list = dataclasses.field(default_factory=list)
    n_records: int = 0


def replay_journal(records: list[dict]) -> ReplayState:
    """Fold a journal record stream back into orchestrator state.

    Raises ``ValueError`` if the stream does not start with a header.
    Decision records referencing unknown trials raise — the journal is
    append-only, so that can only mean a hand-edited file."""
    if not records or records[0].get("type") != "header":
        raise ValueError(
            "tuning journal has no header record — not a tuning_state.jsonl"
        )
    header = records[0]
    sign = -1.0 if header.get("maximize") else 1.0
    trials: dict[int, ReplayedTrial] = {}
    proposer_events: list = []
    rng_state = None
    decided: list = []

    def trial(rec) -> ReplayedTrial:
        t = trials.get(rec["trial"])
        if t is None:
            raise ValueError(
                f"journal decision for unknown trial {rec['trial']} "
                "(record without a preceding ask)"
            )
        return t

    last_wave: list = []
    for rec in records[1:]:
        kind = rec.get("type")
        if kind == "wave":
            last_wave = [tuple(t) for t in rec["tasks"]]
        elif kind == "ask":
            params = np.asarray(rec["params"], float)
            trials[rec["trial"]] = ReplayedTrial(rec["trial"], params)
            proposer_events.append(("ask", params))
            rng_state = rec.get("rng_state", rng_state)
        elif kind == "report":
            trial(rec).reports[int(rec["rung"])] = rec
        elif kind == "promote":
            t = trial(rec)
            r = int(rec["rung"]) - 1
            decided.append((t.id, r, sign * t.reports[r]["metric"]))
            t.rung = int(rec["rung"])
        elif kind == "kill":
            t = trial(rec)
            t.status = "killed"
            decided.append((t.id, int(rec["rung"]), sign * rec["metric"]))
            proposer_events.append(("tell", t.params, sign * rec["metric"]))
        elif kind == "tell":
            t = trial(rec)
            t.status = "completed"
            t.final_metric = float(rec["metric"])
            decided.append((t.id, t.rung, sign * rec["metric"]))
            proposer_events.append(("tell", t.params, sign * rec["metric"]))
        elif kind == "fail":
            t = trial(rec)
            t.status = "failed"
            proposer_events.append(("resolve", t.params))
        # informational records ("retry", "resumed") carry no state

    # Reports whose decision record was lost with the crash: the trial is
    # still "running" and the report sits at its CURRENT rung.
    undecided = [
        t.reports[t.rung]
        for t in sorted(trials.values(), key=lambda t: t.id)
        if t.status == "running" and t.rung in t.reports
    ]
    return ReplayState(
        header=header,
        trials=trials,
        proposer_events=proposer_events,
        rng_state=rng_state,
        decided_reports=decided,
        undecided=undecided,
        last_wave=last_wave,
        n_records=len(records),
    )
