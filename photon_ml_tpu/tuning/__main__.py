"""Tuning CLI: selfcheck + parallel resumable search over both drivers.

Selfcheck (CPU-backend, CI-greppable)::

    python -m photon_ml_tpu.tuning --selfcheck

runs a parallel (4-worker) ASHA+GP search on a synthetic GAME workload,
KILLS it mid-flight at a journal record boundary, resumes from
``tuning_state.jsonl``, and asserts the resumed search's trial history
and journal decision sequence are identical to an uninterrupted run's;
a second deterministic search exercises the executor's crash vocabulary
(one transient failure retried in place, one fatal trial that fails
without sinking the sweep, ASHA pruning) and the telemetry snapshot is
checked for per-trial spans and the started/pruned/failed counters.

Search a GLM λ (LIBSVM data)::

    python -m photon_ml_tpu.tuning --driver glm \
        --train-data a1a --validate-data a1a.t --task logistic \
        --reg-type l2 --trials 16 --workers 4 --asha \
        --output-dir /tmp/tune_out

Search per-coordinate GAME regularization weights (Avro + config JSON,
the same config the training driver takes)::

    python -m photon_ml_tpu.tuning --driver game \
        --train-data train.avro --validate-data val.avro \
        --config config.json --trials 24 --workers 4 \
        --output-dir /tmp/tune_game

A killed search continues with ``--resume`` (refused if the search
space or configuration changed).  Results land in
``tuning_result.json``; the journal, per-trial coefficient files,
events.jsonl / metrics.json all live in the output dir.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading

import numpy as np


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.tuning",
        description="parallel, resumable hyperparameter search",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument("--driver", choices=["glm", "game"])
    p.add_argument("--train-data", help="LIBSVM (glm) or GAME Avro (game)")
    p.add_argument("--validate-data", help="held-out data (required)")
    p.add_argument("--config", help="game: coordinate config JSON")
    p.add_argument("--task", default="logistic", help="glm: task type")
    p.add_argument("--reg-type", default="l2", help="glm: regularization")
    p.add_argument("--optimizer", default="lbfgs", help="glm")
    p.add_argument("--solver", help="glm: registered solver name "
                   "(lbfgs|owlqn|tron|admm|block_cd); unset keeps the "
                   "historical routing bitwise — docs/solvers.md")
    p.add_argument("--max-iters", type=int, default=100, help="glm: full-"
                   "resource iteration budget (non-ASHA trials)")
    p.add_argument("--n-features", type=int, help="glm: fixed width")
    p.add_argument("--output-dir", help="journal + results + telemetry")
    p.add_argument("--trials", type=int, default=16)
    p.add_argument("--workers", type=int, default=4)
    p.add_argument("--proposer", choices=["gp", "random"], default="gp")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--range", default="1e-3,1e3",
        help="lo,hi regularization-weight bounds (log-scaled)",
    )
    p.add_argument("--asha", action="store_true",
                   help="successive halving on intermediate rung metrics")
    p.add_argument("--min-resource", type=int, default=None,
                   help="ASHA rung-0 resource (glm: optimizer iterations, "
                   "default 10; game: CD iterations, default 1)")
    p.add_argument("--reduction-factor", type=int, default=3)
    p.add_argument("--num-rungs", type=int, default=3)
    p.add_argument("--resume", action="store_true",
                   help="replay tuning_state.jsonl and continue the search")
    p.add_argument("--max-retries", type=int, default=2,
                   help="bounded in-place retries of TRANSIENT trial "
                   "failures (watchdog classification)")
    p.add_argument("--no-warm-start", action="store_true")
    p.add_argument("--warm-start-dir",
                   help="published model (GLM .avro or GAME dir) whose "
                   "fixed-effect coefficients seed trials before any "
                   "completed trial exists — chain a search onto the "
                   "freshest published model (docs/freshness.md)")
    p.add_argument("--no-fsync", action="store_true",
                   help="skip the per-record journal fsync (faster, "
                   "crash-safety reduced to flush)")
    p.add_argument("--telemetry", choices=["on", "off"], default="on")
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="expose the live ops plane on this port (/metrics "
        "Prometheus exposition, /snapshot JSON); 0 = ephemeral; omit "
        "to disable",
    )
    p.add_argument(
        "--metrics-interval-s", type=float, default=1.0,
        help="metrics_ts.jsonl sampling interval (0 disables)",
    )
    return p


# ---------------------------------------------------------------------------
# Synthetic GAME workload (selfcheck + tests)
# ---------------------------------------------------------------------------

def synthetic_game_problem(
    seed: int = 0,
    n_users: int = 10,
    rows_per_user: tuple = (6, 18),
    d_global: int = 4,
    d_user: int = 2,
):
    """Mixed-effects logistic data split train/validation: y ~
    sigmoid(x_g·w_g + x_u·w_user[u]).  Returns (train, validation) where
    train = (shards, ids, response) and validation additionally carries
    (weight=None, offset=None) — the tuple make_fit_once takes."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    wg = rng.normal(size=d_global)
    w_users = {
        f"user_{u}": 2.0 * rng.normal(size=d_user) for u in range(n_users)
    }

    def draw(frac: float):
        rows, user_ids = [], []
        for u in range(n_users):
            k = max(2, int(rng.integers(*rows_per_user) * frac))
            rows.append(k)
            user_ids.extend([f"user_{u}"] * k)
        n = sum(rows)
        Xg = rng.normal(size=(n, d_global)).astype(np.float32)
        Xu = rng.normal(size=(n, d_user)).astype(np.float32)
        margins = Xg @ wg + np.array(
            [Xu[i] @ w_users[user_ids[i]] for i in range(n)]
        )
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(
            np.float32
        )
        shards = {
            "global": sp.csr_matrix(Xg), "per_user": sp.csr_matrix(Xu)
        }
        return shards, {"userId": np.array(user_ids)}, y

    t_shards, t_ids, t_y = draw(1.0)
    v_shards, v_ids, v_y = draw(0.6)
    return (t_shards, t_ids, t_y), (v_shards, v_ids, v_y, None, None)


def synthetic_game_fit_once(seed: int = 0):
    """A ready-to-search GAME trial function over the synthetic problem."""
    from photon_ml_tpu.drivers.game_training_driver import make_fit_once
    from photon_ml_tpu.game.estimator import (
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.optim.problem import (
        GlmOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.optim.regularization import RegularizationContext

    (shards, ids, y), validation = synthetic_game_problem(seed)
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=25, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )
    configs = {
        "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0),
        "per_user": RandomEffectCoordinateConfig(
            "per_user", "userId", opt, reg_weight=1.0
        ),
    }
    return make_fit_once(
        "logistic", configs, shards, ids, y, validation
    )


# ---------------------------------------------------------------------------
# Selfcheck
# ---------------------------------------------------------------------------

def _journal_decisions(journal) -> list[dict]:
    """The journal's state-bearing records with run-local noise (wall
    clocks, resume markers) stripped — the replay-parity comparison key."""
    from photon_ml_tpu.tuning.state import STATE_RECORD_TYPES

    out = []
    for rec in journal.read():
        if rec.get("type") not in STATE_RECORD_TYPES:
            continue
        rec = dict(rec)
        rec.pop("wall", None)
        rec.pop("wall_epoch", None)
        out.append(rec)
    return out


def run_selfcheck(out_dir: str) -> list[str]:
    """Returns failure strings (empty = pass)."""
    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.tuning.executor import (
        TuningConfig,
        TuningOrchestrator,
    )
    from photon_ml_tpu.tuning.scheduler import (
        AshaConfig,
        GPProposer,
        GridProposer,
        SearchSpace,
    )
    from photon_ml_tpu.tuning.state import SearchAborted, TuningJournal
    from photon_ml_tpu.utils.watchdog import RetryPolicy

    failures: list[str] = []
    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="tuning-selfcheck"
    ) as tel:
        with tel.span("selfcheck", subsystem="tuning"):
            fit_once = synthetic_game_fit_once(seed=11)
            space = SearchSpace.create(
                [(1e-2, 1e2)] * 2, log_scale=True,
                names=["fixed", "per_user"],
            )
            cfg = TuningConfig(
                max_trials=6,
                workers=4,
                maximize=fit_once.larger_is_better,
                asha=AshaConfig(
                    min_resource=1, reduction_factor=2, num_rungs=2
                ),
                retry=RetryPolicy(max_retries=1),
                sleep=lambda s: None,
            )

            def search(subdir, abort_after=None, resume=False):
                journal = TuningJournal(
                    os.path.join(out_dir, subdir), abort_after=abort_after
                )
                orch = TuningOrchestrator(
                    space, fit_once, GPProposer(space, seed=7), cfg, journal
                )
                try:
                    return orch.run(resume=resume), journal
                finally:
                    journal.close()

            # Uninterrupted reference run.
            result_a, journal_a = search("search_a")
            n_records = len(journal_a.read())

            # Same search, killed mid-flight at a record boundary…
            killed = False
            try:
                search("search_b", abort_after=max(2, n_records // 2))
            except SearchAborted:
                killed = True
            if not killed:
                failures.append(
                    f"abort hook never fired ({n_records} records in the "
                    "uninterrupted journal)"
                )
            # …and resumed from the journal.
            result_b, journal_b = search("search_b", resume=True)

            if result_a.trials != result_b.trials:
                failures.append(
                    "resumed trial history differs from the uninterrupted "
                    f"run:\n  uninterrupted: {result_a.trials}\n  "
                    f"resumed: {result_b.trials}"
                )
            if (result_a.best_trial, result_a.best_metric) != (
                result_b.best_trial, result_b.best_metric
            ):
                failures.append(
                    f"best-trial mismatch: {result_a.best_trial}/"
                    f"{result_a.best_metric} vs {result_b.best_trial}/"
                    f"{result_b.best_metric}"
                )
            dec_a = _journal_decisions(journal_a)
            dec_b = _journal_decisions(journal_b)
            if dec_a != dec_b:
                first = next(
                    (i for i, (a, b) in enumerate(zip(dec_a, dec_b))
                     if a != b),
                    min(len(dec_a), len(dec_b)),
                )
                failures.append(
                    "journal replay mismatch at state record "
                    f"{first}: {dec_a[first:first + 1]} vs "
                    f"{dec_b[first:first + 1]}"
                )
            if result_a.pruned + result_a.completed + result_a.failed == 0:
                failures.append("search produced no terminal trials")

            # Crash vocabulary: deterministic grid with one transient
            # failure (retried in place) and one fatal trial.
            attempts: dict[float, int] = {}
            attempt_lock = threading.Lock()

            def crashy(params, resource=0, warm_start=None):
                x = float(np.asarray(params).ravel()[0])
                with attempt_lock:
                    n = attempts[x] = attempts.get(x, 0) + 1
                if abs(x - 0.95) < 1e-9:
                    raise ValueError("synthetic fatal trial failure")
                if abs(x - 0.7) < 1e-9 and n == 1:
                    raise RuntimeError(
                        "UNAVAILABLE: synthetic transport drop"
                    )
                return -((x - 0.3) ** 2)

            grid = [0.3, 0.9, 0.1, 0.7, 0.5, 0.95]
            c_space = SearchSpace.create([(0.0, 1.0)], names=["x"])
            c_journal = TuningJournal(os.path.join(out_dir, "search_c"))
            c_cfg = TuningConfig(
                max_trials=len(grid),
                workers=2,
                maximize=True,
                asha=AshaConfig(
                    min_resource=1, reduction_factor=2, num_rungs=2
                ),
                retry=RetryPolicy(max_retries=2),
                sleep=lambda s: None,
            )
            result_c = TuningOrchestrator(
                c_space, crashy,
                GridProposer(c_space, [[x] for x in grid]),
                c_cfg, c_journal,
            ).run()
            c_journal.close()
            if result_c.failed != 1:
                failures.append(
                    f"expected exactly 1 fatal trial, got {result_c.failed}"
                )
            if result_c.pruned < 1:
                failures.append(
                    f"expected ASHA pruning, got {result_c.pruned} pruned"
                )
            if attempts.get(0.7) != 2:
                failures.append(
                    "transient failure was not retried exactly once "
                    f"(attempts: {attempts.get(0.7)})"
                )
            best_x = (
                None if result_c.best_params is None
                else result_c.best_params[0]
            )
            if best_x != 0.3:
                failures.append(
                    f"crash-vocabulary search selected {best_x}, "
                    "expected 0.3"
                )
        snap = tel.snapshot()

    # Telemetry contract: per-trial spans in events.jsonl, trial
    # counters + best-metric gauge in metrics.json.
    counters = snap["counters"]
    for name in (
        "tuning_trials_started", "tuning_trials_pruned",
        "tuning_trials_failed", "tuning_trial_retries",
    ):
        if not counters.get(name):
            failures.append(f"metrics counter {name} is missing or zero")
    if snap["gauges"].get("tuning_best_metric") is None:
        failures.append("tuning_best_metric gauge never set")
    events_path = os.path.join(out_dir, "events.jsonl")
    trial_spans = 0
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("type") == "span" and rec.get("name") == \
                        "tuning.trial":
                    trial_spans += 1
    if not trial_spans:
        failures.append("no tuning.trial spans in events.jsonl")
    metrics_path = os.path.join(out_dir, "metrics.json")
    if not os.path.exists(metrics_path):
        failures.append(f"missing {metrics_path}")
    else:
        with open(metrics_path) as f:
            on_disk = json.load(f)
        if "tuning_trials_pruned" not in on_disk.get("counters", {}):
            failures.append(
                "metrics.json lacks the tuning_trials_pruned counter"
            )
    if not failures:
        print(
            f"tuning selfcheck: {result_a.n_trials}-trial parallel "
            f"ASHA+GP search killed at record "
            f"{max(2, n_records // 2)}/{n_records} resumed bit-identically "
            f"({result_a.completed} completed, {result_a.pruned} pruned); "
            f"crash search: {result_c.failed} fatal / "
            f"{attempts.get(0.7, 0) - 1} transient retry / "
            f"{result_c.pruned} pruned; {trial_spans} tuning.trial spans"
        )
    return failures


# ---------------------------------------------------------------------------
# Driver searches
# ---------------------------------------------------------------------------

def _build_search(args):
    """(fit_once, space) for the selected driver."""
    if not args.train_data or not args.validate_data:
        raise SystemExit("--driver requires --train-data and --validate-data")
    lo, hi = (float(s) for s in args.range.split(","))
    if args.driver == "glm":
        from photon_ml_tpu.data import libsvm
        from photon_ml_tpu.drivers.glm_driver import make_fit_once

        X_train, y_train = libsvm.read_libsvm(
            args.train_data, n_features=args.n_features, add_intercept=True
        )
        X_val, y_val = libsvm.read_libsvm(
            args.validate_data,
            n_features=X_train.shape[1] - 1,
            add_intercept=True,
            drop_out_of_range=True,
        )
        fit_once = make_fit_once(
            X_train, y_train, X_val, y_val,
            task=args.task, reg_type=args.reg_type,
            optimizer=args.optimizer, max_iters=args.max_iters,
            solver=args.solver,
        )
        from photon_ml_tpu.tuning.scheduler import SearchSpace

        return fit_once, SearchSpace.create(
            [(lo, hi)], log_scale=True, names=["lambda"]
        )
    # game
    if not args.config:
        raise SystemExit("--driver game requires --config")
    from photon_ml_tpu.data.game_reader import read_game_avro
    from photon_ml_tpu.drivers.game_training_driver import (
        make_fit_once,
        parse_coordinate_config,
    )
    from photon_ml_tpu.tuning.scheduler import SearchSpace

    with open(args.config) as f:
        config = json.load(f)
    configs = dict(
        parse_coordinate_config(spec) for spec in config["coordinates"]
    )
    shards, ids, response, weight, offset, _, index_maps = read_game_avro(
        args.train_data
    )
    v = read_game_avro(args.validate_data, index_maps=index_maps)
    fit_once = make_fit_once(
        config.get("task", "logistic"), configs, shards, ids, response,
        (v[0], v[1], v[2], v[3], v[4]), weight=weight, offset=offset,
    )
    return fit_once, SearchSpace.create(
        [(lo, hi)] * len(configs), log_scale=True, names=list(configs)
    )


def run_search(args) -> dict:
    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.tuning.executor import (
        TuningConfig,
        TuningOrchestrator,
    )
    from photon_ml_tpu.tuning.scheduler import AshaConfig, make_proposer
    from photon_ml_tpu.tuning.state import TuningJournal
    from photon_ml_tpu.utils.logging import PhotonLogger
    from photon_ml_tpu.utils.watchdog import RetryPolicy

    if not args.output_dir:
        raise SystemExit("--output-dir is required")
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        tel = telemetry_mod.Telemetry(
            output_dir=args.output_dir,
            logger=logger,
            enabled=args.telemetry != "off",
        )
        with tel, tel.span(
            "run", driver="tuning", mode=args.driver
        ), telemetry_mod.mount_ops_plane(
            tel, port=args.metrics_port,
            interval_s=args.metrics_interval_s, logger=logger,
        ):
            fit_once, space = _build_search(args)
            asha = None
            if args.asha:
                asha = AshaConfig(
                    min_resource=(
                        args.min_resource
                        if args.min_resource is not None
                        else (10 if args.driver == "glm" else 1)
                    ),
                    reduction_factor=args.reduction_factor,
                    num_rungs=args.num_rungs,
                )
            cfg = TuningConfig(
                max_trials=args.trials,
                workers=args.workers,
                maximize=fit_once.larger_is_better,
                resource=0 if args.driver == "game" else args.max_iters,
                asha=asha,
                retry=RetryPolicy(max_retries=args.max_retries),
                warm_start=not args.no_warm_start,
                warm_start_dir=args.warm_start_dir,
            )
            journal = TuningJournal(
                args.output_dir, fsync=not args.no_fsync
            )
            orch = TuningOrchestrator(
                space, fit_once, make_proposer(
                    args.proposer, space, seed=args.seed
                ),
                cfg, journal, logger=logger,
            )
            result = orch.run(resume=args.resume)
            journal.close()
            out = result.as_dict()
            out["space"] = space.to_config()
            out["primary_metric"] = fit_once.suite.primary
            with open(
                os.path.join(args.output_dir, "tuning_result.json"), "w"
            ) as f:
                json.dump(out, f, indent=2)
            logger.info(
                "search done: %d trials (%d completed, %d pruned, "
                "%d failed), best %s=%s at %s",
                result.n_trials, result.completed, result.pruned,
                result.failed, fit_once.suite.primary, result.best_metric,
                result.best_params,
            )
            return out


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.selfcheck:
        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            failures = run_selfcheck(args.output_dir)
        else:
            with tempfile.TemporaryDirectory(
                prefix="photon_tuning_selfcheck_"
            ) as td:
                failures = run_selfcheck(td)
        if failures:
            print("tuning selfcheck FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
            return 1
        print("tuning selfcheck PASSED")
        return 0
    if not args.driver:
        raise SystemExit("one of --selfcheck / --driver is required")
    from photon_ml_tpu.tuning.state import ResumeMismatch

    try:
        out = run_search(args)
    except ResumeMismatch as exc:
        # A refused resume is an operator decision point, not a crash.
        raise SystemExit(f"tuning: {exc}") from None
    print(json.dumps({
        "best_params": out["best_params"],
        "best_metric": out["best_metric"],
        "n_trials": out["n_trials"],
        "completed": out["completed"],
        "pruned": out["pruned"],
        "failed": out["failed"],
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
