"""Trial orchestration: bounded parallel execution, warm starts, resume.

``TuningOrchestrator`` owns the search loop that hyperparameter/
search.py's ``find`` used to run inline: it asks a proposer
(tuning/scheduler.py) for points, runs trials CONCURRENTLY on a bounded
thread pool, feeds intermediate rung metrics to ASHA, journals every
decision (tuning/state.py), and survives kills via ``resume=True``.

Determinism contract (what makes "resume == uninterrupted" testable and
the selfcheck's bit-parity assertion honest): the loop is
batch-synchronous.  Each iteration forms a WAVE — the first ``workers``
runnable rung-tasks in trial-id order — runs it fully in parallel, then
processes the results in trial-id order.  Thread completion ORDER
therefore never reaches the search state: proposals, ASHA decisions,
warm-start choices, and the journal's state-bearing records are a pure
function of (space, seed, config, trial_fn) alone.  A resumed search
replays the journal to the crash point and continues through the exact
decision sequence the uninterrupted run would have taken — Snap ML's
hierarchical-parallelism observation (arXiv:1803.06333) that many
independent GLM fits are a throughput problem, without giving up
replayability.

Trials are plain callables::

    trial_fn(params, resource, warm_start) -> TrialReport | (metric, metrics, coefficients) | metric

``resource`` is the rung budget (optimizer iterations / CD iterations);
``warm_start`` is a coefficient vector or None.  Warm starts chain two
ways, after "Distributed Coordinate Descent for GLMs with
Regularization" (arXiv:1611.02101)'s λ-path warm starts: a promoted
trial continues from its OWN previous rung's coefficients, and a fresh
trial starts from the nearest COMPLETED trial's coefficients in the
normalized search space (ties to the lower trial id).

Crashes go through the watchdog vocabulary (utils/watchdog.py):
transient verdicts retry in place with bounded backoff; fatal verdicts
mark the trial failed and the search continues — one bad trial never
sinks the sweep.
"""

from __future__ import annotations

import dataclasses
import os
import time
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Callable, Optional

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.tuning.scheduler import (
    AshaConfig,
    AshaScheduler,
    Proposer,
    SearchSpace,
)
from photon_ml_tpu.tuning.state import (
    JOURNAL_VERSION,
    ReplayState,
    ResumeMismatch,
    TrialStore,
    TuningJournal,
    replay_journal,
)
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.utils.watchdog import RetryPolicy


@dataclasses.dataclass
class TrialReport:
    """What one rung execution returns.  ``metric`` is in the CALLER's
    convention (``TuningConfig.maximize`` tells the orchestrator which
    way is up); ``metrics`` is the full evaluation-suite dict journaled
    with the rung report; ``coefficients`` feed warm starts and the
    trial store (None = this trial type has no warm-startable state)."""

    metric: float
    metrics: Optional[dict] = None
    coefficients: Optional[np.ndarray] = None


def _as_report(result) -> TrialReport:
    if isinstance(result, TrialReport):
        return result
    if isinstance(result, tuple):
        return TrialReport(*result)
    return TrialReport(float(result))


@dataclasses.dataclass
class Trial:
    id: int
    params: np.ndarray
    status: str = "running"  # running | completed | killed | failed
    rung: int = 0
    rung_metrics: dict = dataclasses.field(default_factory=dict)
    final_metric: Optional[float] = None
    coefficients: Optional[np.ndarray] = None  # latest rung's, host-side
    retries: int = 0
    error: Optional[str] = None

    def summary(self) -> dict:
        return {
            "id": self.id,
            "params": [float(v) for v in self.params],
            "status": self.status,
            "rung_metrics": {
                str(r): m for r, m in sorted(self.rung_metrics.items())
            },
            "final_metric": self.final_metric,
            "retries": self.retries,
            "error": self.error,
        }


@dataclasses.dataclass
class TuningConfig:
    """How the orchestrator runs the search.

    ``resource`` is what a non-ASHA trial receives as its rung budget
    (0 = trial_fn's own default); with ``asha`` set the rung geometry
    decides.  ``sleep`` is injectable so tests assert on retry behavior
    without timing real backoffs."""

    max_trials: int
    workers: int = 4
    maximize: bool = False
    resource: int = 0
    asha: Optional[AshaConfig] = None
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    warm_start: bool = True
    #: published model to SEED warm starts from (a saved GLM ``.avro``
    #: or a GAME model directory, whose fixed-effect means are used)
    #: when no completed trial is closer — chains a tuning run onto the
    #: freshest published model instead of cold-starting trial 1.
    warm_start_dir: Optional[str] = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        if self.max_trials < 1:
            raise ValueError("max_trials must be >= 1")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")


@dataclasses.dataclass
class TuningResult:
    best_trial: Optional[int]
    best_params: Optional[list]
    best_metric: Optional[float]
    n_trials: int
    completed: int
    pruned: int
    failed: int
    trials: list  # per-trial summaries, id order

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class _Task:
    trial: Trial
    rung: int
    # filled by the worker:
    report: Optional[TrialReport] = None
    exception: Optional[BaseException] = None
    transient: Optional[bool] = None
    wall: float = 0.0


class TuningOrchestrator:
    """One search run (fresh or resumed) over one trial function."""

    def __init__(
        self,
        space: SearchSpace,
        trial_fn: Callable,
        proposer: Proposer,
        config: TuningConfig,
        journal: TuningJournal,
        logger=None,
    ):
        self.space = space
        self.trial_fn = trial_fn
        self.proposer = proposer
        self.config = config
        self.journal = journal
        self.store = TrialStore(journal.directory)
        self.logger = logger
        self.sign = -1.0 if config.maximize else 1.0
        self.asha = AshaScheduler(config.asha) if config.asha else None
        self.trials: dict[int, Trial] = {}
        #: trial_id → (normalized params, coefficients) of COMPLETED
        #: trials — the cross-trial warm-start cache.
        self._completed_coefs: dict[int, tuple] = {}
        self._best: Optional[tuple] = None  # (y, trial_id) minimize-space
        self._counts = {"completed": 0, "pruned": 0, "failed": 0}

    # -- header / resume ----------------------------------------------------
    def _header(self) -> dict:
        return {
            "type": "header",
            "version": JOURNAL_VERSION,
            "fingerprint": self.space.fingerprint(),
            "space": self.space.to_config(),
            "maximize": self.config.maximize,
            "proposer": self.proposer.kind,
            "asha": (
                self.config.asha.to_config() if self.config.asha else None
            ),
            "resource": self.config.resource,
            "max_trials": self.config.max_trials,
            "workers": self.config.workers,
            "warm_start_dir": self.config.warm_start_dir,
            "wall_epoch": time.time(),
        }

    def _verify_header(self, header: dict) -> None:
        if header.get("fingerprint") != self.space.fingerprint():
            raise ResumeMismatch(
                "refusing to resume: the journal was written for a "
                f"different search space (journal fingerprint "
                f"{header.get('fingerprint')!r}, this run "
                f"{self.space.fingerprint()!r}) — clear "
                f"{self.journal.path} or rerun with the original space"
            )
        ours = self._header()
        for key in ("maximize", "proposer", "asha", "resource",
                    "max_trials", "workers", "warm_start_dir"):
            if header.get(key) != ours[key]:
                raise ResumeMismatch(
                    f"refusing to resume: journal {key}={header.get(key)!r} "
                    f"!= this run's {ours[key]!r} — the continuation would "
                    "not reproduce the uninterrupted search"
                )

    def _restore(self, replayed: ReplayState) -> list[_Task]:
        """Rebuild orchestrator + proposer + scheduler state from a
        replayed journal; returns the re-runnable tasks (the wave that
        was in flight when the run died)."""
        self._verify_header(replayed.header)
        for kind, *payload in replayed.proposer_events:
            if kind == "ask":
                self.proposer.restore_ask(payload[0])
            elif kind == "tell":
                self.proposer.tell(*payload)
            else:
                self.proposer.resolve(payload[0])
        if replayed.rng_state is not None:
            self.proposer.set_rng_state(replayed.rng_state)
        if self.asha is not None:
            for trial_id, rung, y in replayed.decided_reports:
                self.asha.record(trial_id, rung, y)
        for rt in sorted(replayed.trials.values(), key=lambda t: t.id):
            t = Trial(
                rt.id, rt.params, status=rt.status, rung=rt.rung,
                final_metric=rt.final_metric,
            )
            t.rung_metrics = {
                int(r): rec["metric"] for r, rec in rt.reports.items()
            }
            stored = self.store.load(t.id)
            if stored is not None:
                t.coefficients = stored[1]
            self.trials[t.id] = t
            # Result counts cover the WHOLE search, not just post-resume
            # activity (telemetry counters, by contrast, are per-process).
            if t.status == "killed":
                self._counts["pruned"] += 1
            elif t.status == "failed":
                self._counts["failed"] += 1
            elif t.status == "completed":
                self._counts["completed"] += 1
                y = self.sign * t.final_metric
                self._note_best(y, t.id)
                if t.coefficients is not None:
                    self._completed_coefs[t.id] = (
                        self.space.normalize(t.params)[0], t.coefficients
                    )
        # Re-derive the decisions the crash swallowed (report journaled,
        # promote/kill/tell not) — same order, same rule, journaled now.
        ready: list[_Task] = []
        for rec in replayed.undecided:
            trial = self.trials[rec["trial"]]
            report = TrialReport(
                rec["metric"], rec.get("metrics"), trial.coefficients
            )
            task = _Task(trial, int(rec["rung"]), report=report)
            self._apply_decision(task, journal_report=False, ready=ready)
        # Unfinished trials with no report at their current rung were in
        # flight (or queued).  The crash's IN-FLIGHT wave (the last
        # journaled wave record's unreported tasks) must re-run as one
        # wave of its own, in its original membership — merging it with
        # promotions the replay just re-derived would compress the
        # schedule relative to the uninterrupted run and change every
        # later proposal.  Everything else re-enters the ready queue.
        # (Trials the re-derived decisions above promoted are queued.)
        queued = {task.trial.id for task in ready}
        inflight_keys = {tuple(t) for t in replayed.last_wave}
        inflight: list[_Task] = []
        for t in sorted(self.trials.values(), key=lambda t: t.id):
            if (
                t.status == "running"
                and t.rung not in t.rung_metrics
                and t.id not in queued
            ):
                task = _Task(t, t.rung)
                if (t.id, t.rung) in inflight_keys:
                    inflight.append(task)
                else:
                    ready.append(task)
        self.journal.append(
            {"type": "resumed", "records": replayed.n_records}
        )
        if self.logger is not None:
            self.logger.info(
                "resumed tuning search: %d journal records, %d trials "
                "(%d completed, %d pruned, %d failed), %d in-flight + %d "
                "queued task(s)",
                replayed.n_records, len(self.trials),
                self._counts["completed"], self._counts["pruned"],
                self._counts["failed"], len(inflight), len(ready),
            )
        return ready, inflight

    # -- main loop ----------------------------------------------------------
    def run(self, resume: bool = False) -> TuningResult:
        tel = telemetry_mod.current()
        # Worker threads attach this context so every tuning.trial span
        # parents to the search's own span instead of rooting loose.
        self._trace_ctx = tel.current_context()
        ready: list[_Task] = []
        inflight: list[_Task] = []
        if resume:
            records = self.journal.read()
            if not records:
                raise ResumeMismatch(
                    f"--resume: no journal at {self.journal.path}"
                )
            ready, inflight = self._restore(replay_journal(records))
        else:
            if self.journal.exists():
                # A stale journal from a previous search must not survive
                # into a later --resume (same policy as the drivers'
                # checkpointers).
                self.journal.clear()
            # Stale trial_<id>.npz files likewise: a later resume would
            # warm-start an unreported trial from ANOTHER search's
            # coefficients.
            self.store.clear()
            self.journal.append(self._header())

        with ThreadPoolExecutor(
            max_workers=self.config.workers,
            thread_name_prefix="tuning-trial",
        ) as pool:
            if inflight:
                # Finish the crash's wave first, under its ORIGINAL
                # (journaled) membership — no new wave record.
                self._execute_wave(pool, inflight, ready, tel)
            while True:
                while (
                    len(ready) < self.config.workers
                    and len(self.trials) < self.config.max_trials
                    and not self.proposer.exhausted()
                ):
                    ready.append(self._ask(tel))
                if not ready:
                    break
                ready.sort(key=lambda task: task.trial.id)
                wave, ready = (
                    ready[: self.config.workers],
                    ready[self.config.workers :],
                )
                self.journal.append({
                    "type": "wave",
                    "tasks": [
                        [task.trial.id, task.rung] for task in wave
                    ],
                })
                self._execute_wave(pool, wave, ready, tel)
        return self._result()

    def _execute_wave(
        self, pool, wave: list, ready: list, tel
    ) -> None:
        futures = [pool.submit(self._run_task, task) for task in wave]
        wait(futures)
        for f in futures:
            f.result()  # re-raise worker infrastructure errors
        for task in sorted(wave, key=lambda task: task.trial.id):
            if task.exception is not None:
                self._apply_failure(task, tel)
            else:
                self._apply_decision(task, ready=ready, tel=tel)

    # -- ask ----------------------------------------------------------------
    def _ask(self, tel) -> _Task:
        params = self.proposer.ask()
        trial = Trial(len(self.trials), np.asarray(params, float))
        self.trials[trial.id] = trial
        self.journal.append({
            "type": "ask",
            "trial": trial.id,
            "params": trial.params,
            # Reproducibility under resume: the generator state AFTER
            # this proposal — restoring it makes the resumed search
            # propose the same future points.
            "rng_state": self.proposer.rng_state,
        })
        tel.counter("tuning_trials_started").inc()
        return _Task(trial, 0)

    # -- worker side --------------------------------------------------------
    def _rung_resource(self, rung: int) -> int:
        if self.asha is not None:
            return self.asha.config.resource(rung)
        return self.config.resource

    def _warm_start(self, task: _Task) -> Optional[np.ndarray]:
        if task.trial.coefficients is not None:
            return task.trial.coefficients  # own previous rung
        if not self.config.warm_start:
            return None
        if self._completed_coefs:
            z = self.space.normalize(task.trial.params)[0]
            best = min(
                self._completed_coefs.items(),
                key=lambda kv: (float(np.sum((kv[1][0] - z) ** 2)), kv[0]),
            )
            return best[1][1]
        return self._published_warm_start()

    def _published_warm_start(self) -> Optional[np.ndarray]:
        """Seed coefficients from ``config.warm_start_dir`` — the
        freshest PUBLISHED model — used only before any trial of this
        run has completed (after that, same-search neighbors are the
        better prior).  Loaded lazily once; a bad explicit path fails
        the run loudly rather than silently cold-starting."""
        if self.config.warm_start_dir is None:
            return None
        if not hasattr(self, "_published_coefs"):
            path = self.config.warm_start_dir
            if os.path.isdir(path):
                from photon_ml_tpu.io.game_store import load_game_model

                model, _ = load_game_model(path)
                fixed = [
                    c.model for c in model.models.values()
                    if hasattr(c, "model")
                ]
                if not fixed:
                    raise ValueError(
                        f"warm_start_dir {path!r} is a GAME model with "
                        "no fixed-effect coordinate — nothing to seed "
                        "trial coefficients from"
                    )
                means = fixed[0].coefficients.means
            else:
                from photon_ml_tpu.io.model_store import load_glm_model

                means = load_glm_model(path)[0].coefficients.means
            self._published_coefs = np.asarray(means, np.float32)
        return self._published_coefs

    def _run_task(self, task: _Task) -> None:
        """Worker thread: run one rung, retrying transient failures in
        place.  Results land ON the task; classification and the journal's
        state-bearing records happen in the (deterministic) processing
        phase."""
        tel = telemetry_mod.current()
        policy = self.config.retry
        resource = self._rung_resource(task.rung)
        warm = self._warm_start(task)
        attempt = 0
        t0 = time.perf_counter()
        with tel.attach(getattr(self, "_trace_ctx", None)), tel.span(
            "tuning.trial",
            trial=task.trial.id,
            rung=task.rung,
            resource=resource,
            params=[float(v) for v in task.trial.params],
            warm_started=warm is not None,
        ) as span:
            while True:
                try:
                    chaos_mod.maybe_fail(
                        "tuning.trial", trial=task.trial.id, rung=task.rung,
                    )
                    task.report = _as_report(
                        self.trial_fn(task.trial.params, resource, warm)
                    )
                    span.set(metric=task.report.metric, attempts=attempt + 1)
                    break
                except Exception as exc:  # noqa: BLE001 — classified below
                    verdict = policy.classify(exc)
                    if verdict.transient and attempt < policy.max_retries:
                        attempt += 1
                        task.trial.retries += 1
                        delay = policy.backoff(attempt - 1)
                        # Informational record (worker-side, so arrival
                        # order is timing-dependent); replay ignores it.
                        self.journal.append({
                            "type": "retry",
                            "trial": task.trial.id,
                            "rung": task.rung,
                            "attempt": attempt,
                            "error": f"{type(exc).__name__}: {exc}"[:200],
                            "matched": verdict.matched,
                            "backoff_seconds": delay,
                        })
                        tel.counter("tuning_trial_retries").inc()
                        tel.event(
                            "tuning.retry",
                            trial=task.trial.id,
                            attempt=attempt,
                            matched=verdict.matched,
                        )
                        if self.logger is not None:
                            self.logger.warning(
                                "trial %d rung %d: transient failure "
                                "(attempt %d/%d): %s",
                                task.trial.id, task.rung, attempt,
                                policy.max_retries, exc,
                            )
                        self.config.sleep(delay)
                        continue
                    task.exception = exc
                    task.transient = verdict.transient
                    span.set(error_class=(
                        "transient_exhausted" if verdict.transient
                        else "fatal"
                    ))
                    break
        task.wall = time.perf_counter() - t0
        tel.histogram("tuning_trial_seconds").observe(task.wall)

    # -- processing phase (deterministic, main thread) -----------------------
    def _note_best(self, y: float, trial_id: int) -> None:
        if self._best is None or (y, trial_id) < self._best:
            self._best = (y, trial_id)

    def _apply_failure(self, task: _Task, tel) -> None:
        trial, exc = task.trial, task.exception
        trial.status = "failed"
        trial.error = f"{type(exc).__name__}: {exc}"[:300]
        self.journal.append({
            "type": "fail",
            "trial": trial.id,
            "rung": task.rung,
            "error": trial.error,
            "transient": bool(task.transient),
            "retries": trial.retries,
        })
        self.proposer.resolve(trial.params)
        self._counts["failed"] += 1
        tel.counter("tuning_trials_failed").inc()
        if self.logger is not None:
            self.logger.warning(
                "trial %d FAILED (%s, search continues): %s",
                trial.id,
                "transient budget exhausted" if task.transient else "fatal",
                trial.error,
            )

    def _apply_decision(
        self,
        task: _Task,
        ready: list,
        journal_report: bool = True,
        tel=None,
    ) -> None:
        """Record a successful rung report and apply the ASHA decision.
        ``journal_report=False`` is the resume path re-deriving a
        decision for an already-journaled report."""
        tel = tel or telemetry_mod.current()
        trial, report = task.trial, task.report
        metric = float(report.metric)
        y = self.sign * metric
        trial.rung_metrics[task.rung] = metric
        if report.coefficients is not None:
            trial.coefficients = np.asarray(report.coefficients)
            # Persist BEFORE the report record: any journaled rung has
            # its warm-start state on disk, so a resumed search
            # warm-starts exactly as the uninterrupted one.
            self.store.save(trial.id, trial.params, trial.coefficients)
        if journal_report:
            self.journal.append({
                "type": "report",
                "trial": trial.id,
                "rung": task.rung,
                "resource": self._rung_resource(task.rung),
                "metric": metric,
                "metrics": report.metrics,
                "wall": round(task.wall, 6),
            })
        decision = (
            self.asha.report(trial.id, task.rung, y)
            if self.asha is not None
            else "complete"
        )
        if decision == "promote":
            trial.rung = task.rung + 1
            self.journal.append({
                "type": "promote", "trial": trial.id, "rung": trial.rung,
            })
            tel.event("tuning.promote", trial=trial.id, rung=trial.rung)
            ready.append(_Task(trial, trial.rung))
        elif decision == "stop":
            trial.status = "killed"
            self.journal.append({
                "type": "kill",
                "trial": trial.id,
                "rung": task.rung,
                "metric": metric,
            })
            # The surrogate still learns from the pruned trial's last
            # rung metric — a bad region stays known-bad.
            self.proposer.tell(trial.params, y)
            self._counts["pruned"] += 1
            tel.counter("tuning_trials_pruned").inc()
        else:  # complete
            trial.status = "completed"
            trial.final_metric = metric
            self.journal.append({
                "type": "tell", "trial": trial.id, "metric": metric,
            })
            self.proposer.tell(trial.params, y)
            if trial.coefficients is not None:
                self._completed_coefs[trial.id] = (
                    self.space.normalize(trial.params)[0],
                    trial.coefficients,
                )
            self._counts["completed"] += 1
            tel.counter("tuning_trials_completed").inc()
            self._note_best(y, trial.id)
            if self._best is not None:
                tel.gauge("tuning_best_metric").set(
                    self.sign * self._best[0]
                )
        if self.logger is not None:
            self.logger.info(
                "trial %d rung %d: metric=%.6g -> %s",
                trial.id, task.rung, metric, decision,
            )

    # -- result -------------------------------------------------------------
    def _result(self) -> TuningResult:
        best_id = self._best[1] if self._best is not None else None
        best = self.trials.get(best_id) if best_id is not None else None
        return TuningResult(
            best_trial=best_id,
            best_params=(
                None if best is None
                else [float(v) for v in best.params]
            ),
            best_metric=None if best is None else best.final_metric,
            n_trials=len(self.trials),
            completed=self._counts["completed"],
            pruned=self._counts["pruned"],
            failed=self._counts["failed"],
            trials=[
                self.trials[i].summary() for i in sorted(self.trials)
            ],
        )
