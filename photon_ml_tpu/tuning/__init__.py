"""Tuning orchestration: parallel trials, ASHA, crash-safe resumable search.

The production layer over photon_ml_tpu/hyperparameter/search.py (the
reference's ``ml.hyperparameter`` package): ask/tell proposers with
constant-liar GP batching and an ASHA successive-halving scheduler
(tuning/scheduler.py), a bounded-concurrency trial executor with λ-path
warm starts and watchdog-classified crash handling
(tuning/executor.py), and an fsync'd append-only decision journal that
makes ``--resume`` replay a killed search bit-identically
(tuning/state.py).  ``python -m photon_ml_tpu.tuning`` is the CLI over
the GLM and GAME drivers; docs/tuning.md is the guide.
"""

from photon_ml_tpu.tuning.executor import (  # noqa: F401
    TrialReport,
    TuningConfig,
    TuningOrchestrator,
    TuningResult,
)
from photon_ml_tpu.tuning.scheduler import (  # noqa: F401
    AshaConfig,
    AshaScheduler,
    GPProposer,
    GridProposer,
    Proposer,
    RandomProposer,
    SearchSpace,
    make_proposer,
)
from photon_ml_tpu.tuning.state import (  # noqa: F401
    ResumeMismatch,
    SearchAborted,
    TrialStore,
    TuningJournal,
    replay_journal,
)
