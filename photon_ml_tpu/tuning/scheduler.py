"""Search scheduling: the ask/tell proposer interface and ASHA.

hyperparameter/search.py is a library FUNCTION — ``find(evaluate, n)``
owns the loop and evaluates synchronously, so it can neither run trials
concurrently nor survive a kill.  This module inverts that control:

- **Proposers** expose ``ask() → params`` / ``tell(params, y)`` so the
  orchestrator (tuning/executor.py) owns the loop, journals every
  decision, and keeps several asks IN FLIGHT at once.  The GP proposer
  supports batched asks via constant-liar imputation: pending
  (asked-but-unresolved) points enter the surrogate fit with the current
  best observed value as a stand-in, so the next ask's
  expected-improvement argmax is pushed away from points already being
  evaluated instead of proposing them again.
- **AshaScheduler** implements successive halving on intermediate rung
  metrics (ASHA, arXiv:1810.05934 applied at this repo's scale): rung r
  runs each trial at ``min_resource·η^r`` resource; on a rung report the
  trial is promoted iff it ranks in the top ``max(1, n//η)`` of every
  metric seen at that rung, else killed.  Decisions are made per report
  (no barrier across trials beyond the executor's wave), and the
  deterministic ``(metric, trial_id)`` ordering makes them replayable.

Everything here speaks MINIMIZATION internally (like
hyperparameter/search.py); the orchestrator applies the sign once at
its boundary.  All randomness flows through one ``numpy`` Generator per
proposer whose full bit-generator state is exposed for the journal
(``rng_state``/``set_rng_state``) — the reproducibility-under-resume
contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessModel,
    expected_improvement,
)


# ---------------------------------------------------------------------------
# Search space
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """A bounded box of named dimensions, optionally log-scaled — the same
    geometry hyperparameter/search.py searches, made an explicit value so
    it can be fingerprinted into the journal header."""

    bounds: tuple  # ((lo, hi), ...)
    log_scale: tuple  # (bool, ...) per dimension
    names: tuple  # ("fixed", "per_user", ...)

    @classmethod
    def create(
        cls,
        bounds: Sequence[tuple],
        log_scale=False,
        names: Optional[Sequence[str]] = None,
    ) -> "SearchSpace":
        bounds = tuple((float(lo), float(hi)) for lo, hi in bounds)
        d = len(bounds)
        for j, (lo, hi) in enumerate(bounds):
            if not lo < hi:
                raise ValueError(f"dimension {j}: empty bounds [{lo}, {hi}]")
        ls = (
            (bool(log_scale),) * d
            if isinstance(log_scale, bool)
            else tuple(bool(b) for b in log_scale)
        )
        if len(ls) != d:
            raise ValueError("log_scale length != bounds length")
        for j, ((lo, _), lg) in enumerate(zip(bounds, ls)):
            if lg and lo <= 0.0:
                raise ValueError(
                    f"dimension {j}: log scale requires a positive lower "
                    f"bound, got {lo}"
                )
        nm = (
            tuple(f"x{j}" for j in range(d))
            if names is None
            else tuple(str(n) for n in names)
        )
        if len(nm) != d:
            raise ValueError("names length != bounds length")
        return cls(bounds=bounds, log_scale=ls, names=nm)

    @property
    def dim(self) -> int:
        return len(self.bounds)

    def to_config(self) -> dict:
        return {
            "names": list(self.names),
            "bounds": [list(b) for b in self.bounds],
            "log_scale": list(self.log_scale),
        }

    @classmethod
    def from_config(cls, cfg: dict) -> "SearchSpace":
        return cls.create(
            cfg["bounds"], log_scale=cfg["log_scale"], names=cfg["names"]
        )

    def fingerprint(self) -> str:
        """Stable identity of the search geometry; a resumed search must
        match the journal's or be refused (tuning/state.py)."""
        return hashlib.sha256(
            json.dumps(self.to_config(), sort_keys=True).encode()
        ).hexdigest()

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        """Uniform (log-uniform where flagged) points in the box."""
        out = np.empty((n, self.dim))
        for j, (lo, hi) in enumerate(self.bounds):
            if self.log_scale[j]:
                out[:, j] = np.exp(
                    rng.uniform(np.log(lo), np.log(hi), size=n)
                )
            else:
                out[:, j] = rng.uniform(lo, hi, size=n)
        return out

    def normalize(self, X: np.ndarray) -> np.ndarray:
        """Map the (possibly log-scaled) box to [0,1]^d — the GP's input
        space, and the metric for nearest-neighbor warm starts."""
        X = np.atleast_2d(np.asarray(X, float))
        out = np.empty_like(X)
        for j, (lo, hi) in enumerate(self.bounds):
            if self.log_scale[j]:
                out[:, j] = (np.log(X[:, j]) - np.log(lo)) / (
                    np.log(hi) - np.log(lo)
                )
            else:
                out[:, j] = (X[:, j] - lo) / (hi - lo)
        return out


# ---------------------------------------------------------------------------
# Proposers
# ---------------------------------------------------------------------------

class Proposer:
    """ask/tell protocol.  ``ask`` returns one point and registers it as
    PENDING; every pending point must later be resolved by ``tell``
    (observed) or ``resolve`` (failed, no observation).  ``y`` is in
    minimization convention."""

    kind = "base"

    def __init__(self, space: SearchSpace, seed: int = 0):
        self.space = space
        self.rng = np.random.default_rng(seed)
        self.pending: list[np.ndarray] = []
        self.observations: list[tuple[np.ndarray, float]] = []

    # -- protocol ----------------------------------------------------------
    def ask(self) -> np.ndarray:
        x = self._propose()
        self.pending.append(np.asarray(x, float))
        return x

    def tell(self, x: np.ndarray, y: float) -> None:
        self._drop_pending(x)
        self.observations.append((np.asarray(x, float), float(y)))

    def resolve(self, x: np.ndarray) -> None:
        """Drop a pending ask without an observation (trial failed)."""
        self._drop_pending(x)

    def exhausted(self) -> bool:
        return False

    # -- journal restore ---------------------------------------------------
    def restore_ask(self, x: np.ndarray) -> None:
        """Re-register a journaled ask as pending WITHOUT consuming RNG
        (the journaled rng_state already reflects it)."""
        self.pending.append(np.asarray(x, float))

    @property
    def rng_state(self) -> dict:
        return self.rng.bit_generator.state

    def set_rng_state(self, state: dict) -> None:
        self.rng.bit_generator.state = state

    # -- internals ---------------------------------------------------------
    def _propose(self) -> np.ndarray:
        raise NotImplementedError

    def _drop_pending(self, x: np.ndarray) -> None:
        x = np.asarray(x, float)
        for i, p in enumerate(self.pending):
            if p.shape == x.shape and np.allclose(p, x, rtol=0, atol=0):
                del self.pending[i]
                return
        # Journal floats round-trip exactly through repr, so a miss means
        # a caller bug — but a proposer must never sink the search over
        # bookkeeping; drop the oldest pending instead.
        if self.pending:
            del self.pending[0]


class RandomProposer(Proposer):
    """Uniform sampling (the RandomSearch analogue)."""

    kind = "random"

    def _propose(self) -> np.ndarray:
        return self.space.sample(self.rng, 1)[0]


class GridProposer(Proposer):
    """A fixed, ordered list of points (λ-path sweeps, bench parity runs).
    RNG-free: sequential and parallel orchestration propose the identical
    trial set."""

    kind = "grid"

    def __init__(self, space: SearchSpace, points, seed: int = 0):
        super().__init__(space, seed)
        self.points = [
            np.atleast_1d(np.asarray(p, float)) for p in points
        ]
        self._next = 0

    def _propose(self) -> np.ndarray:
        if self._next >= len(self.points):
            raise IndexError("grid proposer exhausted")
        x = self.points[self._next]
        self._next += 1
        return x

    def restore_ask(self, x: np.ndarray) -> None:
        super().restore_ask(x)
        self._next += 1

    def exhausted(self) -> bool:
        return self._next >= len(self.points)


class GPProposer(Proposer):
    """GP + expected improvement with constant-liar batching.

    Sequentially this is GaussianProcessSearch's inner step; with k asks
    pending it fits the surrogate over observations ∪ pending, imputing
    each pending point's value as the best observed y (the CL-min
    "constant liar" of Ginsbourger et al.) — the liar flattens EI around
    in-flight points so a batch of asks spreads out instead of k copies
    of the same argmax.
    """

    kind = "gp"

    def __init__(
        self,
        space: SearchSpace,
        seed: int = 0,
        n_seed_points: int = 3,
        n_candidates: int = 256,
        length_scale="fit",
    ):
        super().__init__(space, seed)
        self.n_seed_points = int(n_seed_points)
        self.n_candidates = int(n_candidates)
        self.length_scale = length_scale

    def _propose(self) -> np.ndarray:
        # Cold start: random until the surrogate has seed observations
        # (pending count included — a 4-wide first wave is 4 random seeds,
        # not 1 random + 3 GP fits over nothing).
        if (
            not self.observations
            or len(self.observations) + len(self.pending) < self.n_seed_points
        ):
            return self.space.sample(self.rng, 1)[0]
        X_obs = [x for x, _ in self.observations]
        y_obs = [y for _, y in self.observations]
        best = float(np.min(y_obs))
        liar = best  # CL-min: pending points pinned at the incumbent
        X = np.asarray(X_obs + list(self.pending), float)
        y = np.asarray(y_obs + [liar] * len(self.pending), float)
        gp = GaussianProcessModel(self.length_scale).fit(
            self.space.normalize(X), y
        )
        candidates = self.space.sample(self.rng, self.n_candidates)
        mean, std = gp.predict(self.space.normalize(candidates))
        ei = expected_improvement(mean, std, best)
        return candidates[int(np.argmax(ei))]


PROPOSERS = {
    "random": RandomProposer,
    "gp": GPProposer,
    "grid": GridProposer,
}


def make_proposer(
    kind: str, space: SearchSpace, seed: int = 0, **kwargs
) -> Proposer:
    try:
        cls = PROPOSERS[kind]
    except KeyError:
        raise ValueError(
            f"unknown proposer {kind!r} (have {sorted(PROPOSERS)})"
        ) from None
    return cls(space, seed=seed, **kwargs)


# ---------------------------------------------------------------------------
# ASHA
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AshaConfig:
    """Successive-halving geometry.  Rung r's resource (optimizer
    iterations for GLM trials, CD iterations for GAME trials) is
    ``min_resource · reduction_factor^r``; ``num_rungs`` rungs total, so
    the top rung runs at ``min_resource · η^(num_rungs-1)``."""

    min_resource: int = 1
    reduction_factor: int = 3
    num_rungs: int = 3

    def __post_init__(self):
        if self.min_resource < 1 or self.num_rungs < 1:
            raise ValueError("min_resource and num_rungs must be >= 1")
        if self.reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2")

    def resource(self, rung: int) -> int:
        return self.min_resource * self.reduction_factor**rung

    @property
    def top_rung(self) -> int:
        return self.num_rungs - 1

    def to_config(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_config(cls, cfg: Optional[dict]) -> Optional["AshaConfig"]:
        return None if cfg is None else cls(**cfg)


class AshaScheduler:
    """Promote/kill decisions on rung metrics (minimization convention).

    ``report`` records the metric and decides; ``record`` only records —
    journal replay uses it to rebuild the rung tables for decisions that
    are already journaled, without re-deciding them.  Decisions are a
    pure function of the rung table CONTENTS (a set), so replaying
    records in any order reproduces the table the crashed run had.
    """

    def __init__(self, config: AshaConfig):
        self.config = config
        #: rung → {trial_id: y}; entries never change once written.
        self.rungs: list[dict[int, float]] = [
            {} for _ in range(config.num_rungs)
        ]

    def record(self, trial_id: int, rung: int, y: float) -> None:
        self.rungs[rung][trial_id] = float(y)

    def decide(self, trial_id: int, rung: int) -> str:
        """"complete" (top rung), "promote", or "stop"."""
        if rung >= self.config.top_rung:
            return "complete"
        table = self.rungs[rung]
        # Deterministic total order: metric, then trial id (stable under
        # exact ties, which synthetic objectives do produce).
        ranked = sorted(table.items(), key=lambda kv: (kv[1], kv[0]))
        keep = max(1, len(ranked) // self.config.reduction_factor)
        top = {tid for tid, _ in ranked[:keep]}
        return "promote" if trial_id in top else "stop"

    def report(self, trial_id: int, rung: int, y: float) -> str:
        self.record(trial_id, rung, y)
        return self.decide(trial_id, rung)
