"""Config-keyed solver registry.

The reference keys its optimizer choice off ``OptimizerConfig.optimizerType``
inside each optimization problem class; PRs 1-17 reproduced that as static
``if``-chains in three places (``optim/problem.solve``, ``optim/streaming
.streaming_run_grid``, the GAME block solvers).  This module centralizes the
dispatch: each solver registers a :class:`SolverDef` under a name, and
``OptimizerConfig.solver`` selects one explicitly — or, when unset,
:func:`resolve` reproduces the historical routing rules bitwise (bounds →
SPG, any L1 component → OWL-QN, else the configured optimizer).

Two solver kinds exist:

- ``"jit"`` — the solve is one pure traced function (L-BFGS, OWL-QN, TRON,
  SPG).  It runs inside ``jax.jit`` / ``shard_map`` via the ``resident``
  callable, or as a host loop of streamed passes via ``streamed``.
- ``"host"`` — the solve runs a host-side outer loop around a compiled step
  program (consensus-ADMM, distributed block CD): it CANNOT execute inside
  a traced solve, so ``problem.solve`` rejects it and the grid runners
  route through :func:`photon_ml_tpu.solvers.sharded.run_grid_sharded`
  instead (``sharded`` is the factory: ``sharded(problem, dist, mesh,
  l1_mask) → solve_fn(lam, w_prev, dist_override=None)``).

Registration is guarded by a lock-order-sanitized lock (witness class
``solvers.registry`` — analysis/sanitizers.py): drivers and tuning threads
resolve concurrently while tests register scratch solvers.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, NamedTuple, Optional

import jax

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers

Array = jax.Array


class ResidentSolve(NamedTuple):
    """One resident (device-array) solve request — what ``problem.solve``
    hands a jit-kind solver.  ``l1``/``l2`` are the already-split traced
    regularization weights; ``opt`` is the ``OptimizerConfig``."""

    objective: Any  # GlmObjective
    data: Any  # GlmData
    w0: Array
    l1: Array
    l2: Array
    opt: Any  # OptimizerConfig
    axis_name: Optional[str] = None
    l1_mask: Optional[Array] = None
    bounds: Optional[tuple] = None


class StreamedSolve(NamedTuple):
    """One streamed solve request — what ``streaming_run_grid`` hands a
    jit-kind solver's ``streamed`` callable.  ``sobj`` is the
    StreamingObjective; ``value_and_grad_batch`` is the batched
    line-search evaluator (or None when disabled)."""

    sobj: Any  # StreamingObjective
    w0: Array
    l1: float
    l2: float
    opt: Any  # OptimizerConfig
    l1_mask: Optional[Array] = None
    value_and_grad_batch: Optional[Callable] = None


@dataclasses.dataclass(frozen=True)
class SolverDef:
    """One registered solver.

    ``resident`` / ``streamed`` serve jit-kind solvers on the resident and
    streamed paths; ``sharded`` serves host-kind solvers (and is a
    FACTORY — it binds the problem + sharded data once and returns the
    per-λ ``solve_fn``, so Gram factorizations and compiled step programs
    are shared across a warm-start grid)."""

    name: str
    kind: str  # "jit" | "host"
    description: str
    supports_l1: bool = False
    supports_bounds: bool = False
    resident: Optional[Callable[[ResidentSolve], Any]] = None
    streamed: Optional[Callable[[StreamedSolve], Any]] = None
    sharded: Optional[Callable] = None

    def __post_init__(self):
        if self.kind not in ("jit", "host"):
            raise ValueError(f"solver kind must be jit|host, got {self.kind!r}")
        if self.kind == "jit" and self.resident is None:
            raise ValueError(f"jit-kind solver {self.name!r} needs a resident callable")
        if self.kind == "host" and self.sharded is None:
            raise ValueError(f"host-kind solver {self.name!r} needs a sharded factory")


_REGISTRY: dict[str, SolverDef] = {}
_LOCK = sanitizers.tracked(threading.Lock(), "solvers.registry")


def register(defn: SolverDef, replace: bool = False) -> SolverDef:
    """Register a solver under ``defn.name``; duplicate names are refused
    unless ``replace=True`` (tests swapping in instrumented doubles)."""
    with _LOCK:
        if defn.name in _REGISTRY and not replace:
            raise ValueError(
                f"solver {defn.name!r} is already registered "
                "(pass replace=True to override)"
            )
        _REGISTRY[defn.name] = defn
    tel = telemetry_mod.current()
    if tel.enabled:
        tel.counter("solvers_registered_total").inc()
    return defn


def get(name: str) -> SolverDef:
    with _LOCK:
        defn = _REGISTRY.get(name)
    if defn is None:
        raise KeyError(
            f"unknown solver {name!r}; registered: {names()}"
        )
    return defn


def names() -> list[str]:
    with _LOCK:
        return sorted(_REGISTRY)


def solver_options_dict(opt) -> dict:
    """``OptimizerConfig.solver_options`` (a hashable tuple of (key, value)
    pairs — it lives in lru_cache keys) as a plain dict."""
    return dict(getattr(opt, "solver_options", ()) or ())


def resolve(opt, *, l1_frac: float, has_bounds: bool = False) -> SolverDef:
    """Pick the solver for an ``OptimizerConfig``.

    ``opt.solver`` unset reproduces the historical static routing bitwise:
    bounds → SPG for any smooth config, any L1 component → OWL-QN (the
    orthant machinery is the only L1-capable one), else the configured
    optimizer.  An explicit name is honored as-is, but incompatible
    combinations (an L1 component with a solver that has no subgradient
    handling; bounds with anything but SPG) are rejected here — statically,
    before any compute is spent."""
    name = getattr(opt, "solver", None)
    if name is None:
        if has_bounds:
            return get("spg")
        if l1_frac > 0.0:
            return get("owlqn")
        return get(opt.optimizer.value)
    defn = get(name)
    if has_bounds and not defn.supports_bounds:
        raise ValueError(
            f"solver {name!r} does not support box constraints; "
            "only 'spg' does — drop the bounds or the solver override"
        )
    if l1_frac > 0.0 and not defn.supports_l1:
        raise ValueError(
            f"solver {name!r} has no L1 subgradient handling; use "
            "'owlqn', 'admm', or 'block_cd' for L1/elastic-net configs"
        )
    if name == "spg" and not has_bounds:
        # SPG is a projection method: without box constraints there is no
        # feasible set to project onto (and its resident closure reads
        # ctx.bounds).  Reject up front instead of crashing mid-trace.
        raise ValueError(
            "solver 'spg' needs box constraints (lower/upper bounds); "
            "use 'lbfgs' or 'tron' for unconstrained smooth configs"
        )
    return defn


# ---------------------------------------------------------------------------
# Built-in jit-kind solvers.  Each callable builds EXACTLY the closure the
# pre-registry problem.solve / streaming_run_grid built, so dispatching
# through the registry is bitwise-identical to the old static routing
# (tests/test_solvers.py pins this).
# ---------------------------------------------------------------------------


def _lbfgs_resident(ctx: ResidentSolve):
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve

    obj, data, opt = ctx.objective, ctx.data, ctx.opt
    return lbfgs_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=ctx.l2, axis_name=ctx.axis_name
        ),
        ctx.w0,
        LBFGSConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        ),
    )


def _owlqn_resident(ctx: ResidentSolve):
    from photon_ml_tpu.optim.owlqn import OWLQNConfig, owlqn_solve

    obj, data, opt = ctx.objective, ctx.data, ctx.opt
    return owlqn_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=ctx.l2, axis_name=ctx.axis_name
        ),
        ctx.w0,
        ctx.l1,
        OWLQNConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        ),
        l1_mask=ctx.l1_mask,
    )


def _tron_resident(ctx: ResidentSolve):
    from photon_ml_tpu.optim.tron import TRONConfig, tron_solve

    obj, data, opt = ctx.objective, ctx.data, ctx.opt
    return tron_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=ctx.l2, axis_name=ctx.axis_name
        ),
        lambda w, v, aux: obj.hvp(
            w, v, data, l2_weight=ctx.l2, axis_name=ctx.axis_name, d2w=aux
        ),
        ctx.w0,
        TRONConfig(max_iters=opt.max_iters, tolerance=opt.tolerance),
        d2_fn=lambda w: obj.d2_weights(w, data),
    )


def _spg_resident(ctx: ResidentSolve):
    from photon_ml_tpu.optim.projected import SPGConfig, spg_solve

    obj, data, opt = ctx.objective, ctx.data, ctx.opt
    return spg_solve(
        lambda w: obj.value_and_grad(
            w, data, l2_weight=ctx.l2, axis_name=ctx.axis_name
        ),
        ctx.w0,
        ctx.bounds[0],
        ctx.bounds[1],
        SPGConfig(max_iters=opt.max_iters, tolerance=opt.tolerance),
        w_axis=None,
    )


def _lbfgs_streamed(ctx: StreamedSolve):
    from photon_ml_tpu.optim.lbfgs import LBFGSConfig
    from photon_ml_tpu.optim.streaming import streaming_lbfgs_solve

    opt = ctx.opt
    return streaming_lbfgs_solve(
        lambda w: ctx.sobj.value_and_grad(w, ctx.l2),
        ctx.w0,
        LBFGSConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        ),
        value_and_grad_batch=ctx.value_and_grad_batch,
    )


def _owlqn_streamed(ctx: StreamedSolve):
    from photon_ml_tpu.optim.owlqn import OWLQNConfig
    from photon_ml_tpu.optim.streaming import streaming_owlqn_solve

    opt = ctx.opt
    return streaming_owlqn_solve(
        lambda w: ctx.sobj.value_and_grad(w, ctx.l2),
        ctx.w0,
        ctx.l1,
        OWLQNConfig(
            max_iters=opt.max_iters,
            tolerance=opt.tolerance,
            history=opt.history,
        ),
        l1_mask=ctx.l1_mask,
        value_and_grad_batch=ctx.value_and_grad_batch,
    )


def _tron_streamed(ctx: StreamedSolve):
    from photon_ml_tpu.optim.streaming import streaming_tron_solve
    from photon_ml_tpu.optim.tron import TRONConfig

    opt = ctx.opt
    return streaming_tron_solve(
        lambda w: ctx.sobj.value_and_grad(w, ctx.l2),
        lambda w, v: ctx.sobj.hvp(w, v, ctx.l2),
        ctx.w0,
        TRONConfig(max_iters=opt.max_iters, tolerance=opt.tolerance),
    )


register(SolverDef(
    name="lbfgs",
    kind="jit",
    description="limited-memory BFGS (smooth objectives)",
    resident=_lbfgs_resident,
    streamed=_lbfgs_streamed,
))
register(SolverDef(
    name="owlqn",
    kind="jit",
    description="orthant-wise L-BFGS (L1/elastic-net)",
    supports_l1=True,
    resident=_owlqn_resident,
    streamed=_owlqn_streamed,
))
register(SolverDef(
    name="tron",
    kind="jit",
    description="trust-region Newton-CG (smooth objectives)",
    resident=_tron_resident,
    streamed=_tron_streamed,
))
register(SolverDef(
    name="spg",
    kind="jit",
    description="spectral projected gradient (box constraints)",
    supports_bounds=True,
    resident=_spg_resident,
))
