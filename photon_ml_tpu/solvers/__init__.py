"""Solver subsystem: config-keyed registry + communication-light solvers.

The registry (solvers/registry.py) turns the hard-wired optimizer
dispatch that lived in ``optim/problem.solve``, ``optim/streaming
.streaming_run_grid`` and the GAME block solvers into a config-keyed
factory: every solver — the existing L-BFGS / OWL-QN / TRON / SPG and
the new consensus-ADMM (solvers/admm.py) and distributed block
coordinate descent (solvers/block_cd.py) — registers a
:class:`~photon_ml_tpu.solvers.registry.SolverDef` and is selected by
``OptimizerConfig.solver`` (name) + ``solver_options`` (knobs).  Unset
``solver`` reproduces the historical static routing bitwise (bounds →
SPG, any L1 component → OWL-QN, else the configured optimizer).

Importing the package registers every built-in solver.
"""

from photon_ml_tpu.solvers import admm as _admm  # noqa: F401  (registers)
from photon_ml_tpu.solvers import block_cd as _block_cd  # noqa: F401
from photon_ml_tpu.solvers import registry
from photon_ml_tpu.solvers.registry import (  # noqa: F401
    ResidentSolve,
    SolverDef,
    StreamedSolve,
    get,
    names,
    register,
    resolve,
    solver_options_dict,
)
