"""Grid runner + shard builders for host-kind solvers (ADMM, block CD).

Host-kind solvers (``SolverDef.kind == "host"``) run a host-side outer loop
around one compiled step program, so they cannot execute inside the traced
``problem.solve``.  This module is their entry: :func:`run_grid_sharded`
plugs a host-kind solver's ``sharded`` factory into the SAME
``problem.grid_loop`` warm-start chain the traced paths use — identical
checkpoint/resume semantics (GridCheckpointer via ``on_solved``, the
``grid.point`` chaos boundary), identical solver telemetry spans.

Sharding comes in two flavors, chosen by the caller:

- a real device mesh (``parallel.distributed.data_mesh``) — the solver's
  step program runs SPMD under ``shard_map`` with one ``lax.psum`` per
  outer iteration (multihost-ready);
- LOGICAL shards on one device (``mesh=None``) — the same leading-shard-axis
  layout (``shard_glm_data(..., mesh=None, n_shards=k)``), with ``vmap``'d
  per-shard subproblems and an axis-0 sum standing in for the psum, so the
  communication-per-iteration A/B (bench.py ``BENCH_ONLY=solvers``) runs
  anywhere, and single-device callers (tuning ``fit_once``, the GAME
  fixed-effect coordinate) still get ≥2 shards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def resolve_shard_count(opt, mesh=None, default: int = 2) -> int:
    """The shard count for a host-kind solve: the mesh size when a mesh
    participates, else the solver_options ``shards`` knob, else
    ``default`` logical shards."""
    from photon_ml_tpu.solvers import registry

    if mesh is not None:
        return mesh.devices.size
    shards = int(registry.solver_options_dict(opt).get("shards", 0) or 0)
    return shards if shards > 0 else default


def stack_resident(data, n_shards: int):
    """Device-resident GlmData → DistributedGlmData with ``n_shards``
    LOGICAL shards: rows padded (weight 0) to a multiple of the shard
    count, every array reshaped to a leading shard axis.  Dense features
    only — splitting a device-resident COO block into row shards would
    need a host round-trip; densify upstream instead."""
    from photon_ml_tpu.ops.sparse import DenseMatrix
    from photon_ml_tpu.parallel.distributed import (
        DistributedGlmData,
        _pad_rows_to,
    )

    if not isinstance(data.features, DenseMatrix):
        raise ValueError(
            "logical sharding of device-resident data needs DenseMatrix "
            "features; build shards from host data (shard_glm_data) for "
            "sparse inputs"
        )
    rows = int(data.labels.shape[0])
    total = _pad_rows_to(rows, n_shards)
    pad = total - rows
    rows_per = total // n_shards

    def pad_rows(a, fill=0.0):
        if pad == 0:
            return a
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill)

    stacked = dataclasses.replace(
        data,
        features=DenseMatrix(
            pad_rows(data.features.data).reshape(n_shards, rows_per, -1)
        ),
        labels=pad_rows(data.labels).reshape(n_shards, rows_per),
        weights=pad_rows(data.weights).reshape(n_shards, rows_per),
        offsets=pad_rows(data.offsets).reshape(n_shards, rows_per),
    )
    return DistributedGlmData(data=stacked, n_shards=n_shards)


def run_grid_sharded(
    problem,
    dist,
    mesh,
    reg_weights: Sequence[float],
    w0: Optional[Array] = None,
    l1_mask: Optional[Array] = None,
    warm_start: bool = True,
    solved: Optional[dict] = None,
    on_solved=None,
):
    """The λ-grid warm-start chain for a host-kind solver over sharded
    data — the host-loop counterpart of
    ``parallel.distributed.run_grid_distributed``."""
    from photon_ml_tpu.solvers import registry

    cfg = problem.config
    defn = registry.resolve(
        cfg.optimizer, l1_frac=cfg.regularization.l1_weight(1.0)
    )
    if defn.kind != "host":
        raise ValueError(
            f"run_grid_sharded serves host-kind solvers; {defn.name!r} is "
            "jit-kind — use problem.run_grid / run_grid_distributed"
        )
    if cfg.compute_variances:
        raise ValueError(
            f"compute_variances is not supported with solver "
            f"{defn.name!r}; drop the variance request or use a jit-kind "
            "solver"
        )
    solve = defn.sharded(problem, dist, mesh, l1_mask)
    d = int(dist.data.features.shape[-1])
    if w0 is None:
        w0 = jnp.zeros((d,), jnp.float32)
    return problem.grid_loop(
        lambda lam, w_prev: solve(lam, w_prev),
        reg_weights, w0, warm_start, solved, on_solved, None,
    )


def make_fixed_effect_trainer(problem, data, n_shards: int, l1_mask=None):
    """A GAME fixed-effect trainer backed by a host-kind solver:
    ``trainer(offsets, w0, reg_weight) → coefficients``.

    The dataset shards once (logical, dense); each GAME outer iteration's
    residual offsets re-slot into the SAME shard layout, so the solver's
    compiled step program is reused across iterations."""
    template = stack_resident(data, n_shards)
    rows = int(data.labels.shape[0])
    rows_per = int(template.data.labels.shape[-1])
    total = rows_per * n_shards

    from photon_ml_tpu.solvers import registry

    cfg = problem.config
    defn = registry.resolve(
        cfg.optimizer, l1_frac=cfg.regularization.l1_weight(1.0)
    )
    solve = defn.sharded(problem, template, None, l1_mask)

    def trainer(offsets: Array, w0: Array, reg_weight: float) -> Array:
        off = jnp.asarray(offsets, jnp.float32)
        if total != rows:
            off = jnp.pad(off, (0, total - rows))
        dist_k = dataclasses.replace(
            template,
            data=dataclasses.replace(
                template.data, offsets=off.reshape(n_shards, rows_per)
            ),
        )
        return solve(reg_weight, w0, dist_override=dist_k).w

    return trainer
