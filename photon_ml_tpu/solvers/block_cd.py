"""Distributed block coordinate descent — local CD sweeps, periodic sync.

The reference's Spark ecosystem pairs GLMs with distributed coordinate
descent ("Distributed Coordinate Descent for Generalized Linear Models with
Regularization", PAPERS.md): workers run proximal-Newton coordinate updates
against LOCAL rows and synchronize per block round instead of per step.
The TPU translation, with one crucial correction:

- the coordinate space is partitioned statically into ``n_blocks`` blocks;
  round k works block ``k mod n_blocks`` (round-robin cycling);
- each round opens with ONE all-reduce of the active block's GLOBAL
  per-coordinate gradient and curvature at the round-start iterate
  (``[g_blk, h_blk, f]`` — 2·blk+1 floats);
- each shard then runs ``sweeps`` sequential prox-Newton CD sweeps over the
  block against its OWN rows, using the DRIFT-CORRECTED gradient
  ``ĝ_j = g_j^glob(m₀) + (g_j^loc(m) − g_j^loc(m₀))`` — the global
  round-start gradient plus the shard's live local drift (maintained
  margins make every update O(rows)).  Naive local sweeps average to a
  BIASED fixed point (shard-local Newton steps cancel where
  ``Σ_s g^s/h^s = 0``, not where ``Σ_s g^s = 0`` — measured ~0.6% objective
  gap on heterogeneous logistic shards); with the correction the update is
  zero exactly at GLOBAL prox-stationarity, so cycling the blocks converges
  to the true optimum;
- the block synchronization closes the round with a second all-reduce of
  the shard-averaged block delta (``blk`` floats).

Two fixed-size all-reduces per block round — independent of sweep count and
block size versus one per line-search step for the psum-per-evaluation
solvers.  Like consensus-ADMM (solvers/admm.py) this runs over a real
``shard_map`` mesh (``lax.psum`` over ``DATA_AXIS``) or as logical shards
(``vmap`` + axis-0 sums) on one device, fires the ``distributed.allreduce``
chaos site at each round's reduce seam, and publishes the
``solver_allreduce_*`` / ``solver_outer_iterations_total`` counters.

Scope: per-shard column access needs DENSE features (``DenseMatrix``) and
identity normalization — sparse inputs are densified upstream when small
(glm_driver) or rejected pointedly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.optim.lbfgs import SolveResult
from photon_ml_tpu.optim.owlqn import _pseudo_gradient

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class BlockCDOptions:
    """Knobs, settable via ``OptimizerConfig.solver_options`` (docs/solvers.md).

    ``max_rounds`` of 0 defers to ``OptimizerConfig.max_iters × n_blocks``
    (one configured "iteration" ≈ one full block cycle); ``tolerance`` of 0
    defers to ``OptimizerConfig.tolerance`` (relative objective change over
    one full cycle)."""

    n_blocks: int = 4
    sweeps: int = 2  # local CD sweeps over the active block per round
    max_rounds: int = 0
    tolerance: float = 0.0
    shards: int = 0  # logical-shard count (0 = auto; sharded.py reads it)

    @classmethod
    def from_options(cls, options: dict) -> "BlockCDOptions":
        fields = [f.name for f in dataclasses.fields(cls)]
        unknown = sorted(set(options) - set(fields))
        if unknown:
            raise ValueError(
                f"unknown block_cd solver_options {unknown}; valid: {fields}"
            )
        coerced = {
            k: (float(v) if k == "tolerance" else int(v))
            for k, v in options.items()
        }
        opts = cls(**coerced)
        if opts.n_blocks < 1 or opts.sweeps < 1:
            raise ValueError("block_cd n_blocks and sweeps must be >= 1")
        return opts


def make_sharded_solver(problem, dist, mesh, l1_mask=None):
    """Registry ``sharded`` factory (same contract as
    solvers.admm.make_sharded_solver)."""
    from photon_ml_tpu.ops.sparse import DenseMatrix
    from photon_ml_tpu.parallel.compat import shard_map
    from photon_ml_tpu.parallel.distributed import DATA_AXIS
    from photon_ml_tpu.solvers import registry as registry_mod

    if not isinstance(dist.data.features, DenseMatrix):
        raise ValueError(
            "block_cd needs dense per-shard columns (DenseMatrix); densify "
            "the design matrix upstream (glm_driver does this automatically "
            "for small feature spaces) or use the 'admm' solver, whose "
            "matvec-based subproblems take sparse features"
        )
    if problem.normalization is not None:
        raise ValueError(
            "block_cd does not compose with feature normalization (its "
            "column updates read raw columns); drop --normalization or use "
            "'admm'"
        )
    obj = problem.objective
    loss = obj.loss
    cfg = problem.config
    opt = cfg.optimizer
    opts = BlockCDOptions.from_options(
        registry_mod.solver_options_dict(opt)
    )
    l1_frac = cfg.regularization.l1_weight(1.0)
    l2_frac = cfg.regularization.l2_weight(1.0)

    n = dist.n_shards
    d = int(dist.data.features.shape[-1])
    n_blocks = min(opts.n_blocks, d)
    max_rounds = opts.max_rounds or opt.max_iters * n_blocks
    tol = opts.tolerance or opt.tolerance
    mask = (
        jnp.ones((d,), jnp.float32)
        if l1_mask is None
        else jnp.asarray(l1_mask, jnp.float32)
    )
    # Static block partition, padded with -1 so every round runs the SAME
    # compiled step program (coords are a traced argument).
    splits = np.array_split(np.arange(d, dtype=np.int32), n_blocks)
    blk = max(len(s) for s in splits)
    blocks = [
        jnp.asarray(
            np.concatenate([s, np.full(blk - len(s), -1, np.int32)])
        )
        for s in splits
    ]

    def block_stats(local, w, coords):
        """Round-start margins + the shard's block gradient/curvature and
        data term — the payload of the round's FIRST reduce."""
        x_mat = local.features.data
        y, wt, off = local.labels, local.weights, local.offsets
        m0 = x_mat @ w + off
        u0 = wt * loss.d1(m0, y)
        d20 = wt * loss.d2(m0, y)
        cols = jnp.take(x_mat, jnp.maximum(coords, 0), axis=1)  # (rows, blk)
        g0 = cols.T @ u0
        h0 = (cols * cols).T @ d20
        f0 = jnp.sum(wt * loss.value(m0, y))
        return m0, u0, cols, g0, h0, f0

    def local_sweeps(local, w, coords, m0, cols, g0_loc, g_glob, h_glob,
                     l1, l2):
        """``sweeps`` drift-corrected prox-Newton CD passes over the active
        block; returns the shard's block delta (blk,)."""
        y, wt = local.labels, local.weights
        w_blk0 = w[jnp.maximum(coords, 0)]
        valid = coords >= 0
        h = jnp.maximum(h_glob + l2, 1e-12)
        pos = jnp.tile(jnp.arange(blk, dtype=jnp.int32), opts.sweeps)

        def coord_step(carry, i):
            w_blk, m = carry
            col = cols[:, i]
            wj = w_blk[i]
            g_live = jnp.vdot(col, wt * loss.d1(m, y))
            ghat = g_glob[i] + (g_live - g0_loc[i]) + l2 * wj
            zhat = wj - ghat / h[i]
            thr = l1 * mask[jnp.maximum(coords[i], 0)] / h[i]
            wj_new = jnp.sign(zhat) * jnp.maximum(jnp.abs(zhat) - thr, 0.0)
            wj_new = jnp.where(valid[i], wj_new, wj)
            m = m + (wj_new - wj) * col
            return (w_blk.at[i].set(wj_new), m), None

        (w_blk, _), _ = lax.scan(coord_step, (w_blk0, m0), pos)
        return jnp.where(valid, w_blk - w_blk0, 0.0)

    def apply_sync(w, coords, delta_sum, f0, l1, l2):
        """Block synchronization from the second reduce (replicated)."""
        upd = jnp.zeros((d,), jnp.float32).at[
            jnp.maximum(coords, 0)
        ].add(jnp.where(coords >= 0, delta_sum / n, 0.0))
        w_next = w + upd
        f_total = (
            f0 + l1 * jnp.sum(jnp.abs(w) * mask)
            + 0.5 * l2 * jnp.vdot(w, w)
        )
        return w_next, f_total

    if mesh is not None:
        spec_data = jax.sharding.PartitionSpec(DATA_AXIS)
        spec_repl = jax.sharding.PartitionSpec()

        def spmd_step(dd, w, coords, l1, l2):
            local = dd.local()
            m0, _u0, cols, g0, h0, f0_loc = block_stats(local, w, coords)
            tot1 = lax.psum(
                jnp.concatenate([g0, h0, f0_loc[None]]), DATA_AXIS
            )
            g_glob, h_glob, f0 = tot1[:blk], tot1[blk:2 * blk], tot1[2 * blk]
            delta = local_sweeps(
                local, w, coords, m0, cols, g0, g_glob, h_glob, l1, l2
            )
            delta_sum = lax.psum(delta, DATA_AXIS)
            return apply_sync(w, coords, delta_sum, f0, l1, l2)

        step = jax.jit(shard_map(
            spmd_step,
            mesh=mesh,
            in_specs=(spec_data, spec_repl, spec_repl, spec_repl, spec_repl),
            out_specs=(spec_repl, spec_repl),
            check_vma=False,
        ))

        def spmd_eval(dd, w, l1, l2):
            val, grad = obj.raw_value_and_grad(w, dd.local())
            val, grad = lax.psum((val, grad), DATA_AXIS)
            val = (
                val + l1 * jnp.sum(jnp.abs(w) * mask)
                + 0.5 * l2 * jnp.vdot(w, w)
            )
            return val, _pseudo_gradient(w, grad + l2 * w, l1, mask)

        eval_fn = jax.jit(shard_map(
            spmd_eval,
            mesh=mesh,
            in_specs=(spec_data, spec_repl, spec_repl, spec_repl),
            out_specs=(spec_repl, spec_repl),
            check_vma=False,
        ))
    else:
        def logical_step(dd, w, coords, l1, l2):
            m0, _u0, cols, g0, h0, f0_loc = jax.vmap(
                lambda local: block_stats(local, w, coords)
            )(dd.data)
            g_glob = jnp.sum(g0, axis=0)
            h_glob = jnp.sum(h0, axis=0)
            f0 = jnp.sum(f0_loc)
            delta = jax.vmap(
                lambda local, m0s, colss, g0s: local_sweeps(
                    local, w, coords, m0s, colss, g0s, g_glob, h_glob,
                    l1, l2,
                )
            )(dd.data, m0, cols, g0)
            return apply_sync(w, coords, jnp.sum(delta, axis=0), f0, l1, l2)

        step = jax.jit(logical_step)

        def logical_eval(dd, w, l1, l2):
            vals, grads = jax.vmap(
                lambda local: obj.raw_value_and_grad(w, local)
            )(dd.data)
            val = (
                jnp.sum(vals) + l1 * jnp.sum(jnp.abs(w) * mask)
                + 0.5 * l2 * jnp.vdot(w, w)
            )
            return val, _pseudo_gradient(
                w, jnp.sum(grads, axis=0) + l2 * w, l1, mask
            )

        eval_fn = jax.jit(logical_eval)

    # first reduce: [g_blk, h_blk, f] — second: the block delta.
    payload_bytes = (2 * blk + 1) * 4 + blk * 4

    def solve_fn(lam, w_prev, dist_override=None) -> SolveResult:
        dd = dist if dist_override is None else dist_override
        l1 = jnp.asarray(l1_frac * float(lam), jnp.float32)
        l2 = jnp.asarray(l2_frac * float(lam), jnp.float32)
        w = (
            jnp.zeros((d,), jnp.float32)
            if w_prev is None
            else jnp.asarray(w_prev, jnp.float32)
        )
        values = []
        rounds = 0
        converged = False
        for k in range(max_rounds):
            # The reduce seam: the step program about to run carries this
            # round's two all-reduces (docs/robustness.md).
            chaos_mod.maybe_fail(
                "distributed.allreduce", solver="block_cd", outer=k
            )
            w_new, f_total = step(dd, w, blocks[k % n_blocks], l1, l2)
            values.append(float(f_total))  # objective at round-START w
            w = w_new
            rounds = k + 1
            # Objective change over one full block cycle (every coordinate
            # visited once): the per-round change of a single small block
            # can be ~0 while other blocks still move.
            if k >= n_blocks:
                prev, cur = values[-1 - n_blocks], values[-1]
                if abs(prev - cur) <= tol * max(1.0, abs(cur)):
                    converged = True
                    break

        value, grad = eval_fn(dd, w, l1, l2)
        tel = telemetry_mod.current()
        if tel.enabled:
            tel.counter("solver_outer_iterations_total").inc(rounds)
            # Two fused reduces per round + the final exact evaluation.
            tel.counter("solver_allreduce_count").inc(2 * rounds + 1)
            tel.counter("solver_allreduce_bytes_total").inc(
                rounds * payload_bytes + (d + 1) * 4
            )
            tel.counter("solvers_sharded_solves_total").inc()
        return SolveResult(
            w=w,
            value=value,
            grad=grad,
            iterations=jnp.asarray(rounds, jnp.int32),
            converged=jnp.asarray(converged),
            values=jnp.asarray(values, jnp.float32),
            grad_norms=jnp.asarray(
                [abs(v) for v in np.diff(values)] or [0.0], jnp.float32
            ),
        )

    return solve_fn


def _register():
    from photon_ml_tpu.solvers import registry

    registry.register(registry.SolverDef(
        name="block_cd",
        kind="host",
        description=(
            "distributed block coordinate descent: drift-corrected local "
            "prox-Newton CD sweeps + two all-reduces per block round"
        ),
        supports_l1=True,
        sharded=make_sharded_solver,
    ))


_register()
