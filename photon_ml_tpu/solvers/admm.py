"""Consensus-ADMM for L1/elastic-net GLMs — one all-reduce per outer iteration.

The existing distributed solvers (L-BFGS / OWL-QN / TRON over ``shard_map``)
pay one fused ``psum`` per objective evaluation — several per line search,
dozens per solve.  Consensus ADMM (Boyd et al. §7.2; "Unwrapping ADMM"
/ PAPERS.md) restructures the solve so the only cross-shard communication is
ONE fixed-size all-reduce per OUTER iteration:

- **x-update** (per shard, zero communication): each shard s minimizes its
  local objective plus a proximal tie to the consensus,
  ``x_s = argmin f_s(x) + ρ/2·‖x − (z − u_s)‖²`` — warm-started local
  L-BFGS for any GLM loss, or (linear task) a CLOSED FORM through a cached
  eigendecomposition of the local Gram matrix: ``(G_s + ρI)x = b_s + ρv``
  solves as ``Q((Qᵀ(b_s + ρv)) / (Λ + ρ))``, the "transpose reduction"
  trick — the factorization is computed once per dataset and survives every
  outer iteration AND every adaptive-ρ change.
- **consensus z-update** (replicated): with the whole L1/L2 regularizer
  carried by z, the update is one soft-threshold,
  ``z = S_{λ₁·mask/(λ₂+Nρ)}(ρ·Σ_s(x̂_s + u_s)/(λ₂+Nρ))``, where
  ``x̂ = α·x + (1−α)·z`` is the over-relaxed iterate (α ∈ [1, 1.8]).
- **dual update** (per shard): ``u_s += x̂_s − z``.

The single all-reduce carries ``[Σ(x̂+u), Σx, ‖x‖², ‖u‖², f_s(x_s),
iters]`` — 2d+4 floats.  The exact primal residual falls out of the
identity ``Σ‖x_s − z‖² = Σ‖x_s‖² − 2⟨Σx_s, z⟩ + N‖z‖²``, so residual-based
stopping and adaptive ρ (μ/τ rule, with the scaled dual rescaled when ρ
changes) need nothing beyond that one reduce.  ρ is a TRACED argument of
the one compiled step program, so adaptation never recompiles.

Two sharding modes, same math: a real mesh (``shard_map`` + ``lax.psum``
over ``parallel.distributed.DATA_AXIS`` — multihost-ready, nothing here is
host-count-aware) when ≥2 devices participate, or LOGICAL shards (leading
shard axis + ``vmap`` x-updates + an axis-0 sum standing in for the psum)
on one device, so communication-per-iteration is measurable anywhere
(bench.py ``BENCH_ONLY=solvers``).

Chaos sites: ``distributed.allreduce`` fires before each step dispatch (the
reduce seam), ``admm.consensus`` after the consensus z-update commits (the
outer-iteration boundary).  A kill at either resumes bitwise through the
GridCheckpointer: the in-flight λ re-solves deterministically from the same
warm start (docs/robustness.md).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, SolveResult, lbfgs_solve
from photon_ml_tpu.optim.owlqn import _pseudo_gradient

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class ADMMOptions:
    """Knobs, settable via ``OptimizerConfig.solver_options`` (docs/solvers.md).

    ``max_outer`` of 0 defers to ``OptimizerConfig.max_iters``; likewise
    ``abstol`` of 0 defers to ``OptimizerConfig.tolerance``."""

    rho: float = 1.0  # initial penalty
    adaptive_rho: bool = True
    mu: float = 10.0  # residual-imbalance trigger (Boyd §3.4.1)
    tau: float = 2.0  # ρ scale factor on trigger
    over_relaxation: float = 1.5  # α ∈ [1.0, 1.8]
    abstol: float = 0.0
    reltol: float = 1e-4
    max_outer: int = 0
    local_solver: str = "auto"  # auto | lbfgs | ridge
    local_max_iters: int = 25  # L-BFGS subproblem budget
    local_tolerance: float = 1e-8
    shards: int = 0  # logical-shard count (0 = auto; sharded.py reads it)

    @classmethod
    def from_options(cls, options: dict) -> "ADMMOptions":
        fields = {f.name: f.type for f in dataclasses.fields(cls)}
        unknown = sorted(set(options) - set(fields))
        if unknown:
            raise ValueError(
                f"unknown admm solver_options {unknown}; valid: {sorted(fields)}"
            )
        coerced = {}
        for key, val in options.items():
            if key == "local_solver":
                coerced[key] = str(val)
            elif key == "adaptive_rho":
                coerced[key] = bool(val)
            elif key in ("max_outer", "local_max_iters", "shards"):
                coerced[key] = int(val)
            else:
                coerced[key] = float(val)
        opts = cls(**coerced)
        if opts.local_solver not in ("auto", "lbfgs", "ridge"):
            raise ValueError(
                f"admm local_solver must be auto|lbfgs|ridge, got "
                f"{opts.local_solver!r}"
            )
        if not 1.0 <= opts.over_relaxation <= 1.8:
            raise ValueError(
                "admm over_relaxation must lie in [1.0, 1.8] "
                f"(got {opts.over_relaxation})"
            )
        return opts


def _soft_threshold(t: Array, thresh: Array) -> Array:
    return jnp.sign(t) * jnp.maximum(jnp.abs(t) - thresh, 0.0)


def make_sharded_solver(problem, dist, mesh, l1_mask=None):
    """Registry ``sharded`` factory: bind (problem, sharded data, mesh) once,
    return ``solve_fn(lam, w_prev, dist_override=None) → SolveResult``.

    ``dist`` is a ``parallel.distributed.DistributedGlmData`` (every array
    carrying a leading shard axis); ``mesh`` is a 1-D device mesh over
    ``DATA_AXIS`` for real SPMD execution, or None for logical shards on
    the default device.  ``dist_override`` lets callers swap the dataset
    (same shapes) per solve without recompiling — the GAME fixed-effect
    coordinate re-slots its per-iteration offsets this way."""
    from photon_ml_tpu.parallel.compat import shard_map
    from photon_ml_tpu.parallel.distributed import DATA_AXIS
    from photon_ml_tpu.solvers import registry as registry_mod

    obj = problem.objective
    cfg = problem.config
    opt = cfg.optimizer
    opts = ADMMOptions.from_options(registry_mod.solver_options_dict(opt))
    max_outer = opts.max_outer or opt.max_iters
    abstol = opts.abstol or opt.tolerance
    l1_frac = cfg.regularization.l1_weight(1.0)
    l2_frac = cfg.regularization.l2_weight(1.0)
    alpha = opts.over_relaxation

    n = dist.n_shards
    d = int(dist.data.features.shape[-1])
    mask = (
        jnp.ones((d,), jnp.float32)
        if l1_mask is None
        else jnp.asarray(l1_mask, jnp.float32)
    )
    use_ridge = opts.local_solver == "ridge" or (
        opts.local_solver == "auto" and problem.task == "squared"
    )
    if use_ridge and problem.task != "squared":
        raise ValueError(
            "admm local_solver='ridge' needs the linear (squared) task "
            "(the closed "
            f"form assumes a quadratic objective); task is {problem.task!r}"
        )
    local_cfg = LBFGSConfig(
        max_iters=opts.local_max_iters,
        tolerance=opts.local_tolerance,
        history=opt.history,
    )

    # -- per-shard pieces (pure; run under shard_map OR vmap) ---------------
    def x_update_lbfgs(local, x_prev, v, rho):
        def vg(w):
            val, g = obj.raw_value_and_grad(w, local)
            dw = w - v
            return val + 0.5 * rho * jnp.vdot(dw, dw), g + rho * dw

        res = lbfgs_solve(vg, x_prev, local_cfg)
        dw = res.w - v
        f_loc = res.value - 0.5 * rho * jnp.vdot(dw, dw)
        return res.w, res.iterations.astype(jnp.float32), f_loc

    def ridge_prep(local):
        zero = jnp.zeros((d,), jnp.float32)
        c, g0 = obj.raw_value_and_grad(zero, local)
        d2w = obj.d2_weights(zero, local)
        gram = jax.vmap(
            lambda e: obj.raw_hvp(zero, e, local, d2w)
        )(jnp.eye(d, dtype=jnp.float32))
        evals, q = jnp.linalg.eigh(gram)
        return q, evals, -g0, c

    def x_update_ridge(prep, v, rho):
        q, evals, b, c = prep
        x = q @ ((q.T @ (b + rho * v)) / (evals + rho))
        gx = q @ (evals * (q.T @ x))
        f_loc = 0.5 * jnp.vdot(x, gx) - jnp.vdot(b, x) + c
        return x, jnp.ones((), jnp.float32), f_loc

    def shard_step(solve_local, xl, ul, z, rho):
        """x-update + over-relaxation + the shard's psum payload."""
        x_new, iters, f_loc = solve_local(z - ul, rho)
        xh = alpha * x_new + (1.0 - alpha) * z
        scalars = jnp.stack([
            jnp.vdot(x_new, x_new), jnp.vdot(ul, ul), f_loc, iters,
        ])
        return x_new, xh, jnp.concatenate([xh + ul, x_new, scalars])

    def consensus(tot, z_prev, rho, l1, l2):
        """z-update + residuals from the reduced payload (replicated)."""
        p_sum, x_sum = tot[:d], tot[d : 2 * d]
        sum_x2, sum_u2 = tot[2 * d], tot[2 * d + 1]
        f_sum, iters_sum = tot[2 * d + 2], tot[2 * d + 3]
        denom = l2 + rho * n
        z = _soft_threshold(rho * p_sum / denom, (l1 / denom) * mask)
        r2 = jnp.maximum(
            sum_x2 - 2.0 * jnp.vdot(x_sum, z) + n * jnp.vdot(z, z), 0.0
        )
        obj_proxy = (
            f_sum
            + l1 * jnp.sum(jnp.abs(z) * mask)
            + 0.5 * l2 * jnp.vdot(z, z)
        )
        stats = jnp.stack([
            obj_proxy, r2, jnp.linalg.norm(z - z_prev), sum_x2, sum_u2,
            iters_sum, jnp.linalg.norm(z),
        ])
        return z, stats

    # -- the ONE compiled step program (+ one final exact evaluation) -------
    if mesh is not None:
        spec_data = jax.sharding.PartitionSpec(DATA_AXIS)
        spec_repl = jax.sharding.PartitionSpec()

        def spmd_step(dd, prep, x, u, z, rho, l1, l2):
            local = dd.local() if prep is None else None
            solve_local = (
                (lambda v, r: x_update_ridge(
                    jax.tree.map(lambda a: a[0], prep), v, r))
                if use_ridge
                else (lambda v, r: x_update_lbfgs(local, x[0], v, r))
            )
            x_new, xh, payload = shard_step(solve_local, x[0], u[0], z, rho)
            tot = lax.psum(payload, DATA_AXIS)
            z_new, stats = consensus(tot, z, rho, l1, l2)
            u_new = u[0] + xh - z_new
            return x_new[None], u_new[None], z_new, stats

        def _make_step(prep_in_spec):
            return jax.jit(shard_map(
                spmd_step,
                mesh=mesh,
                in_specs=(
                    spec_data, prep_in_spec, spec_data, spec_data,
                    spec_repl, spec_repl, spec_repl, spec_repl,
                ),
                out_specs=(spec_data, spec_data, spec_repl, spec_repl),
                check_vma=False,
            ))

        step_lbfgs = None if use_ridge else _make_step(spec_repl)
        step_ridge = _make_step(spec_data) if use_ridge else None

        def spmd_prep(dd):
            q, evals, b, c = ridge_prep(dd.local())
            return q[None], evals[None], b[None], c[None]

        prep_fn = jax.jit(shard_map(
            spmd_prep,
            mesh=mesh,
            in_specs=(spec_data,),
            out_specs=(spec_data,) * 4,
            check_vma=False,
        )) if use_ridge else None

        def spmd_eval(dd, z, l1, l2):
            val, grad = obj.raw_value_and_grad(z, dd.local())
            val, grad = lax.psum((val, grad), DATA_AXIS)
            val = (
                val + l1 * jnp.sum(jnp.abs(z) * mask)
                + 0.5 * l2 * jnp.vdot(z, z)
            )
            return val, _pseudo_gradient(z, grad + l2 * z, l1, mask)

        eval_fn = jax.jit(shard_map(
            spmd_eval,
            mesh=mesh,
            in_specs=(spec_data, spec_repl, spec_repl, spec_repl),
            out_specs=(spec_repl, spec_repl),
            check_vma=False,
        ))

        def spmd_local_grad(dd, z):
            return obj.raw_value_and_grad(z, dd.local())[1][None]

        # Shard-local gradients, NO collective: each device keeps its row.
        local_grad_fn = jax.jit(shard_map(
            spmd_local_grad,
            mesh=mesh,
            in_specs=(spec_data, spec_repl),
            out_specs=spec_data,
            check_vma=False,
        ))
    else:
        def logical_step(dd, prep, x, u, z, rho, l1, l2):
            if use_ridge:
                one = lambda pr, xl, ul: shard_step(
                    lambda v, r: x_update_ridge(pr, v, r), xl, ul, z, rho
                )
                x_new, xh, payload = jax.vmap(one)(prep, x, u)
            else:
                one = lambda local, xl, ul: shard_step(
                    lambda v, r: x_update_lbfgs(local, xl, v, r), xl, ul,
                    z, rho,
                )
                x_new, xh, payload = jax.vmap(one)(dd.data, x, u)
            tot = jnp.sum(payload, axis=0)  # the psum's stand-in
            z_new, stats = consensus(tot, z, rho, l1, l2)
            u_new = u + xh - z_new
            return x_new, u_new, z_new, stats

        step_jit = jax.jit(logical_step)
        step_lbfgs = None if use_ridge else step_jit
        step_ridge = step_jit if use_ridge else None
        prep_fn = jax.jit(
            lambda dd: jax.vmap(ridge_prep)(dd.data)
        ) if use_ridge else None

        def logical_eval(dd, z, l1, l2):
            vals, grads = jax.vmap(
                lambda local: obj.raw_value_and_grad(z, local)
            )(dd.data)
            val = jnp.sum(vals)
            grad = jnp.sum(grads, axis=0)
            val = (
                val + l1 * jnp.sum(jnp.abs(z) * mask)
                + 0.5 * l2 * jnp.vdot(z, z)
            )
            return val, _pseudo_gradient(z, grad + l2 * z, l1, mask)

        eval_fn = jax.jit(logical_eval)

        local_grad_fn = jax.jit(lambda dd, z: jax.vmap(
            lambda local: obj.raw_value_and_grad(z, local)[1]
        )(dd.data))

    payload_bytes = (2 * d + 4) * 4
    prep_cache: dict[int, tuple] = {}

    def solve_fn(lam, w_prev, dist_override=None) -> SolveResult:
        dd = dist if dist_override is None else dist_override
        l1 = jnp.asarray(l1_frac * float(lam), jnp.float32)
        l2 = jnp.asarray(l2_frac * float(lam), jnp.float32)
        if w_prev is None:
            w_prev = jnp.zeros((d,), jnp.float32)
        prep = None
        if use_ridge:
            # The Gram factorization is cached for the BOUND dataset (it
            # survives every λ of a grid and every ρ change); an override
            # (GAME's per-iteration offsets shift b and c) re-runs the
            # one-time prep program for its own data.
            if dist_override is None:
                prep = prep_cache.get("default")
                if prep is None:
                    prep = prep_cache["default"] = prep_fn(dist)
            else:
                prep = prep_fn(dd)
        step = step_ridge if use_ridge else step_lbfgs

        z = jnp.asarray(w_prev, jnp.float32)
        x = jnp.broadcast_to(z, (n, d)) + jnp.zeros((n, d), jnp.float32)
        rho = float(opts.rho)
        # Warm dual: at the consensus fixed point u*_s = -grad f_s(z*)/rho
        # (x-update stationarity at x=z), so seeding the duals from the
        # shard-local gradients at z0 removes the cold-dual transient.
        # Deterministic in (data, z0, rho) -> bitwise-safe under resume.
        u = -local_grad_fn(dd, z) / jnp.asarray(rho, jnp.float32)
        values, rnorms = [], []
        rounds = 0
        converged = False
        r = s = float("inf")
        local_iters = 0.0
        for k in range(max_outer):
            # The reduce seam: the step program about to run carries this
            # iteration's single all-reduce (docs/robustness.md).
            chaos_mod.maybe_fail(
                "distributed.allreduce", solver="admm", outer=k
            )
            x, u, z_new, stats = step(
                dd, prep, x, u, z, jnp.asarray(rho, jnp.float32), l1, l2
            )
            stats = np.asarray(stats, np.float64)
            (obj_proxy, r2, dz, sum_x2, sum_u2,
             iters_sum, znorm) = stats.tolist()
            rounds = k + 1
            local_iters += iters_sum
            r = float(np.sqrt(r2))
            s = rho * float(np.sqrt(n)) * dz
            values.append(obj_proxy)
            rnorms.append(r)
            z = z_new
            # The consensus commit: z is adopted; a kill here loses only
            # the in-flight λ, which re-solves deterministically on resume.
            chaos_mod.maybe_fail(
                "admm.consensus", solver="admm", outer=k, rho=rho
            )
            eps_pri = (
                np.sqrt(n * d) * abstol
                + opts.reltol * max(np.sqrt(sum_x2), np.sqrt(n) * znorm)
            )
            eps_dual = (
                np.sqrt(n * d) * abstol
                + opts.reltol * rho * np.sqrt(sum_u2)
            )
            if r <= eps_pri and s <= eps_dual:
                converged = True
                break
            if opts.adaptive_rho:
                # μ/τ imbalance rule; the SCALED dual u = y/ρ rescales
                # inversely with ρ (Boyd §3.4.1).
                if r > opts.mu * s:
                    rho *= opts.tau
                    u = u / opts.tau
                elif s > opts.mu * r:
                    rho /= opts.tau
                    u = u * opts.tau

        value, grad = eval_fn(dd, z, l1, l2)
        tel = telemetry_mod.current()
        if tel.enabled:
            tel.counter("solver_outer_iterations_total").inc(rounds)
            # One reduce per outer round + the final exact evaluation.
            tel.counter("solver_allreduce_count").inc(rounds + 1)
            tel.counter("solver_allreduce_bytes_total").inc(
                rounds * payload_bytes + (d + 1) * 4
            )
            tel.gauge("solver_consensus_residual").set(r)
            tel.counter("solvers_sharded_solves_total").inc()
        return SolveResult(
            w=z,
            value=value,
            grad=grad,
            iterations=jnp.asarray(rounds, jnp.int32),
            converged=jnp.asarray(converged),
            values=jnp.asarray(values, jnp.float32),
            grad_norms=jnp.asarray(rnorms, jnp.float32),
        )

    return solve_fn


def _register():
    from photon_ml_tpu.solvers import registry

    registry.register(registry.SolverDef(
        name="admm",
        kind="host",
        description=(
            "consensus ADMM: per-shard subproblems + soft-threshold "
            "consensus, one all-reduce per outer iteration"
        ),
        supports_l1=True,
        sharded=make_sharded_solver,
    ))


_register()
