"""Publication-based model distribution: cold start + catch-up over
the wire, no shared filesystem anywhere on the serving path.

PRs 12/13 built the freshness root — journaled snapshot/delta
publications with per-subscriber acks — but every subscriber so far
reads the root's DIRECTORY.  Cross-machine fleets have no shared
directory.  This module puts an HTTP transport around the root:

- :class:`PublicationServer` — serves a freshness root read-only over
  HTTP: ``GET /publications`` (the committed journal view,
  ``read_publications``), ``GET /blob/<seq>/<relpath>`` (raw artifact
  bytes, traversal-guarded), plus the ack sidecar as POSTs
  (``/ack``, ``/unregister``) so remote subscribers participate in
  retention exactly like local ones.
- :class:`PublicationClient` — the pull side: list publications, then
  ``fetch`` one into a local cache dir — manifest FIRST, verified
  against the journal's ``manifest_sha256`` (so a tampered or torn
  server-side artifact is refused before any payload downloads), then
  every listed file with its own sha256 check, staged and atomically
  renamed.  End-to-end the checksums chain journal -> manifest ->
  file bytes; a mismatch anywhere refuses the artifact.  Transient
  download failures retry per file (``cluster.fetch`` chaos seam).
- :func:`cold_start` — a brand-new host's bootstrap: newest committed
  SNAPSHOT publication (deltas patch a base; a cold host has none),
  fetched and verified, returns the local model dir + the snapshot
  seq to resume catching up from.  A root with no snapshot is a
  pointed error naming the fix (``publish_snapshot``).
- :class:`RemoteApplier` — ``DeltaApplier``'s contract over the wire:
  apply every newly-committed publication in sequence order (deltas
  via the delta reload path, snapshots via full reload), ack the
  high-water seq through the server, never retry a failed apply.

Metric family: ``cluster_*``.  Chaos seam: ``cluster.fetch`` fires
per blob download (a fault is a dropped transfer — the client
retries; exhausted retries fail the fetch, which cold start/apply
surface).  docs/serving.md "Cluster" has the cold-start walkthrough.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.freshness.delta import (
    MANIFEST_FILE,
    DeltaError,
    _manifest_digest,
)
from photon_ml_tpu.freshness.publisher import (
    SNAPSHOT_MANIFEST,
    SNAPSHOT_MODEL_DIR,
    Publication,
    read_publications,
    remove_ack,
    write_ack,
)


class FetchError(RuntimeError):
    """A publication could not be fetched/verified over the wire."""


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class PublicationServer:
    """Serve a freshness root over HTTP, read-only plus the ack
    sidecar.  The root's PUBLISHER stays wherever the training loop
    runs; this server is just the wire in front of its directory."""

    def __init__(self, root: str):
        self.root = root
        self._server = None
        self._thread: Optional[threading.Thread] = None

    def serve(
        self, host: str = "127.0.0.1", port: int = 0
    ) -> "PublicationServer":
        if self._server is not None:
            return self
        server = _PubServer((host, port), _PubHandler)
        server.pub_root = self.root
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            name="cluster-publication-http", daemon=True,
        )
        self._thread.start()
        return self

    @property
    def base_url(self) -> str:
        if self._server is None:
            raise RuntimeError("publication server is not serving")
        h, p = self._server.server_address[:2]
        return f"http://{h}:{p}"

    def close(self, timeout: float = 5.0) -> None:
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=timeout)


class _PubServer(ThreadingHTTPServer):
    daemon_threads = True
    pub_root: str


class _PubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_bytes(self, payload: bytes) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 — stdlib casing
        root = self.server.pub_root
        if self.path == "/publications":
            pubs = read_publications(root)
            self._send_json(200, {"publications": [
                {
                    "seq": p.seq,
                    "kind": p.kind,
                    "manifest_sha256": p.manifest_sha256,
                    "event_wall_epoch": p.event_wall_epoch,
                    "n_changed_rows": p.n_changed_rows,
                    "publish_wall_epoch": p.publish_wall_epoch,
                    "dir": os.path.basename(p.path),
                }
                for p in pubs
            ]})
            return
        if self.path == "/healthz":
            self._send_json(200, {
                "status": "ok",
                "publications": len(read_publications(root)),
            })
            return
        if self.path.startswith("/blob/"):
            self._do_blob(root, self.path[len("/blob/"):])
            return
        self._send_json(404, {"error": f"no route {self.path}"})

    def _do_blob(self, root: str, rest: str) -> None:
        # /blob/<seq>/<relpath>: only files inside a COMMITTED
        # publication's directory are served — the journal, staging
        # dirs, and anything path-traversal can reach are refused.
        seq_s, _, relpath = rest.partition("/")
        try:
            seq = int(seq_s)
        except ValueError:
            self._send_json(400, {"error": f"bad seq {seq_s!r}"})
            return
        pub = next(
            (p for p in read_publications(root) if p.seq == seq), None
        )
        if pub is None:
            self._send_json(
                404, {"error": f"no committed publication seq {seq}"}
            )
            return
        base = os.path.realpath(pub.path)
        full = os.path.realpath(os.path.join(base, relpath))
        if not (full == base or full.startswith(base + os.sep)):
            self._send_json(
                403, {"error": f"path {relpath!r} escapes the artifact"}
            )
            return
        try:
            with open(full, "rb") as f:
                payload = f.read()
        except (FileNotFoundError, IsADirectoryError):
            self._send_json(
                404, {"error": f"no file {relpath!r} in seq {seq}"}
            )
            return
        tel = telemetry_mod.current()
        tel.counter("cluster_blob_requests_total").inc()
        tel.counter("cluster_blob_bytes_total").inc(len(payload))
        self._send_bytes(payload)

    def do_POST(self) -> None:  # noqa: N802 — stdlib casing
        root = self.server.pub_root
        n = int(self.headers.get("Content-Length") or 0)
        try:
            payload = json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError as exc:
            self._send_json(400, {"error": f"bad JSON body: {exc}"})
            return
        subscriber = payload.get("subscriber_id")
        if not subscriber:
            self._send_json(400, {"error": "subscriber_id is required"})
            return
        if self.path == "/ack":
            try:
                write_ack(root, subscriber, int(payload.get("seq", 0)))
            except (TypeError, ValueError) as exc:
                self._send_json(400, {"error": str(exc)})
                return
            telemetry_mod.current().counter("cluster_acks_total").inc()
            self._send_json(200, {"ok": True})
        elif self.path == "/unregister":
            try:
                removed = remove_ack(root, subscriber)
            except ValueError as exc:
                self._send_json(400, {"error": str(exc)})
                return
            self._send_json(200, {"ok": True, "removed": removed})
        else:
            self._send_json(404, {"error": f"no route {self.path}"})


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

def _http_get(url: str, timeout_s: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        if resp.status != 200:
            raise FetchError(f"GET {url} -> HTTP {resp.status}")
        return resp.read()


class PublicationClient:
    """Pull publications from a :class:`PublicationServer` into a
    local cache, checksum-verified end to end."""

    def __init__(
        self,
        base_url: str,
        cache_dir: str,
        timeout_s: float = 30.0,
        retries: int = 2,
    ):
        self.base_url = str(base_url).rstrip("/")
        self.cache_dir = cache_dir
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.fetches = 0
        self.fetch_retries = 0
        os.makedirs(cache_dir, exist_ok=True)

    # -- listing ------------------------------------------------------------
    def publications(self) -> List[Publication]:
        raw = _http_get(
            self.base_url + "/publications", self.timeout_s
        )
        out = []
        for p in json.loads(raw)["publications"]:
            out.append(Publication(
                seq=int(p["seq"]),
                path=p["dir"],  # server-relative name; fetch localizes
                manifest_sha256=p["manifest_sha256"],
                event_wall_epoch=p.get("event_wall_epoch"),
                n_changed_rows=int(p.get("n_changed_rows", 0)),
                publish_wall_epoch=p["publish_wall_epoch"],
                kind=p.get("kind", "delta"),
            ))
        return out

    # -- fetching -----------------------------------------------------------
    def _local_dir(self, pub: Publication) -> str:
        return os.path.join(
            self.cache_dir, f"{pub.kind}-{pub.seq:06d}"
        )

    def _get_blob(self, pub: Publication, relpath: str) -> bytes:
        """One artifact file over the wire, with per-file retry: a
        transient drop (the ``cluster.fetch`` seam) re-requests the
        SAME file; checksums downstream make re-reads safe."""
        url = f"{self.base_url}/blob/{pub.seq}/{relpath}"
        tel = telemetry_mod.current()
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                # The transfer seam: a fault is this blob's download
                # dropped mid-flight (docs/robustness.md).
                chaos_mod.maybe_fail(
                    "cluster.fetch", seq=pub.seq, file=relpath,
                )
                return _http_get(url, self.timeout_s)
            except Exception as exc:  # noqa: BLE001 — retry transfers
                last = exc
                if attempt < self.retries:
                    self.fetch_retries += 1
                    tel.counter("cluster_fetch_retries").inc()
        tel.counter("cluster_fetch_failures_total").inc()
        raise FetchError(
            f"blob {relpath} of seq {pub.seq} failed after "
            f"{self.retries + 1} attempts: {last}"
        ) from last

    def _manifest_and_files(
        self, pub: Publication
    ) -> Tuple[str, bytes, Dict[str, dict]]:
        """Download + verify the manifest; returns ``(manifest_name,
        manifest_bytes, {relpath: {"sha256", "nbytes"}})``.  The
        manifest's self-digest must equal the JOURNAL's recorded
        digest — the end-to-end anchor: a server whose artifact
        diverged from its journal is refused here, before any payload
        moves."""
        name = (
            SNAPSHOT_MANIFEST if pub.kind == "snapshot"
            else MANIFEST_FILE
        )
        raw = self._get_blob(pub, name)
        try:
            manifest = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise FetchError(
                f"seq {pub.seq}: unparseable manifest {name}: {exc}"
            ) from exc
        digest = _manifest_digest(manifest)
        if digest != pub.manifest_sha256 or \
                digest != manifest.get("manifest_sha256"):
            raise FetchError(
                f"seq {pub.seq}: manifest digest mismatch (journal "
                f"{pub.manifest_sha256[:16]}…, computed {digest[:16]}…)"
                " — the artifact diverged from the journal; refuse"
            )
        if pub.kind == "snapshot":
            files = {
                rel: {"sha256": e["sha256"], "nbytes": e["nbytes"]}
                for rel, e in manifest["files"].items()
            }
        else:
            files = {
                c["file"]: {"sha256": c["sha256"], "nbytes": c["nbytes"]}
                for c in manifest["coordinates"]
                if c.get("file")
            }
        return name, raw, files

    def fetch(self, pub: Publication) -> str:
        """Materialize one publication into the local cache; returns
        the local artifact directory (same layout as the root's).
        Idempotent: an already-fetched seq returns its cached dir
        without touching the wire (the atomic rename below is the
        completeness marker)."""
        final = self._local_dir(pub)
        if os.path.isdir(final):
            return final
        t0 = time.perf_counter()
        tel = telemetry_mod.current()
        name, raw_manifest, files = self._manifest_and_files(pub)
        staging = final + ".staging"
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        total = 0
        try:
            for relpath, entry in sorted(files.items()):
                payload = self._get_blob(pub, relpath)
                actual = hashlib.sha256(payload).hexdigest()
                if actual != entry["sha256"]:
                    raise FetchError(
                        f"seq {pub.seq} file {relpath}: sha256 "
                        f"mismatch (wire {actual[:16]}…, manifest "
                        f"{entry['sha256'][:16]}…) — transfer "
                        "corrupted or server tampered; refuse"
                    )
                dest = os.path.join(staging, relpath)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                with open(dest, "wb") as f:
                    f.write(payload)
                total += len(payload)
            with open(os.path.join(staging, name), "wb") as f:
                f.write(raw_manifest)
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        if os.path.isdir(final):
            # A concurrent fetch won the rename; ours is redundant.
            shutil.rmtree(staging)
        else:
            os.replace(staging, final)
        self.fetches += 1
        tel.counter("cluster_fetches_total").inc()
        tel.counter("cluster_fetch_bytes_total").inc(
            total + len(raw_manifest)
        )
        tel.histogram("cluster_fetch_seconds").observe(
            time.perf_counter() - t0
        )
        return final

    # -- ack sidecar over the wire ------------------------------------------
    def _post(self, route: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + route, data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read() or b"{}")

    def ack(self, subscriber_id: str, seq: int) -> None:
        self._post("/ack", {"subscriber_id": subscriber_id, "seq": seq})

    def unregister(self, subscriber_id: str) -> bool:
        out = self._post(
            "/unregister", {"subscriber_id": subscriber_id}
        )
        return bool(out.get("removed"))


# ---------------------------------------------------------------------------
# Cold start + remote catch-up
# ---------------------------------------------------------------------------

def cold_start(
    client: PublicationClient,
    subscriber_id: Optional[str] = None,
) -> Tuple[str, Publication]:
    """Bootstrap a host with NO local model state: fetch the newest
    committed snapshot publication and return ``(local_model_dir,
    snapshot_publication)`` — load the dir, then hand the seq to a
    :class:`RemoteApplier` to catch up by deltas.  Registers
    ``subscriber_id``'s ack at the snapshot seq when given, so
    retention holds every delta this host still needs."""
    snapshots = [
        p for p in client.publications() if p.kind == "snapshot"
    ]
    if not snapshots:
        raise DeltaError(
            "cold start needs a snapshot publication and the root has "
            "none — deltas patch a base a cold host does not have; "
            "run DeltaPublisher.publish_snapshot(model_dir) on the "
            "publisher side first"
        )
    newest = max(snapshots, key=lambda p: p.seq)
    local = client.fetch(newest)
    if subscriber_id is not None:
        client.ack(subscriber_id, newest.seq)
    telemetry_mod.current().counter("cluster_cold_starts_total").inc()
    telemetry_mod.current().event(
        "cluster.cold_start",
        seq=newest.seq, subscriber_id=subscriber_id,
    )
    return os.path.join(local, SNAPSHOT_MODEL_DIR), newest


class RemoteApplier:
    """:class:`~photon_ml_tpu.freshness.applier.DeltaApplier`'s
    contract, over the wire: poll the publication server, fetch every
    newly-committed publication (checksum-verified), apply in sequence
    order — deltas via the service's delta reload, snapshots via full
    reload — and ack the high-water seq through the server.  A failed
    apply is recorded and NEVER retried (same reasoning as the local
    applier: a deterministic failure repeats; the runbook escalates to
    a fresh cold start)."""

    def __init__(
        self,
        service,
        client: PublicationClient,
        subscriber_id: str,
        start_seq: int = 0,
        poll_interval_s: float = 0.25,
    ):
        self._service = service
        self.client = client
        self.subscriber_id = str(subscriber_id)
        self.applied_seq = int(start_seq)
        self.poll_interval_s = float(poll_interval_s)
        self.applied = 0
        self.failed: List[int] = []
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> list:
        """Fetch + apply every pending publication; returns their
        SwapResults.  Listing failures (server briefly down) return
        empty — the next poll catches up."""
        try:
            pending = [
                p for p in self.client.publications()
                if p.seq > self.applied_seq
            ]
        except Exception as exc:  # noqa: BLE001 — degrade, never die
            telemetry_mod.current().event(
                "cluster.applier_poll_failed",
                subscriber_id=self.subscriber_id,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            return []
        results = []
        seq_before = self.applied_seq
        tel = telemetry_mod.current()
        for pub in sorted(pending, key=lambda p: p.seq):
            try:
                local = self.client.fetch(pub)
                if pub.kind == "snapshot":
                    result = self._service.reload(
                        os.path.join(local, SNAPSHOT_MODEL_DIR)
                    )
                else:
                    result = self._service.reload(local, mode="delta")
            except Exception as exc:  # noqa: BLE001 — never retried
                self.failed.append(pub.seq)
                self.applied_seq = pub.seq
                tel.event(
                    "cluster.apply_failed",
                    subscriber_id=self.subscriber_id,
                    seq=pub.seq,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
                continue
            results.append(result)
            self.applied_seq = pub.seq
            if result.status == "swapped":
                self.applied += 1
            else:
                self.failed.append(pub.seq)
                tel.event(
                    "cluster.apply_failed",
                    subscriber_id=self.subscriber_id,
                    seq=pub.seq,
                    stage=result.stage,
                    reason=result.reason,
                )
        if self.applied_seq > seq_before:
            try:
                self.client.ack(self.subscriber_id, self.applied_seq)
            except Exception:  # noqa: BLE001 — next advance re-acks
                pass
        return results

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "RemoteApplier":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run,
                name=f"cluster-applier-{self.subscriber_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — keep polling
                pass
            self._stop_evt.wait(self.poll_interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)

    def stats(self) -> dict:
        return {
            "subscriber_id": self.subscriber_id,
            "applied_seq": self.applied_seq,
            "applied": self.applied,
            "failed": list(self.failed),
        }
