"""Cluster CLI: the 3-host control-plane drill + registry utilities.

Selfcheck (device-free beyond the CPU backend, CI-greppable)::

    python -m photon_ml_tpu.cluster --selfcheck

replays the cluster drill from docs/serving.md "Cluster" against real
HTTP on localhost — 2 warm hosts plus 1 cold one, a replicated quota
coordinator, a membership registry, and a publication server — and
gates:

- **coordinator kill**: the leader replica dies mid-phase under
  >= 120 rps open-loop load; hosts ride the degrade-to-last-lease
  contract, a follower claims the leader lease and replays the grant
  journal, and leadership moves within ~one quota lease TTL.
  Over-admission for the over-subscribed tenant stays within one
  lease window of its fleet budget; ZERO failed requests.
- **host join + drain**: a host with NO local model state cold-starts
  over the wire from the newest committed snapshot publication
  (checksums verified end-to-end), registers, and is joined into the
  router by the MembershipWatcher; a veteran host drains via the
  registry.  ZERO failed requests, ZERO rejections for the in-quota
  tenant through both transitions — and the cold host's scores are
  BIT-IDENTICAL to in-process scoring of the source model.
- the aggregator's host set follows membership: the drained host's
  series are marked departed once it leaves, never summed forever.

Registry utilities (the ops surface the runbooks in ops/README.md
drive)::

    python -m photon_ml_tpu.cluster --serve-registry --port 7000
    python -m photon_ml_tpu.cluster --registry http://HOST:7000 --members
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.cluster",
        description="cluster control plane: drill selfcheck + registry",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument(
        "--output-dir",
        help="telemetry output dir (selfcheck defaults to a tempdir)",
    )
    p.add_argument(
        "--rate", type=float, default=150.0,
        help="open-loop rps the drill offers (the gate floor is 120)",
    )
    p.add_argument(
        "--lease-ttl", type=float, default=1.0,
        help="quota lease TTL seconds; the failover bound scales with it",
    )
    p.add_argument(
        "--serve-registry", action="store_true",
        help="run a standalone membership registry until Ctrl-C",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--heartbeat-ttl", type=float, default=2.0,
        help="registry heartbeat TTL seconds (--serve-registry)",
    )
    p.add_argument(
        "--registry", metavar="URL",
        help="membership registry base URL for --members",
    )
    p.add_argument(
        "--members", action="store_true",
        help="print the registry's current member set as JSON and exit",
    )
    return p


# ---------------------------------------------------------------------------
# The drill
# ---------------------------------------------------------------------------

def run_cluster_drill(
    out_dir: str,
    drill_rate: float = 150.0,
    lease_ttl_s: float = 1.0,
) -> list[str]:
    """The 3-host cluster drill (module docstring has the gates).
    Returns failure strings (empty = pass)."""
    import time

    import numpy as np

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.cluster.coordination import (
        CoordinatorReplica,
        ReplicatedQuotaCoordinator,
    )
    from photon_ml_tpu.cluster.distribution import (
        PublicationClient,
        PublicationServer,
        cold_start,
    )
    from photon_ml_tpu.cluster.membership import (
        HeartbeatAgent,
        MembershipRegistry,
        MembershipWatcher,
        RegistryClient,
    )
    from photon_ml_tpu.freshness.publisher import DeltaPublisher
    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.fleet import FleetBudget, FleetRouter, LocalHost
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload
    from photon_ml_tpu.serving.tenancy import TenancyConfig, TenantSpec
    from photon_ml_tpu.telemetry.fleet import FleetAggregator

    failures: list[str] = []
    n_hosts = 3                 # 2 warm + 1 cold joiner
    acme_budget_rps = 600.0     # in-quota tenant: the zero-shed gates
    metered_budget_rps = 60.0   # over-subscribed: the admission bound
    burst_s = 0.25
    heartbeat_ttl_s = max(1.0, lease_ttl_s)
    workload = SyntheticWorkload(n_entities=64, seed=11)
    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)
    # Static specs = the pre-lease defaults: each tenant's per-host
    # slice of its fleet budget.  acme's slice is sized so the SURVIVING
    # hosts absorb the drill rate in-quota even mid-drain.
    tenancy = TenancyConfig(tenants=(
        TenantSpec(
            name="acme",
            quota_rps=acme_budget_rps / n_hosts,
            burst=max(acme_budget_rps * burst_s / n_hosts, 1.0),
            max_queue=256,
        ),
        TenantSpec(
            name="metered",
            quota_rps=metered_budget_rps / n_hosts,
            burst=max(metered_budget_rps * burst_s / n_hosts, 1.0),
            max_queue=256,
        ),
    ))
    batcher_cfg = BatcherConfig(
        max_batch_size=8, max_wait_us=2_000, max_queue=512,
        tenancy=tenancy,
    )

    def build_service() -> ScoringService:
        return ScoringService(
            ScoringRuntime(workload.model, workload.index_maps, rt_cfg),
            batcher_cfg,
        )

    def make_request(i: int, phase, tenant: str) -> dict:
        obj = dict(workload.request(i))
        obj["tenant"] = tenant
        return obj

    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="cluster-selfcheck"
    ) as tel:
        # The publication the cold host pulls: snapshot the source model
        # into the freshness root and serve that root over HTTP.
        model_dir = os.path.join(out_dir, "models", "v1")
        save_game_model(workload.model, workload.index_maps, model_dir)
        pub_root = os.path.join(out_dir, "pubs")
        publisher = DeltaPublisher(pub_root)
        snap_pub = publisher.publish_snapshot(model_dir)
        pub_server = PublicationServer(pub_root)
        pub_server.serve()

        registry = MembershipRegistry(heartbeat_ttl_s=heartbeat_ttl_s)
        registry.serve()
        reg_client = RegistryClient(registry.base_url)

        # Two coordinator replicas over ONE durable store (the
        # replicated-log stand-in): leader lease + grant journal.
        store = os.path.join(out_dir, "coordinator")
        budgets = [
            FleetBudget("acme", acme_budget_rps, burst_s=burst_s),
            FleetBudget("metered", metered_budget_rps, burst_s=burst_s),
        ]
        replicas = [
            CoordinatorReplica(
                f"replica{i}", store, budgets, lease_ttl_s=lease_ttl_s
            )
            for i in range(2)
        ]
        coordinator = ReplicatedQuotaCoordinator(replicas)

        hosts = [
            LocalHost(f"host{i}", build_service()).start()
            for i in range(2)
        ]
        clients = [
            h.attach_lease_client(coordinator).start() for h in hosts
        ]
        router = FleetRouter(
            [h.base_url for h in hosts], probe_interval_s=0.1,
        ).start()
        # Register the warm hosts BEFORE the watcher's first poll — an
        # empty registry would read as "everyone left".
        for h in hosts:
            reg_client.register(h.host_id, h.base_url)
        agents = [
            HeartbeatAgent(
                reg_client, h.host_id, h.base_url,
                heartbeat_ttl_s=heartbeat_ttl_s,
            ).start()
            for h in hosts
        ]
        aggregator = FleetAggregator(
            {h.host_id: h.base_url for h in hosts},
            fetch=lambda url, timeout_s: {
                "transport": tel.metrics.transport_snapshot()
            },
            stale_drop_s=10.0,
        )
        watcher = MembershipWatcher(
            reg_client, router, aggregator=aggregator, interval_s=0.1,
        ).start()
        cold: dict = {}
        failover: dict = {}
        try:
            # Warm the bucket ladders and let lease shares settle.
            for i in range(8):
                router.score(make_request(i, None, "acme"))
            time.sleep(3 * lease_ttl_s / 2)

            # -- gate 1: coordinator kill under load ----------------------
            def kill_coordinator():
                leader_id = coordinator.leader() or replicas[0].replica_id
                victim = next(
                    r for r in replicas if r.replica_id == leader_id
                )
                failover["victim"] = victim
                t0 = time.monotonic()
                victim.kill()
                deadline = t0 + 5.0 * lease_ttl_s
                while time.monotonic() < deadline:
                    cur = coordinator.leader()
                    if cur is not None and cur != leader_id:
                        break
                    time.sleep(0.02)
                failover["elapsed_s"] = time.monotonic() - t0
                failover["from"] = leader_id
                failover["to"] = coordinator.leader()
                return {
                    "killed": leader_id,
                    "failover_s": round(failover["elapsed_s"], 3),
                    "new_leader": failover["to"],
                }

            def restart_coordinator():
                failover["victim"].restart()
                return True

            q_report = loadgen.run_fleet_scenario(
                router.submit, make_request,
                loadgen.SCENARIOS["coordinator_failover"],
                tenant="metered", base_rate_rps=drill_rate,
                actions={
                    "kill_coordinator": kill_coordinator,
                    "restart_coordinator": restart_coordinator,
                },
                seed=1,
            )
            if q_report.failed:
                failures.append(
                    f"coordinator_failover: {q_report.failed} non-shed "
                    "FAILURES (sheds are the design working; failures "
                    f"are not): {q_report.snapshot()}"
                )
            if failover.get("to") in (None, failover.get("from")):
                failures.append(
                    "coordinator_failover: leadership never moved off "
                    f"the killed replica: {failover.get('from')!r} -> "
                    f"{failover.get('to')!r}"
                )
            elif failover["elapsed_s"] > 1.25 * lease_ttl_s:
                # The bound: leader-lease expiry (ttl/2) + one host
                # renew interval (ttl/2) = one quota lease TTL, plus
                # scheduling slop.
                failures.append(
                    "coordinator_failover: takeover took "
                    f"{failover['elapsed_s']:.2f}s > 1.25 x lease TTL "
                    f"({lease_ttl_s:g}s)"
                )
            burst_total = metered_budget_rps * burst_s
            for pname in ("baseline", "kill", "recover"):
                pr = q_report.phase(pname)
                duration = next(
                    d for n, d, _, _ in q_report.phases if n == pname
                )
                # One lease window of over-admission is legal while the
                # leadership is in flight; exact enforcement before and
                # after.
                window = lease_ttl_s if pname == "kill" else 0.0
                bound = (
                    metered_budget_rps * (duration + window) * 1.15
                    + burst_total + 10
                )
                if pr.completed > bound:
                    failures.append(
                        f"coordinator_failover phase {pname}: admitted "
                        f"{pr.completed} > bound {bound:.0f} (budget "
                        f"{metered_budget_rps:g} rps over {duration:g}s "
                        "+ one lease window) — enforcement leaked past "
                        "the lease contract"
                    )
                if pr.completed < 0.4 * metered_budget_rps * duration:
                    failures.append(
                        f"coordinator_failover phase {pname}: admitted "
                        f"only {pr.completed} — degraded toward zero "
                        "(the contract is never-zero)"
                    )
            if any(lc.stale for lc in clients):
                failures.append(
                    "after failover: lease clients still stale "
                    f"({[lc.stale for lc in clients]}) — renewal never "
                    "reached the new leader"
                )

            # -- gate 2: cold-start join + drain --------------------------
            def join_host():
                client = PublicationClient(
                    pub_server.base_url,
                    cache_dir=os.path.join(out_dir, "cold_cache"),
                )
                local_model, pub = cold_start(client, subscriber_id="host2")
                runtime = ScoringRuntime.load(local_model, rt_cfg)
                host = LocalHost(
                    "host2", ScoringService(runtime, batcher_cfg)
                ).start()
                lease = host.attach_lease_client(coordinator).start()
                agent = HeartbeatAgent(
                    reg_client, "host2", host.base_url,
                    heartbeat_ttl_s=heartbeat_ttl_s,
                ).start()
                cold.update(
                    host=host, lease=lease, agent=agent, seq=pub.seq,
                )
                return {"host": "host2", "snapshot_seq": pub.seq}

            def drain_host():
                return reg_client.drain(hosts[0].host_id)

            j_report = loadgen.run_fleet_scenario(
                router.submit, make_request,
                loadgen.SCENARIOS["host_join_drain"],
                tenant="acme", base_rate_rps=drill_rate,
                actions={
                    "join_host": join_host, "drain_host": drain_host,
                },
                seed=2,
            )
            if j_report.failed:
                failures.append(
                    f"host_join_drain: {j_report.failed} FAILED requests "
                    f"(must be 0): {j_report.snapshot()}"
                )
            if j_report.shed:
                failures.append(
                    f"host_join_drain: {j_report.shed} rejections for "
                    f"the in-quota tenant (must be 0): "
                    f"{j_report.snapshot()}"
                )
            if j_report.completed < drill_rate:  # ~1s of traffic, floor
                failures.append(
                    f"host_join_drain: only {j_report.completed} "
                    "requests completed — the scenario never loaded the "
                    "fleet"
                )
            for key in ("join_host", "drain_host"):
                if str(j_report.actions.get(key)).startswith("ERROR"):
                    failures.append(
                        f"{key} action failed: {j_report.actions[key]}"
                    )

            # Convergence: the cold host routed, the drained host out.
            deadline = time.monotonic() + 10.0
            cold_state = h0_state = None
            while time.monotonic() < deadline:
                hz = {
                    h["url"]: h["state"]
                    for h in router.healthz()["hosts"]
                }
                cold_state = (
                    hz.get(cold["host"].base_url) if "host" in cold
                    else None
                )
                h0_state = hz.get(hosts[0].base_url)
                if cold_state == "healthy" and h0_state == "removed":
                    break
                time.sleep(0.05)
            if cold_state != "healthy":
                failures.append(
                    "host_join_drain: cold host never became a healthy "
                    f"routing target (state {cold_state!r}): "
                    f"{router.healthz()}"
                )
            if h0_state != "removed":
                failures.append(
                    "host_join_drain: drained host never left the "
                    f"rotation (state {h0_state!r}): {router.healthz()}"
                )

            # Bitwise parity: the cold host's scores vs in-process
            # scoring of the SOURCE model (snapshot -> wire -> verify ->
            # load must change nothing).
            if "host" in cold:
                # Untenanted requests: parity judges VALUES, not the
                # cold host's freshly-leased admission budget.
                ref_requests = [workload.request(i) for i in range(16)]
                ref_rt = ScoringRuntime(
                    workload.model, workload.index_maps, rt_cfg
                )
                want = np.asarray(
                    [
                        ref_rt.score_rows([ref_rt.parse_request(r)])[0][0]
                        for r in ref_requests
                    ],
                    np.float32,
                )
                got = np.asarray(
                    [
                        np.float32(
                            cold["host"].service.score(r, timeout=60)[
                                "score"
                            ]
                        )
                        for r in ref_requests
                    ],
                    np.float32,
                )
                if got.tobytes() != want.tobytes():
                    bad = int(np.argmax(got != want))
                    failures.append(
                        "cold host scores are NOT bit-identical to the "
                        f"source model (first diff row {bad}: "
                        f"{got[bad]!r} vs {want[bad]!r})"
                    )

            # The aggregator follows membership: retire host0 fully and
            # watch its series get marked departed (satellite: no
            # forever-sums).
            agents[0].stop(leave=True)
            deadline = time.monotonic() + 5.0
            departed = False
            while time.monotonic() < deadline:
                aggregator.poll_once()
                h0 = aggregator.slo_report()["hosts"].get(
                    hosts[0].host_id
                )
                if h0 is None or h0.get("departed"):
                    departed = True
                    break
                time.sleep(0.05)
            if not departed:
                failures.append(
                    "aggregator never marked the departed host stale — "
                    "its last-seen series would sum forever"
                )

            snap = tel.snapshot()
        finally:
            watcher.stop()
            for a in agents:
                a.stop(leave=True)
            if "agent" in cold:
                cold["agent"].stop(leave=True)
            router.stop()
            for h in hosts:
                h.stop()
            if "host" in cold:
                cold["host"].stop()
            registry.close()
            pub_server.close()
            for r in replicas:
                r.close()
        counters = snap["counters"]
        for name, floor in (
            ("cluster_elections_total", 2),
            ("cluster_failovers_total", 1),
            ("cluster_renewals_total", n_hosts),
            ("cluster_joins_total", n_hosts),
            ("cluster_heartbeats_total", n_hosts),
            ("cluster_drains_total", 1),
            ("cluster_cold_starts_total", 1),
            ("cluster_fetches_total", 1),
            ("cluster_acks_total", 1),
            ("serving_fleet_joins_total", 1),
        ):
            if counters.get(name, 0) < floor:
                failures.append(
                    f"{name} = {counters.get(name, 0)}, expected >= "
                    f"{floor} — the drill left no metric trace"
                )
    if not failures:
        print(
            "cluster selfcheck: coordinator kill failed over "
            f"{failover['from']} -> {failover['to']} in "
            f"{failover['elapsed_s']:.2f}s (bound 1.25 x "
            f"{lease_ttl_s:g}s lease TTL) with {q_report.completed} "
            f"admitted / 0 failed at {drill_rate:g} rps; cold host "
            f"joined from snapshot seq {snap_pub.seq} serving "
            f"bit-identical scores and host0 drained with "
            f"{j_report.completed} completed / 0 failed / 0 shed"
        )
    return failures


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    if args.members:
        from photon_ml_tpu.cluster.membership import RegistryClient

        if not args.registry:
            print("--members needs --registry URL", file=sys.stderr)
            return 2
        members = RegistryClient(args.registry).members()
        print(json.dumps(members, indent=2, sort_keys=True))
        return 0

    if args.serve_registry:
        from photon_ml_tpu.cluster.membership import MembershipRegistry

        registry = MembershipRegistry(
            heartbeat_ttl_s=args.heartbeat_ttl
        )
        registry.serve(host=args.host, port=args.port)
        print(
            f"membership registry on {registry.base_url} "
            "(/register /heartbeat /drain /leave /members /healthz); "
            "Ctrl-C to stop",
            flush=True,
        )
        try:
            import threading

            threading.Event().wait()
        except KeyboardInterrupt:
            print("shutting down")
        finally:
            registry.close()
        return 0

    if args.selfcheck:
        def run(root: str) -> list[str]:
            os.makedirs(root, exist_ok=True)
            return run_cluster_drill(
                root, drill_rate=args.rate, lease_ttl_s=args.lease_ttl
            )

        if args.output_dir:
            failures = run(args.output_dir)
        else:
            with tempfile.TemporaryDirectory(
                prefix="photon_cluster_selfcheck_"
            ) as td:
                failures = run(td)
        if failures:
            print("cluster selfcheck FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
            return 1
        print("cluster selfcheck PASSED")
        return 0

    build_arg_parser().print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
